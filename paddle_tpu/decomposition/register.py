"""Decomposition registry (reference:
/root/reference/python/paddle/decomposition/register.py Registry /
register_decomp; lookup consumed by decomp.py decompose()).

Holds op_name -> rule, where a rule is a jax-traceable function with the
same positional (array) signature as the op's kernel closure plus the
op's attributes as keyword arguments. Rules must compose only whitelisted
jax primitives (see primitives.py; enforced by
tests/test_decomposition.py::test_rules_are_primitive_only).
"""
from __future__ import annotations

import inspect


class Registry:
    """A general registry object."""

    __slots__ = ["name", "rules"]

    def __init__(self, name: str):
        self.name = name
        self.rules = {}

    def register(self, op_type: str, rule):
        assert isinstance(op_type, str)
        assert inspect.isfunction(rule)
        if op_type in self.rules:
            raise ValueError(
                f"decomposition rule for {op_type!r} already registered")
        self.rules[op_type] = rule

    def lookup(self, op_type: str):
        return self.rules.get(op_type)


_decomposition_ops = Registry("decomposition")


def register_decomp(op_type: str):
    """Decorator registering the primitive-lowering rule for ``op_type``."""

    def wrapper(rule):
        _decomposition_ops.register(op_type, rule)
        return rule

    return wrapper


def has_decomp(op_type: str) -> bool:
    return _decomposition_ops.lookup(op_type) is not None


def lookup(op_type: str):
    return _decomposition_ops.lookup(op_type)


# process-global like the reference's prim flag (FLAGS_prim_all): ops
# evaluated on worker threads (DataLoader, serving) must see the toggle
class _PrimState:
    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = False


_prim = _PrimState()


def enable_prim():
    """Route decomposable ops through their primitive rules (eager and
    inside jit traces alike — the swap happens at kernel-call time)."""
    _prim.enabled = True


def disable_prim():
    _prim.enabled = False


def prim_enabled() -> bool:
    return _prim.enabled


class DecompAware:
    """Kernel closure that knows its op name and attributes.

    Decomposable functional ops wrap their kernel fn in this before
    handing it to ``apply()``; under ``enable_prim()`` the call routes to
    the registered primitive rule instead, and ``decompose(program)``
    reads ``.attrs`` off recorded static nodes to rewrite them. This is
    the dispatch-seam analog of the reference's PIR decompose pass
    (/root/reference/python/paddle/decomposition/decomp.py) — no IR walk
    is needed because the kernel fn IS the op body.
    """

    __slots__ = ("op_name", "fn", "attrs")

    def __init__(self, op_name: str, fn, **attrs):
        self.op_name = op_name
        self.fn = fn
        self.attrs = attrs

    def __call__(self, *xs, **kw):
        if _prim.enabled:
            rule = _decomposition_ops.lookup(self.op_name)
            if rule is not None:
                return rule(*xs, **self.attrs)
        return self.fn(*xs, **kw)
