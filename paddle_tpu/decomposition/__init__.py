"""paddle.decomposition — composite-op → primitive-op lowering.

Reference surface: /root/reference/python/paddle/decomposition/
(__init__.py exports decompose + register_decomp; rules.py;
primitives.py; C++ rules in paddle/fluid/primitive/composite/).

TPU-native design: instead of a PIR pass, the registry hangs off the
``apply()`` dispatch seam. Decomposable functional ops wrap their kernel
closure in ``DecompAware`` (op name + attrs); ``enable_prim()`` swaps in
the registered primitive-only rule at kernel-call time — covering eager,
jit traces, and partial capture alike — while ``decompose(program)``
rewrites already-recorded static Programs via the executor node-override
table. Rules lower to a closed whitelist of jax primitives
(primitives.py), asserted by tests/test_decomposition.py.
"""
from . import rules  # noqa: F401  (registers the built-in rules)
from .decomp import decompose
from .primitives import ALLOWED_PRIMITIVES
from .register import (DecompAware, disable_prim, enable_prim, has_decomp,
                       lookup, prim_enabled, register_decomp)

__all__ = ["decompose", "register_decomp", "has_decomp", "lookup",
           "enable_prim", "disable_prim", "prim_enabled", "DecompAware",
           "ALLOWED_PRIMITIVES"]
