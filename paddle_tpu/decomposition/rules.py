"""Composite → primitive decomposition rules (reference:
/root/reference/python/paddle/decomposition/rules.py and
/root/reference/paddle/fluid/primitive/composite/composite.h —
mean/softmax/silu/relu/rsqrt/squeeze/unsqueeze/add_n/layer_norm/
full_like/gelu/sigmoid/leaky_relu/index_select/stack decomps).

Each rule has the same positional (array) signature as the op's kernel
closure, with the op attributes as keyword arguments (captured off the
DecompAware wrapper at the call site). Rules use only whitelisted jax
primitives (primitives.py) — no jax.nn composites, no custom_jvp — so a
backend consuming the decomposed program sees a closed primitive basis.
Numerics are the stable forms (shifted softmax, tanh-form sigmoid), and
normalizations accumulate in f32 like the fused kernels they replace.
"""
from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from .register import register_decomp


@register_decomp("relu")
def relu(x):
    return jnp.maximum(x, jnp.zeros((), x.dtype))


@register_decomp("sigmoid")
def sigmoid(x):
    # tanh form: stable at both tails (exp-form overflows for x << 0)
    half = jnp.asarray(0.5, x.dtype)
    return half * (jnp.tanh(half * x) + jnp.asarray(1.0, x.dtype))


@register_decomp("silu")
def silu(x):
    half = jnp.asarray(0.5, x.dtype)
    return x * (half * (jnp.tanh(half * x) + jnp.asarray(1.0, x.dtype)))


@register_decomp("gelu")
def gelu(x, approximate=False):
    one = jnp.asarray(1.0, x.dtype)
    half = jnp.asarray(0.5, x.dtype)
    if approximate:
        c = jnp.asarray(math.sqrt(2.0 / math.pi), x.dtype)
        k = jnp.asarray(0.044715, x.dtype)
        return half * x * (one + jnp.tanh(c * (x + k * x * x * x)))
    inv_sqrt2 = jnp.asarray(1.0 / math.sqrt(2.0), x.dtype)
    return half * x * (one + lax.erf(x * inv_sqrt2))


@register_decomp("leaky_relu")
def leaky_relu(x, negative_slope=0.01):
    slope = jnp.asarray(negative_slope, x.dtype)
    return jnp.where(x > jnp.zeros((), x.dtype), x, slope * x)


@register_decomp("softmax")
def softmax(x, axis=-1, dtype=None):
    if dtype is not None:
        x = x.astype(dtype)
    shifted = x - lax.stop_gradient(
        jnp.max(x, axis=axis, keepdims=True))
    e = jnp.exp(shifted)
    return e / jnp.sum(e, axis=axis, keepdims=True)


@register_decomp("mean")
def mean(x, axis=None, keepdim=False):
    count = 1
    shape = x.shape
    axes = (tuple(range(len(shape))) if axis is None
            else tuple(a % len(shape) for a in
                       (axis if isinstance(axis, (tuple, list))
                        else (axis,))))
    for a in axes:
        count *= shape[a]
    total = jnp.sum(x, axis=axes, keepdims=keepdim)
    return total / jnp.asarray(count, total.dtype)


@register_decomp("rsqrt")
def rsqrt(x):
    return jnp.asarray(1.0, x.dtype) / jnp.sqrt(x)


@register_decomp("square")
def square(x):
    return x * x


@register_decomp("stack")
def stack(*xs, axis=0):
    nd = xs[0].ndim + 1
    ax = axis % nd
    expanded = [lax.expand_dims(a, (ax,)) for a in xs]
    return lax.concatenate(expanded, ax) if len(expanded) > 1 \
        else expanded[0]


@register_decomp("squeeze")
def squeeze(x, axis=None):
    if axis is None:
        axes = tuple(i for i, d in enumerate(x.shape) if d == 1)
    else:
        raw = axis if isinstance(axis, (tuple, list)) else (axis,)
        axes = tuple(a % x.ndim for a in raw)
        axes = tuple(a for a in axes if x.shape[a] == 1)
    return lax.squeeze(x, axes) if axes else x


@register_decomp("unsqueeze")
def unsqueeze(x, axis=0):
    raw = axis if isinstance(axis, (tuple, list)) else (axis,)
    out = x
    for ax in sorted(int(a) for a in raw):
        out = lax.expand_dims(out, (ax % (out.ndim + 1),))
    return out


@register_decomp("add_n")
def add_n(*xs):
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return out


@register_decomp("index_select")
def index_select(x, index, axis=0):
    return jnp.take(x, index, axis=axis, mode="clip")


@register_decomp("full_like")
def full_like(x, fill_value=0, dtype=None):
    d = dtype if dtype is not None else x.dtype
    return lax.broadcast_in_dim(jnp.asarray(fill_value, d), x.shape, ())


@register_decomp("layer_norm")
def layer_norm(x, *wb, axes=(-1,), epsilon=1e-5):
    acc = x.astype(jnp.float32)
    mu = jnp.mean(acc, axis=axes, keepdims=True)
    centered = acc - mu
    var = jnp.mean(centered * centered, axis=axes, keepdims=True)
    out = (centered / jnp.sqrt(var + jnp.asarray(epsilon, jnp.float32))
           ).astype(x.dtype)
    if len(wb) >= 1:
        out = out * wb[0].astype(x.dtype)
    if len(wb) == 2:
        out = out + wb[1].astype(x.dtype)
    return out


@register_decomp("bn_stats")
def bn_stats(x, axes=()):
    mu_keep = jnp.mean(x, axis=axes, keepdims=True)
    centered = x - mu_keep
    return (lax.squeeze(mu_keep, axes),
            jnp.mean(centered * centered, axis=axes))


@register_decomp("batch_norm")
def batch_norm(x, mean, var, *wb, ch_axis=1, epsilon=1e-5,
               has_w=False, has_b=False):
    shape = [1] * x.ndim
    shape[ch_axis] = -1
    inv = (jnp.asarray(1.0, jnp.float32)
           / jnp.sqrt(var.astype(jnp.float32)
                      + jnp.asarray(epsilon, jnp.float32))).astype(x.dtype)
    out = (x - mean.reshape(shape).astype(x.dtype)) * inv.reshape(shape)
    it = iter(wb)
    if has_w:
        out = out * next(it).reshape(shape).astype(x.dtype)
    if has_b:
        out = out + next(it).reshape(shape).astype(x.dtype)
    return out


@register_decomp("instance_norm")
def instance_norm(x, *wb, axes=(), ch_axis=1, eps=1e-5,
                  has_w=False, has_b=False):
    acc = x.astype(jnp.float32)
    mu = jnp.mean(acc, axis=axes, keepdims=True)
    centered = acc - mu
    var = jnp.mean(centered * centered, axis=axes, keepdims=True)
    out = (centered / jnp.sqrt(var + jnp.asarray(eps, jnp.float32))
           ).astype(x.dtype)
    shape = [1] * x.ndim
    shape[ch_axis] = -1
    it = iter(wb)
    if has_w:
        out = out * next(it).reshape(shape).astype(x.dtype)
    if has_b:
        out = out + next(it).reshape(shape).astype(x.dtype)
    return out


@register_decomp("dropout")
def dropout(x, key, p=0.5, axis=None, mode="upscale_in_train"):
    import jax

    if axis is None:
        shape = x.shape
    else:
        axes = (axis,) if isinstance(axis, int) else tuple(axis)
        shape = tuple(x.shape[i] if i in axes else 1
                      for i in range(x.ndim))
    # jax.random.bernoulli is itself a primitive composition (counter
    # RNG + arithmetic — no custom_jvp), so the rule draws through it
    # and stays bit-exact with the composite under the same key
    keep = jax.random.bernoulli(key, 1.0 - p, shape)
    if mode == "upscale_in_train":
        # divide (not multiply-by-reciprocal): bit-identical to the
        # composite kernel
        return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x)
                         ).astype(x.dtype)
    return jnp.where(keep, x, jnp.zeros_like(x))


@register_decomp("rms_norm")
def rms_norm(x, *w, epsilon=1e-6, axis=-1):
    acc = x.astype(jnp.float32)
    ms = jnp.mean(acc * acc, axis=axis, keepdims=True)
    out = (acc / jnp.sqrt(ms + jnp.asarray(epsilon, jnp.float32))
           ).astype(x.dtype)
    if w and w[0] is not None:
        out = out * w[0].astype(x.dtype)
    return out
