"""decompose() over recorded static Programs (reference:
/root/reference/python/paddle/decomposition/decomp.py — walks a PIR
program, calls each op's registered rule, splices the primitive subgraph
in place).

TPU-native: a static Program node's kernel closure IS the op body, so
decomposition is a node-override swap — no graph surgery. For every node
whose fn is DecompAware with a registered rule, install
``partial(rule, **attrs)`` through the executor's override table
(static/executor.py:88) after an eval_shape equivalence check (the
InferMeta safety net: a rule must preserve output shapes/dtypes exactly).
"""
from __future__ import annotations

import functools
from typing import Iterable, Optional, Sequence

import jax

from .register import DecompAware, lookup

__all__ = ["decompose"]


def _check_avals(node, new_fn):
    """Assert the rule reproduces the node's recorded output avals."""
    from ..framework.core import Tensor
    from ..static.program import Variable

    args = node.args
    sym_pos = [i for i, a in enumerate(args) if isinstance(a, Variable)]
    avals = [args[i].aval for i in sym_pos]

    def abstract(*sym_vals):
        full = list(args)
        for i, v in zip(sym_pos, sym_vals):
            full[i] = v
        full = [a._value if isinstance(a, Tensor) else a for a in full]
        return new_fn(*full, **node.kwargs)

    out = jax.eval_shape(abstract, *avals)
    out_list = list(out) if isinstance(out, (tuple, list)) else [out]
    if len(out_list) != len(node.out_vars):
        raise ValueError(
            f"decomposition rule for {node.op_name!r} returns "
            f"{len(out_list)} outputs, op has {len(node.out_vars)}")
    for av, var in zip(out_list, node.out_vars):
        if tuple(av.shape) != tuple(var.aval.shape) or \
                av.dtype != var.aval.dtype:
            raise ValueError(
                f"decomposition rule for {node.op_name!r} changes output "
                f"{var.name}: {var.aval.shape}/{var.aval.dtype} -> "
                f"{av.shape}/{av.dtype}")


def decompose(program, src_vars: Optional[Sequence] = None,
              blacklist: Iterable[str] = frozenset(),
              whitelist: Optional[Iterable[str]] = None):
    """Rewrite registered composite ops in ``program`` to primitive rules.

    Returns ``src_vars`` unchanged (node overrides keep the same output
    Variables — reference decompose() returns dst_vars because PIR
    splicing re-creates values; here identity is preserved), and records
    the swap in the executor override table. ``blacklist``/``whitelist``
    filter by op name, matching the reference signature
    (python/paddle/decomposition/decomp.py:decompose).
    """
    blacklist = set(blacklist)
    whitelist = set(whitelist) if whitelist is not None else None
    changed = []
    for node in program.nodes:
        fn = node.fn
        if not isinstance(fn, DecompAware):
            continue
        name = fn.op_name
        if name in blacklist or (whitelist is not None
                                 and name not in whitelist):
            continue
        rule = lookup(name)
        if rule is None:
            continue
        new_fn = functools.partial(rule, **fn.attrs)
        _check_avals(node, new_fn)
        program._node_overrides[id(node)] = new_fn
        changed.append(name)
    if changed:
        program.version += 1  # invalidate the executor's compile cache
    program._decomposed_ops = tuple(changed)
    return src_vars
