"""Primitive whitelist for decomposition rules (reference:
/root/reference/python/paddle/decomposition/primitives.py — the flat list
of primitive python ops composite rules may use).

Here the primitive set is jax/lax primitives: a rule's jaxpr must contain
only these (tests/test_decomposition.py traces every rule and asserts
it). Notably EXCLUDED: custom_jvp_call / custom_vjp_call (jax.nn
composites), rsqrt and erf_inv (decompose via sqrt/div), reduce_prod,
and any pjit-wrapped composite — the point of a rule is that a compiler
backend sees only this closed basis.
"""

ALLOWED_PRIMITIVES = frozenset({
    # elementwise arithmetic
    "add", "sub", "mul", "div", "neg", "sign", "abs", "max", "min",
    "rem", "floor", "ceil", "round",
    # transcendental (TPU-native: these map to VPU ops / XLA intrinsics)
    "exp", "log", "log1p", "expm1", "tanh", "erf", "sqrt",
    "integer_pow", "pow", "logistic",
    # comparisons / selection
    "eq", "ne", "lt", "le", "gt", "ge", "select_n", "clamp",
    "is_finite", "and", "or", "not", "xor",
    # type / shape plumbing
    "convert_element_type", "bitcast_convert_type", "stop_gradient",
    "broadcast_in_dim", "reshape", "transpose", "squeeze",
    "expand_dims", "rev", "concatenate", "slice", "dynamic_slice",
    "pad", "iota",
    # reductions
    "reduce_sum", "reduce_max", "reduce_min", "reduce_and", "reduce_or",
    "argmax", "argmin",
    # gather/scatter family (index_select & friends)
    "gather", "scatter", "scatter-add", "dynamic_update_slice",
    # counter-based RNG (dropout): random bits are primitive on every
    # backend — the composition into distributions is what decomposes
    "threefry2x32", "random_wrap", "random_bits",
    "shift_right_logical",
})
