"""paddle.flops parity (/root/reference/python/paddle/hapi/
dynamic_flops.py): FLOPs of a Layer's forward. TPU-native twist: instead
of per-layer-type hand-counted formulas, the forward is traced and the
number comes from XLA's own cost model (compiled.cost_analysis()['flops'])
— exact for whatever the compiler will actually run, fused ops included.
"""
from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["flops"]


def flops(net, input_size: Sequence[int], dtype="float32",
          custom_ops: Optional[dict] = None,
          print_detail: bool = False) -> int:
    """Returns total FLOPs of one forward pass at `input_size` (a shape
    for a single input, or list of shapes for multiple)."""
    from ..framework import dtype as dtypes
    from ..jit import functional_call, _collect

    shapes = input_size if isinstance(input_size[0], (list, tuple)) \
        else [input_size]
    d = dtypes.convert_dtype(dtype)
    params, buffers = _collect(net)
    p_arrays = [p._value for _, p in params]
    b_arrays = [b._value for _, b in buffers]
    was_training = getattr(net, "training", False)
    net.eval()

    def fwd(pa, ba, *inputs):
        out, _ = functional_call(net, pa, ba, inputs)
        return out

    dummies = [jnp.zeros(tuple(s), d) for s in shapes]
    try:
        compiled = jax.jit(fwd).lower(p_arrays, b_arrays,
                                      *dummies).compile()
    finally:
        if was_training:
            net.train()
    costs = compiled.cost_analysis()
    if isinstance(costs, list):  # older jax returns one dict per device
        costs = costs[0]
    total = int(costs.get("flops", 0))

    if print_detail:
        n_params = sum(int(np.prod(a.shape)) for a in p_arrays)
        print(f"Total Flops: {total}     Total Params: {n_params}")
    return total
