"""Terminal progress bar for hapi (reference
/root/reference/python/paddle/hapi/progressbar.py)."""
from __future__ import annotations

import sys
import time


class ProgressBar:
    def __init__(self, num=None, width=30, verbose=1, file=sys.stdout):
        self._num = num
        self._width = width
        self._verbose = verbose
        self.file = file
        self._seen = 0
        self._start = time.time()
        self._last_update = 0.0

    def start(self):
        self._start = time.time()

    def update(self, current_num, values=None):
        self._seen = current_num
        if self._verbose == 0:
            return
        now = time.time()
        vals = " - ".join(
            f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
            for k, v in (values or []))
        if self._num:
            frac = min(current_num / self._num, 1.0)
            filled = int(frac * self._width)
            bar = "=" * filled + ("." * (self._width - filled))
            msg = f"\rstep {current_num}/{self._num} [{bar}] {vals}"
        else:
            msg = f"\rstep {current_num} {vals}"
        # verbose=1: live same-line bar; verbose=2: one line per call
        if self._verbose == 1:
            self.file.write(msg)
            if self._num and current_num >= self._num:
                elapsed = now - self._start
                self.file.write(f" - {elapsed:.1f}s\n")
        else:
            self.file.write(msg.lstrip("\r") + "\n")
        self.file.flush()
        self._last_update = now
