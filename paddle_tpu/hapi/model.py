"""hapi.Model — Keras-style train/eval/predict loop (reference
/root/reference/python/paddle/hapi/model.py:1054 `Model`, fit:1735,
evaluate:1924, predict:2026, train_batch:1245, save:1378, load:1456).

TPU-native redesign: the reference dispatches to DynamicGraphAdapter /
StaticGraphAdapter; here there is a single eager path, with an optional
jit-compiled fused train step (paddle_tpu.jit.TrainStep — fwd+bwd+opt in one
donated XLA program) enabled by ``prepare(..., jit=True)``.
"""
from __future__ import annotations

import os
import pickle
import warnings
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from ..framework.core import Tensor, to_tensor
from ..framework.io import save as _fw_save, load as _fw_load
from ..metric import Metric
from ..nn.layer.layers import Layer
from .callbacks import config_callbacks
from .model_summary import summary as _summary

__all__ = ["Model"]


def _to_list(x):
    if x is None:
        return []
    if isinstance(x, (list, tuple)):
        return list(x)
    return [x]


def _as_tensors(batch):
    out = []
    for b in _to_list(batch):
        out.append(b if isinstance(b, Tensor) else to_tensor(np.asarray(b)))
    return out


class Model:
    """High-level model wrapping a ``Layer`` with train/eval/predict loops."""

    def __init__(self, network: Layer, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics: List[Metric] = []
        self._train_step = None  # jit-fused step when prepare(jit=True)
        self._use_jit_step = False
        self.stop_training = False
        self._save_dir = None

    # ------------------------------------------------------------- prepare
    def prepare(self, optimizer=None, loss=None, metrics=None,
                amp_configs=None, jit: bool = False):
        self._optimizer = optimizer
        if loss is not None and not (isinstance(loss, Layer) or
                                     callable(loss)):
            raise TypeError("loss must be a Layer or a callable")
        self._loss = loss
        self._metrics = _to_list(metrics)
        for m in self._metrics:
            if not isinstance(m, Metric):
                raise TypeError(f"metric {m} is not a paddle_tpu.metric.Metric")
        self._use_jit_step = jit
        self._amp_configs = amp_configs
        return self

    # ------------------------------------------------------- batch methods
    def _compute_loss(self, outputs: List[Tensor], labels: List[Tensor]):
        if self._loss is None:
            raise RuntimeError("call prepare(loss=...) before training")
        return self._loss(*(outputs + labels))

    def train_batch(self, inputs, labels=None, update=True):
        """One optimization step; returns ([loss], [metric values...])."""
        if self._optimizer is None:
            raise RuntimeError("call prepare(optimizer=...) before training")
        self.network.train()
        inputs = _as_tensors(inputs)
        labels = _as_tensors(labels)

        if self._use_jit_step:
            if not update:
                raise NotImplementedError(
                    "gradient accumulation (update=False) is not supported "
                    "with prepare(jit=True); use eager mode or fold "
                    "accumulation into the batch size")
            if self._train_step is None:
                from ..jit import TrainStep
                loss_fn = (lambda out, *lbs:
                           self._loss(*( _to_list(out) + list(lbs))))
                self._train_step = TrainStep(self.network, loss_fn,
                                             self._optimizer)
            loss = self._train_step(inputs[0] if len(inputs) == 1 else inputs,
                                    labels[0] if len(labels) == 1 else labels)
            # fused step returns only the loss; per-batch metric outputs are
            # not materialized (matches reference AMP-O2 fast path behavior)
            return [float(loss)], []

        outputs = _to_list(self.network(*inputs))
        loss = self._compute_loss(outputs, labels)
        loss.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metric_vals = self._update_metrics(outputs, labels)
        return [float(loss)], metric_vals

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        inputs = _as_tensors(inputs)
        labels = _as_tensors(labels)
        from ..framework.core import no_grad
        with no_grad():
            outputs = _to_list(self.network(*inputs))
            losses = []
            if self._loss is not None and labels:
                losses = [float(self._compute_loss(outputs, labels))]
        metric_vals = self._update_metrics(outputs, labels)
        return losses, metric_vals

    def predict_batch(self, inputs):
        self.network.eval()
        inputs = _as_tensors(inputs)
        from ..framework.core import no_grad
        with no_grad():
            outputs = _to_list(self.network(*inputs))
        return [o.numpy() for o in outputs]

    def _update_metrics(self, outputs, labels):
        vals = []
        for m in self._metrics:
            stats = m.compute(*(outputs + labels))
            r = m.update(*_to_list(stats))
            vals.append(r)
        return vals

    # --------------------------------------------------------------- loops
    def _make_loader(self, data, batch_size, shuffle, num_workers, drop_last):
        from ..io import DataLoader, Dataset
        if data is None or hasattr(data, "__iter__") and not isinstance(
                data, Dataset):
            return data  # already a loader/iterable
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          num_workers=num_workers, drop_last=drop_last)

    @staticmethod
    def _split_batch(batch):
        batch = list(batch) if isinstance(batch, (list, tuple)) else [batch]
        if len(batch) == 1:
            return batch, []
        return batch[:-1], batch[-1:]

    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        loader = self._make_loader(train_data, batch_size, shuffle,
                                   num_workers, drop_last)
        eval_loader = self._make_loader(eval_data, batch_size, False,
                                        num_workers, False)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        self._save_dir = save_dir
        metric_names = ["loss"] + [n for m in self._metrics
                                   for n in _to_list(m.name())]
        cbks = config_callbacks(
            callbacks, model=self, epochs=epochs, steps=steps,
            log_freq=log_freq, save_freq=save_freq, save_dir=save_dir,
            verbose=verbose, metrics=metric_names)

        self.stop_training = False
        cbks.on_train_begin()
        global_step = 0
        logs = {}
        for epoch in range(epochs):
            cbks.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, batch in enumerate(loader):
                cbks.on_train_batch_begin(step)
                ins, lbs = self._split_batch(batch)
                update = (step + 1) % accumulate_grad_batches == 0
                losses, metrics = self.train_batch(ins, lbs, update=update)
                logs = {"loss": losses[0], "step": step,
                        "batch_size": (ins[0].shape[0] if ins else None)}
                for m, v in zip(self._metrics, metrics):
                    for n, vv in zip(_to_list(m.name()), _to_list(v)):
                        logs[n] = vv
                cbks.on_train_batch_end(step, logs)
                global_step += 1
                if num_iters is not None and global_step >= num_iters:
                    self.stop_training = True
                if self.stop_training:
                    break
            # epoch-end logs use accumulated metric values
            for m in self._metrics:
                for n, vv in zip(_to_list(m.name()),
                                 _to_list(m.accumulate())):
                    logs[n] = vv
            cbks.on_epoch_end(epoch, logs)
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                self.evaluate(eval_loader, batch_size=batch_size,
                              verbose=verbose, callbacks=cbks,
                              _inner=True)
            if self.stop_training:
                break
        cbks.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_samples=None,
                 _inner=False):
        loader = self._make_loader(eval_data, batch_size, False,
                                   num_workers, False)
        try:
            steps = len(loader)
        except TypeError:
            steps = None
        metric_names = ["loss"] + [n for m in self._metrics
                                   for n in _to_list(m.name())]
        cbks = callbacks if _inner else config_callbacks(
            callbacks, model=self, steps=steps, log_freq=log_freq,
            verbose=verbose, metrics=metric_names, mode="eval")
        for m in self._metrics:
            m.reset()
        cbks.on_eval_begin({"steps": steps, "metrics": metric_names})
        logs = {}
        seen = 0
        loss_sum, loss_cnt = 0.0, 0
        for step, batch in enumerate(loader):
            cbks.on_eval_batch_begin(step)
            ins, lbs = self._split_batch(batch)
            losses, metrics = self.eval_batch(ins, lbs)
            if losses:
                loss_sum += losses[0]
                loss_cnt += 1
                logs["loss"] = losses[0]
            cbks.on_eval_batch_end(step, logs)
            seen += ins[0].shape[0] if ins else 0
            if num_samples is not None and seen >= num_samples:
                break
        result = {}
        if loss_cnt:
            result["loss"] = loss_sum / loss_cnt
        for m in self._metrics:
            for n, vv in zip(_to_list(m.name()), _to_list(m.accumulate())):
                result[n] = vv
        cbks.on_eval_end(result)
        return result

    def predict(self, test_data, batch_size=1, num_workers=0,
                stack_outputs=False, verbose=1, callbacks=None):
        loader = self._make_loader(test_data, batch_size, False,
                                   num_workers, False)
        cbks = config_callbacks(callbacks, model=self, verbose=verbose,
                                metrics=[], mode="predict")
        cbks.on_predict_begin()
        outputs: List[List[np.ndarray]] = []
        for step, batch in enumerate(loader):
            cbks.on_predict_batch_begin(step)
            ins, _ = self._split_batch(batch) if isinstance(
                batch, (list, tuple)) and len(batch) > 1 else (_to_list(batch), [])
            outs = self.predict_batch(ins)
            outputs.append(outs)
            cbks.on_predict_batch_end(step, {"step": step})
        # transpose: list-of-batches-of-outputs -> per-output list
        n_out = len(outputs[0]) if outputs else 0
        result = [[b[i] for b in outputs] for i in range(n_out)]
        if stack_outputs:
            result = [np.concatenate(r, axis=0) for r in result]
        cbks.on_predict_end()
        return result

    # ----------------------------------------------------------- save/load
    def save(self, path: str, training: bool = True):
        """Save `path + '.pdparams'` (+ `.pdopt` when training=True) — same
        file layout as the reference (model.py:1378)."""
        dirname = os.path.dirname(path)
        if dirname:
            os.makedirs(dirname, exist_ok=True)
        _fw_save(self.network.state_dict(), path + ".pdparams")
        if training and self._optimizer is not None:
            _fw_save(self._optimizer.state_dict(), path + ".pdopt")

    def load(self, path: str, skip_mismatch: bool = False,
             reset_optimizer: bool = False):
        param_path = path + ".pdparams" if not path.endswith(".pdparams") \
            else path
        state = _fw_load(param_path)
        if skip_mismatch:
            own = self.network.state_dict()
            state = {k: v for k, v in state.items()
                     if k in own and list(np.shape(v)) == list(own[k].shape)}
        self.network.set_state_dict(state)
        opt_path = path + ".pdopt"
        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(opt_path):
            self._optimizer.set_state_dict(_fw_load(opt_path))
        return self

    # --------------------------------------------------------------- misc
    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        return _summary(self.network, input_size or self._infer_input_size(),
                        dtypes=dtype)

    def _infer_input_size(self):
        if self._inputs is None:
            raise ValueError("summary needs input_size (no inputs spec set)")
        specs = _to_list(self._inputs)
        return [tuple(s.shape) for s in specs]
