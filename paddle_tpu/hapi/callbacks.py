"""hapi callbacks (reference /root/reference/python/paddle/hapi/callbacks.py:
Callback:140, CallbackList:36, ProgBarLogger:253, ModelCheckpoint:550,
LRScheduler:636, EarlyStopping:719, VisualDL:883).

Same hook protocol as the reference; bodies are host-side Python, so nothing
here touches the jit path.
"""
from __future__ import annotations

import numbers
import os
import warnings
from typing import List, Optional

import numpy as np

from .progressbar import ProgressBar

__all__ = [
    "Callback", "CallbackList", "ProgBarLogger", "ModelCheckpoint",
    "LRScheduler", "EarlyStopping", "VisualDL", "config_callbacks",
]


class Callback:
    """Base class: no-op hooks for every train/eval/predict event."""

    def __init__(self):
        self.model = None
        self.params = {}

    def set_params(self, params):
        self.params = params

    def set_model(self, model):
        self.model = model

    def on_train_begin(self, logs=None): pass
    def on_train_end(self, logs=None): pass
    def on_eval_begin(self, logs=None): pass
    def on_eval_end(self, logs=None): pass
    def on_predict_begin(self, logs=None): pass
    def on_predict_end(self, logs=None): pass
    def on_epoch_begin(self, epoch, logs=None): pass
    def on_epoch_end(self, epoch, logs=None): pass
    def on_train_batch_begin(self, step, logs=None): pass
    def on_train_batch_end(self, step, logs=None): pass
    def on_eval_batch_begin(self, step, logs=None): pass
    def on_eval_batch_end(self, step, logs=None): pass
    def on_predict_batch_begin(self, step, logs=None): pass
    def on_predict_batch_end(self, step, logs=None): pass


class CallbackList:
    def __init__(self, callbacks: Optional[List[Callback]] = None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def _call(self, name, *args):
        for c in self.callbacks:
            getattr(c, name)(*args)

    def __getattr__(self, name):
        if name.startswith("on_"):
            return lambda *a: self._call(name, *a)
        raise AttributeError(name)


class ProgBarLogger(Callback):
    """Batch/epoch progress logging (reference callbacks.py:253)."""

    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_train_begin(self, logs=None):
        self.epochs = self.params.get("epochs")
        self.steps = self.params.get("steps")
        self._train_metrics = self.params.get("metrics", [])

    def on_epoch_begin(self, epoch, logs=None):
        if self.verbose and self.epochs:
            print(f"Epoch {epoch + 1}/{self.epochs}")
        self.progbar = ProgressBar(num=self.steps, verbose=self.verbose)
        self.progbar.start()

    def _values(self, logs):
        out = []
        for k in self._train_metrics:
            if k in (logs or {}):
                v = logs[k]
                if isinstance(v, (list, tuple, np.ndarray)):
                    v = float(np.asarray(v).reshape(-1)[0])
                out.append((k, v))
        return out

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and (step + 1) % self.log_freq == 0:
            self.progbar.update(step + 1, self._values(logs))

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            self.progbar.update(self.steps or 0, self._values(logs))

    def on_eval_begin(self, logs=None):
        self._eval_steps = (logs or {}).get("steps")
        self._eval_metrics = (logs or {}).get("metrics", [])
        self.eval_progbar = ProgressBar(num=self._eval_steps,
                                        verbose=self.verbose)
        if self.verbose:
            print("Eval begin...")

    def on_eval_batch_end(self, step, logs=None):
        if self.verbose and (step + 1) % self.log_freq == 0:
            vals = [(k, logs[k]) for k in self._eval_metrics
                    if k in (logs or {})]
            self.eval_progbar.update(step + 1, vals)

    def on_eval_end(self, logs=None):
        if self.verbose:
            vals = [(k, v) for k, v in (logs or {}).items()
                    if isinstance(v, (numbers.Number, list))]
            print("Eval samples done - " + str(vals))


class ModelCheckpoint(Callback):
    """Periodic save of model+optimizer state (reference callbacks.py:550)."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.model is not None and self.save_dir and \
                epoch % self.save_freq == 0:
            path = os.path.join(self.save_dir, str(epoch))
            self.model.save(path)

    def on_train_end(self, logs=None):
        if self.model is not None and self.save_dir:
            self.model.save(os.path.join(self.save_dir, "final"))


class LRScheduler(Callback):
    """Steps the optimizer's LRScheduler (reference callbacks.py:636)."""

    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        if by_step and by_epoch:
            raise ValueError("by_step and by_epoch are mutually exclusive")
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = getattr(self.model, "_optimizer", None)
        lr = getattr(opt, "_learning_rate", None)
        return lr if hasattr(lr, "step") else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s is not None:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s is not None:
                s.step()


class EarlyStopping(Callback):
    """Stop when a monitored metric stops improving (reference
    callbacks.py:719)."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.verbose = verbose
        self.baseline = baseline
        self.min_delta = abs(min_delta)
        self.wait_epoch = 0
        self.best_weights = None
        self.stopped_epoch = 0
        self.save_best_model = save_best_model
        if mode not in ("auto", "min", "max"):
            warnings.warn(f"mode {mode} unknown, fallback to auto")
            mode = "auto"
        if mode == "min" or (mode == "auto" and "acc" not in self.monitor):
            self.monitor_op = np.less
        else:
            self.monitor_op = np.greater
        self.min_delta *= 1 if self.monitor_op == np.greater else -1

    def on_train_begin(self, logs=None):
        self.wait_epoch = 0
        if self.baseline is not None:
            self.best_value = self.baseline
        else:
            self.best_value = np.inf if self.monitor_op == np.less else -np.inf

    def on_epoch_end(self, epoch, logs=None):
        self.stopped_epoch = epoch

    def on_eval_end(self, logs=None):
        if logs is None or self.monitor not in logs:
            warnings.warn(f"Monitor of EarlyStopping should be loss or "
                          f"metric name; {self.monitor} missing.")
            return
        current = logs[self.monitor]
        if isinstance(current, (list, tuple, np.ndarray)):
            current = float(np.asarray(current).reshape(-1)[0])
        if self.monitor_op(current - self.min_delta, self.best_value):
            self.best_value = current
            self.wait_epoch = 0
            if self.save_best_model and self.model is not None and \
                    getattr(self.model, "_save_dir", None):
                self.model.save(os.path.join(self.model._save_dir,
                                             "best_model"))
        else:
            self.wait_epoch += 1
        if self.wait_epoch > self.patience:
            self.model.stop_training = True
            if self.verbose:
                print(f"Epoch {self.stopped_epoch + 1}: early stopping.")


class VisualDL(Callback):
    """Scalar logging (reference callbacks.py:883). The VisualDL package is
    not bundled; falls back to an in-memory record usable in tests."""

    def __init__(self, log_dir):
        super().__init__()
        self.log_dir = log_dir
        self.epochs = None
        self.steps = None
        self.records = []  # (tag, step, value) fallback record

    def _log(self, logs, mode, step):
        for k, v in (logs or {}).items():
            if isinstance(v, (list, tuple, np.ndarray)):
                try:
                    v = float(np.asarray(v).reshape(-1)[0])
                except Exception:
                    continue
            if isinstance(v, numbers.Number):
                self.records.append((f"{mode}/{k}", step, float(v)))

    def on_train_batch_end(self, step, logs=None):
        self._log(logs, "train", step)

    def on_eval_end(self, logs=None):
        self._log(logs, "eval", 0)


def config_callbacks(callbacks=None, model=None, batch_size=None, epochs=None,
                     steps=None, log_freq=2, verbose=2, save_freq=1,
                     save_dir=None, metrics=None, mode="train"):
    cbks = list(callbacks or [])
    if not any(isinstance(c, ProgBarLogger) for c in cbks) and verbose:
        cbks.append(ProgBarLogger(log_freq, verbose=verbose))
    if not any(isinstance(c, LRScheduler) for c in cbks):
        cbks.append(LRScheduler())
    if not any(isinstance(c, ModelCheckpoint) for c in cbks):
        cbks.append(ModelCheckpoint(save_freq, save_dir))
    cbk_list = CallbackList(cbks)
    cbk_list.set_model(model)
    params = {
        "batch_size": batch_size, "epochs": epochs, "steps": steps,
        "verbose": verbose, "metrics": metrics or [],
    }
    cbk_list.set_params(params)
    return cbk_list
