"""Layer summary (reference /root/reference/python/paddle/hapi/
model_summary.py `summary`): walks the layer tree with forward hooks on a
dry-run forward, prints a table, returns {'total_params', 'trainable_params'}.
"""
from __future__ import annotations

from typing import List, Tuple, Union

import numpy as np

from ..framework.core import Tensor, to_tensor
from ..nn.layer.layers import Layer

__all__ = ["summary"]


def _prod(shape):
    out = 1
    for s in shape:
        out *= int(s)
    return out


def summary(net: Layer, input_size=None, dtypes=None, input=None):
    """Print a per-layer table of output shapes and param counts.

    ``input_size``: tuple, list of tuples, or omitted when ``input``
    (example tensors) is given. Batch dim may be -1 (mapped to 1).
    """
    if input is None:
        if input_size is None:
            raise ValueError("summary needs input_size or input")
        sizes = [input_size] if isinstance(input_size, tuple) else \
            list(input_size)
        dts = dtypes if isinstance(dtypes, (list, tuple)) else \
            [dtypes] * len(sizes)
        input = []
        for sz, dt in zip(sizes, dts):
            shape = [1 if d in (-1, None) else int(d) for d in sz]
            arr = np.zeros(shape, dtype=np.dtype(dt or "float32"))
            input.append(to_tensor(arr))
    elif isinstance(input, Tensor):
        input = [input]

    records: List[dict] = []
    hooks = []

    def register(layer: Layer, name: str):
        def hook(l, inputs, outputs):
            out = outputs[0] if isinstance(outputs, (tuple, list)) else outputs
            shape = list(out.shape) if isinstance(out, Tensor) else []
            n_params = sum(_prod(p.shape) for p in
                           l.parameters(include_sublayers=False))
            trainable = sum(
                _prod(p.shape) for p in l.parameters(include_sublayers=False)
                if not getattr(p, "stop_gradient", False))
            records.append({"name": f"{type(l).__name__}-{len(records) + 1}",
                            "output_shape": shape, "params": n_params,
                            "trainable": trainable})
        hooks.append(layer.register_forward_post_hook(hook))

    for name, sub in net.named_sublayers():
        if not sub._sub_layers:  # leaf layers only, like the reference
            register(sub, name)
    if not records and not net._sub_layers:
        register(net, "net")

    was_training = net.training
    net.eval()
    try:
        net(*input)
    finally:
        for h in hooks:
            h.remove()
        if was_training:
            net.train()

    total = sum(_prod(p.shape) for p in net.parameters())
    trainable = sum(_prod(p.shape) for p in net.parameters()
                    if not getattr(p, "stop_gradient", False))

    header = f"{'Layer (type)':<28}{'Output Shape':<24}{'Param #':<12}"
    line = "-" * len(header)
    print(line)
    print(header)
    print("=" * len(header))
    for r in records:
        print(f"{r['name']:<28}{str(r['output_shape']):<24}"
              f"{r['params']:<12,}")
    print("=" * len(header))
    print(f"Total params: {total:,}")
    print(f"Trainable params: {trainable:,}")
    print(f"Non-trainable params: {total - trainable:,}")
    print(line)
    return {"total_params": total, "trainable_params": trainable}
