"""paddle_tpu.hapi — high-level Model API (reference
/root/reference/python/paddle/hapi/)."""
from . import callbacks  # noqa: F401
from .callbacks import (  # noqa: F401
    Callback, CallbackList, EarlyStopping, LRScheduler, ModelCheckpoint,
    ProgBarLogger, VisualDL,
)
from .model import Model  # noqa: F401
from .model_summary import summary  # noqa: F401
from .flops import flops  # noqa: F401

__all__ = ["Model", "summary", "callbacks", "Callback", "CallbackList",
           "ProgBarLogger", "ModelCheckpoint", "LRScheduler",
           "EarlyStopping", "VisualDL"]
