"""DiT — Diffusion Transformer (SD3/DiT family).

Capability parity target: the diffusion-transformer configs the
reference trains (BASELINE.json 'SD3/DiT (conv+attn)'); reference
framework pieces: conv/attention kernels + fused layers (SURVEY.md §2.1
fused kernels). Architecture per the public DiT recipe: patchify conv →
N transformer blocks with adaLN-Zero timestep/label conditioning →
linear unpatchify predicting noise (and optionally sigma).

TPU notes: patchify is a stride-p conv (MXU-tiled by XLA); adaLN
modulation is elementwise and fuses into the surrounding matmuls; all
attention rides the same flash path as the LLMs.
"""
from __future__ import annotations

import math
from dataclasses import dataclass

import jax.numpy as jnp

from ..framework.core import apply
from .. import nn
from ..nn import functional as F

__all__ = ["DiTConfig", "DiT", "dit_tiny", "dit_s_2", "dit_xl_2"]


@dataclass
class DiTConfig:
    input_size: int = 32           # latent spatial size
    patch_size: int = 2
    in_channels: int = 4
    hidden_size: int = 1152
    depth: int = 28
    num_heads: int = 16
    mlp_ratio: float = 4.0
    num_classes: int = 1000
    class_dropout_prob: float = 0.1
    learn_sigma: bool = True
    dtype: str = "float32"
    use_recompute: bool = False


class TimestepEmbedder(nn.Layer):
    """Sinusoidal frequencies → 2-layer MLP."""

    def __init__(self, hidden_size, freq_dim=256, dtype="float32"):
        super().__init__(dtype=dtype)
        self.freq_dim = freq_dim
        self.mlp = nn.Sequential(
            nn.Linear(freq_dim, hidden_size), nn.Silu(),
            nn.Linear(hidden_size, hidden_size))

    def forward(self, t):
        half = self.freq_dim // 2

        def embed(ta):
            freqs = jnp.exp(-math.log(10000.0)
                            * jnp.arange(half, dtype=jnp.float32) / half)
            args = ta.astype(jnp.float32)[:, None] * freqs[None, :]
            return jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
        emb = apply("timestep_embed", embed, t)
        return self.mlp(emb)


class LabelEmbedder(nn.Layer):
    """Class-label embedding with CFG dropout (extra 'null' class)."""

    def __init__(self, num_classes, hidden_size, dropout_prob,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.num_classes = num_classes
        self.dropout_prob = dropout_prob
        self.embedding_table = nn.Embedding(num_classes + 1, hidden_size)

    def forward(self, labels):
        if self.training and self.dropout_prob > 0:
            from ..framework.core import default_generator
            import jax

            def drop(la):
                key = default_generator.next_key()
                keep = jax.random.uniform(key, la.shape) >= \
                    self.dropout_prob
                return jnp.where(keep, la, self.num_classes)
            labels = apply("cfg_drop", drop, labels)
        return self.embedding_table(labels)


def _modulate(x, shift, scale):
    return x * (1 + scale.unsqueeze(1)) + shift.unsqueeze(1)


class DiTBlock(nn.Layer):
    """Transformer block with adaLN-Zero conditioning."""

    def __init__(self, hidden_size, num_heads, mlp_ratio=4.0,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.num_heads = num_heads
        self.head_dim = hidden_size // num_heads
        self.norm1 = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                  weight_attr=False, bias_attr=False)
        self.qkv = nn.Linear(hidden_size, 3 * hidden_size)
        self.proj = nn.Linear(hidden_size, hidden_size)
        self.norm2 = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                  weight_attr=False, bias_attr=False)
        mlp_hidden = int(hidden_size * mlp_ratio)
        self.mlp = nn.Sequential(
            nn.Linear(hidden_size, mlp_hidden), nn.GELU(approximate=True),
            nn.Linear(mlp_hidden, hidden_size))
        # adaLN-Zero: 6 modulation vectors; final proj initialized to 0 so
        # each block starts as identity
        self.adaLN_modulation = nn.Sequential(
            nn.Silu(), nn.Linear(hidden_size, 6 * hidden_size))
        last = self.adaLN_modulation[1]
        last.weight.set_value(jnp.zeros_like(last.weight._value))
        last.bias.set_value(jnp.zeros_like(last.bias._value))

    def forward(self, x, c):
        mod = self.adaLN_modulation(c)
        (shift_msa, scale_msa, gate_msa, shift_mlp, scale_mlp,
         gate_mlp) = mod.chunk(6, axis=-1)
        b, s = x.shape[0], x.shape[1]
        h = _modulate(self.norm1(x), shift_msa, scale_msa)
        qkv = self.qkv(h).reshape([b, s, 3, self.num_heads, self.head_dim])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        attn = F.scaled_dot_product_attention(q, k, v)
        attn = self.proj(attn.reshape([b, s, -1]))
        x = x + gate_msa.unsqueeze(1) * attn
        h = _modulate(self.norm2(x), shift_mlp, scale_mlp)
        return x + gate_mlp.unsqueeze(1) * self.mlp(h)


class FinalLayer(nn.Layer):
    def __init__(self, hidden_size, patch_size, out_channels,
                 dtype="float32"):
        super().__init__(dtype=dtype)
        self.norm_final = nn.LayerNorm(hidden_size, epsilon=1e-6,
                                       weight_attr=False, bias_attr=False)
        self.linear = nn.Linear(hidden_size,
                                patch_size * patch_size * out_channels)
        self.linear.weight.set_value(
            jnp.zeros_like(self.linear.weight._value))
        self.linear.bias.set_value(jnp.zeros_like(self.linear.bias._value))
        self.adaLN_modulation = nn.Sequential(
            nn.Silu(), nn.Linear(hidden_size, 2 * hidden_size))

    def forward(self, x, c):
        shift, scale = self.adaLN_modulation(c).chunk(2, axis=-1)
        return self.linear(_modulate(self.norm_final(x), shift, scale))


class DiT(nn.Layer):
    def __init__(self, cfg: DiTConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        self.out_channels = cfg.in_channels * (2 if cfg.learn_sigma else 1)
        self.x_embedder = nn.Conv2D(cfg.in_channels, cfg.hidden_size,
                                    cfg.patch_size, stride=cfg.patch_size)
        self.t_embedder = TimestepEmbedder(cfg.hidden_size,
                                           dtype=cfg.dtype)
        self.y_embedder = LabelEmbedder(cfg.num_classes, cfg.hidden_size,
                                        cfg.class_dropout_prob, cfg.dtype)
        n_patches = (cfg.input_size // cfg.patch_size) ** 2
        import jax
        from ..framework.core import default_generator, Parameter
        self.pos_embed = Parameter(
            0.02 * jax.random.normal(default_generator.next_key(),
                                     (1, n_patches, cfg.hidden_size),
                                     jnp.float32))
        self.blocks = nn.LayerList([
            DiTBlock(cfg.hidden_size, cfg.num_heads, cfg.mlp_ratio,
                     cfg.dtype) for _ in range(cfg.depth)])
        self.final_layer = FinalLayer(cfg.hidden_size, cfg.patch_size,
                                      self.out_channels, cfg.dtype)

    def unpatchify(self, x):
        c, p = self.out_channels, self.cfg.patch_size
        hw = int(math.isqrt(x.shape[1]))

        def f(xa):
            b = xa.shape[0]
            xa = xa.reshape(b, hw, hw, p, p, c)
            xa = jnp.einsum("bhwpqc->bchpwq", xa)
            return xa.reshape(b, c, hw * p, hw * p)
        return apply("unpatchify", f, x)

    def forward(self, x, t, y):
        """x: [B, C, H, W] noisy latents; t: [B] timesteps; y: [B]
        labels. Returns predicted noise [B, out_C, H, W]."""
        h = self.x_embedder(x)  # [B, hidden, H/p, W/p]
        b = h.shape[0]
        h = h.flatten(2).transpose([0, 2, 1])  # [B, N, hidden]
        h = h + self.pos_embed
        c = self.t_embedder(t) + self.y_embedder(y)
        if self.cfg.dtype != "float32":
            # pos_embed/embedders are f32 masters; narrow activations so
            # the block stack actually runs at the configured precision
            h = h.astype(self.cfg.dtype)
            c = c.astype(self.cfg.dtype)
        for block in self.blocks:
            if self.cfg.use_recompute:
                from ..distributed.fleet import recompute
                h = recompute(_BlockFn(block), h, c)
            else:
                h = block(h, c)
        h = self.final_layer(h, c)
        return self.unpatchify(h)

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


class _BlockFn:
    def __init__(self, block):
        self.block = block

    def parameters(self):
        return self.block.parameters()

    def __call__(self, x, c):
        return self.block(x, c)


def dit_tiny(**kw) -> DiTConfig:
    return DiTConfig(input_size=8, patch_size=2, in_channels=4,
                     hidden_size=64, depth=2, num_heads=4, num_classes=10,
                     **kw)


def dit_s_2(**kw) -> DiTConfig:
    return DiTConfig(patch_size=2, hidden_size=384, depth=12, num_heads=6,
                     **kw)


def dit_xl_2(**kw) -> DiTConfig:
    return DiTConfig(patch_size=2, hidden_size=1152, depth=28,
                     num_heads=16, **kw)
