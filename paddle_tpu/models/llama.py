"""Llama-family decoder-only transformer — the framework's flagship model.

Capability parity target: PaddleNLP's Llama stack trained with Fleet 4D
parallel (reference framework side: fleet hybrid topology
/root/reference/python/paddle/distributed/fleet/base/topology.py:174, TP
layers /root/reference/python/paddle/distributed/fleet/layers/mpu/
mp_layers.py, fused rope/rms incubate ops).

TPU-native design:
- RMSNorm + rotary + GQA attention via ops.flash_attention (Pallas on TPU)
- SwiGLU MLP
- tensor parallel via Column/RowParallelLinear + VocabParallelEmbedding
  when a fleet mesh with mp_degree > 1 is active
- FSDP/dp are placement recipes applied by fleet.distributed_model
- bf16 weights with f32 master copies in the optimizer (multi_precision)
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Optional

import jax.numpy as jnp

from ..framework.core import Tensor, apply
from .. import nn
from ..nn import functional as F
from ..ops.rope import build_rope_cache, rope_reference

__all__ = ["LlamaConfig", "LlamaForCausalLM", "LlamaModel", "llama_tiny",
           "llama_small", "llama_mid", "llama_1b", "llama_3_8b"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    dtype: str = "float32"
    use_recompute: bool = False
    # reference recompute_granularity (PaddleNLP llama configs):
    # "full"      — whole block rematerialized (max memory savings)
    # "full_attn" — only the attention sublayer (ln1 + attn)
    #               rematerialized; MLP activations stored. The middle
    #               ground that keeps most of the no-remat MFU
    # "core_attn" — only the attention inner (scores/softmax/context)
    #               recomputed. With the Pallas flash kernel this is the
    #               plain forward: flash backward already recomputes
    #               from q/k/v instead of storing probabilities
    recompute_granularity: str = "full"
    # parallelism knobs (consumed when a fleet mesh is active)
    tensor_parallel: bool = False
    sequence_parallel: bool = False
    # context parallelism (reference hybrid_configs sep_degree,
    # fleet/base/topology.py:497 + meta_parallel/segment_parallel.py):
    # >1 = training attention runs zigzag ring attention over the
    # fleet mesh's 'sep' axis (must match its size); the sequence dim
    # of q/k/v shards across the ring, KV blocks rotate over ICI
    sep_degree: int = 1
    # >0: forward() returns hidden states and loss() computes the head
    # matmul + cross entropy in chunks of this many tokens under
    # jax.checkpoint (training-memory config; generate() still works —
    # the cached decode path keeps the normal head)
    chunked_ce_tokens: int = 0


def _mp_active() -> bool:
    from ..distributed.fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    return hcg is not None and hcg.get_model_parallel_world_size() > 1


def _sep_mesh(sep_degree: int):
    """The fleet mesh, when CP is requested and the mesh has a 'sep'
    axis of the configured size (loud on mismatch)."""
    if sep_degree <= 1:
        return None
    from ..distributed.fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    if hcg is None:
        return None                      # single-device runs/tests
    mesh = hcg.mesh
    if "sep" not in mesh.dim_names or \
            mesh.get_dim_size("sep") != sep_degree:
        raise ValueError(
            f"sep_degree={sep_degree} needs a fleet mesh with a 'sep' "
            f"axis of that size; got {mesh.dim_names} "
            f"{[mesh.get_dim_size(a) for a in mesh.dim_names]} — set "
            "hybrid_configs sep_degree")
    return mesh


class LlamaMLP(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__(dtype=cfg.dtype)
        if cfg.tensor_parallel and _mp_active():
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)
            self.gate_proj = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size, has_bias=False,
                gather_output=False)
            self.up_proj = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size, has_bias=False,
                gather_output=False)
            self.down_proj = RowParallelLinear(
                cfg.intermediate_size, cfg.hidden_size, has_bias=False,
                input_is_parallel=True)
        else:
            self.gate_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                                       bias_attr=False)
            self.up_proj = nn.Linear(cfg.hidden_size, cfg.intermediate_size,
                                     bias_attr=False)
            self.down_proj = nn.Linear(cfg.intermediate_size, cfg.hidden_size,
                                       bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class LlamaAttention(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__(dtype=cfg.dtype)
        self.num_heads = cfg.num_attention_heads
        self.num_kv_heads = cfg.num_key_value_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.hidden_size = cfg.hidden_size
        q_out = cfg.hidden_size
        kv_out = self.num_kv_heads * self.head_dim
        self._tp = cfg.tensor_parallel and _mp_active()
        if self._tp:
            # heads shard over mp: q/k/v stay feature-sharded
            # (gather_output=False), attention runs on the local heads, and
            # o_proj's row-parallel matmul reduces — matching the
            # reference's mp_layers head partitioning.
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)
            from ..distributed.fleet.mpu import _get_mesh
            mesh = _get_mesh()
            mp = mesh.get_dim_size("mp")
            if self.num_kv_heads % mp or self.num_heads % mp:
                raise ValueError(
                    f"num_heads {self.num_heads} / num_kv_heads "
                    f"{self.num_kv_heads} must divide mp degree {mp}")
            self.q_proj = ColumnParallelLinear(cfg.hidden_size, q_out,
                                               has_bias=False,
                                               gather_output=False)
            self.k_proj = ColumnParallelLinear(cfg.hidden_size, kv_out,
                                               has_bias=False,
                                               gather_output=False)
            self.v_proj = ColumnParallelLinear(cfg.hidden_size, kv_out,
                                               has_bias=False,
                                               gather_output=False)
            self.o_proj = RowParallelLinear(q_out, cfg.hidden_size,
                                            has_bias=False,
                                            input_is_parallel=True)
        else:
            self.q_proj = nn.Linear(cfg.hidden_size, q_out, bias_attr=False)
            self.k_proj = nn.Linear(cfg.hidden_size, kv_out, bias_attr=False)
            self.v_proj = nn.Linear(cfg.hidden_size, kv_out, bias_attr=False)
            self.o_proj = nn.Linear(q_out, cfg.hidden_size, bias_attr=False)
        self.rope_theta = cfg.rope_theta
        self.sep_degree = cfg.sep_degree

    def forward(self, x, rope_cos=None, rope_sin=None, past_kv=None,
                pos=None):
        """past_kv: optional (k_cache, v_cache) Tensors of fixed shape
        [b, max_len, kv_heads, head_dim]; pos: scalar Tensor — number of
        tokens already cached. With a cache, returns (out, new_kv) and
        attends this chunk's queries over cache[:pos]+chunk (the decode
        path; shapes stay static so ONE compiled program serves every
        step)."""
        b, s = x.shape[0], x.shape[1]
        q = self.q_proj(x).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(x).reshape([b, s, self.num_kv_heads, self.head_dim])
        if self._tp:
            # keep the head dim sharded over mp through the reshape
            from ..distributed.fleet.mpu import _constrain, _get_mesh
            mesh = _get_mesh()
            head_spec = [None, None, "mp", None]
            q = _constrain(q, mesh, head_spec)
            k = _constrain(k, mesh, head_spec)
            v = _constrain(v, mesh, head_spec)

        # rotary embedding (fused-rope parity) applied inside one taped
        # op; with a cache the table is sliced at the running offset
        if past_kv is None:
            def rope_fn(qa, ka):
                cos, sin = build_rope_cache(s, self.head_dim,
                                            self.rope_theta, jnp.float32)
                qo = rope_reference(qa, cos.astype(qa.dtype),
                                    sin.astype(qa.dtype))
                ko = rope_reference(ka, cos.astype(ka.dtype),
                                    sin.astype(ka.dtype))
                return qo, ko
            q, k = apply("fused_rope", rope_fn, q, k)
            sep_mesh = _sep_mesh(self.sep_degree)
            if sep_mesh is not None:
                # context parallelism: zigzag ring attention over the
                # 'sep' axis (sequence sharded, KV rotates the ring);
                # dp/mp compose as GSPMD auto axes around it
                from ..distributed.ring_attention import ring_attention
                out = apply(
                    "ring_attention",
                    lambda qa, ka, va: ring_attention(
                        qa, ka, va, sep_mesh, axis="sep", causal=True),
                    q, k, v)
            else:
                out = F.scaled_dot_product_attention(q, k, v,
                                                     is_causal=True)
            out = out.reshape([b, s, self.num_heads * self.head_dim])
            if self._tp:
                from ..distributed.fleet.mpu import _constrain, _get_mesh
                out = _constrain(out, _get_mesh(), [None, None, "mp"])
            return self.o_proj(out)

        past_k, past_v = past_kv
        max_len = past_k.shape[1]

        def cached_attn(qa, ka, va, pk, pv, p):
            import jax
            cos_f, sin_f = build_rope_cache(max_len, self.head_dim,
                                            self.rope_theta, jnp.float32)
            # cache layout [1, max_len, 1, d] → slice the seq axis
            cos = jax.lax.dynamic_slice_in_dim(cos_f, p, s, axis=1)
            sin = jax.lax.dynamic_slice_in_dim(sin_f, p, s, axis=1)
            qa = rope_reference(qa, cos.astype(qa.dtype),
                                sin.astype(qa.dtype))
            ka = rope_reference(ka, cos.astype(ka.dtype),
                                sin.astype(ka.dtype))
            nk = jax.lax.dynamic_update_slice_in_dim(pk, ka, p, axis=1)
            nv = jax.lax.dynamic_update_slice_in_dim(pv, va, p, axis=1)
            # GQA attention of the s new queries over nk[:, :p+s]
            group = self.num_heads // self.num_kv_heads
            qg = qa.reshape(b, s, self.num_kv_heads, group, self.head_dim)
            scores = jnp.einsum("bqkgd,bskd->bkgqs",
                                qg.astype(jnp.float32),
                                nk.astype(jnp.float32))
            scores = scores / jnp.sqrt(float(self.head_dim))
            kpos = jnp.arange(max_len)[None, None, None, None, :]
            qpos = p + jnp.arange(s)[None, None, None, :, None]
            scores = jnp.where(kpos <= qpos, scores, -1e30)
            probs = jax.nn.softmax(scores, axis=-1)
            og = jnp.einsum("bkgqs,bskd->bqkgd", probs,
                            nv.astype(jnp.float32))
            o = og.reshape(b, s, self.num_heads * self.head_dim)
            return o.astype(qa.dtype), nk, nv

        out, new_k, new_v = apply("cached_attention", cached_attn,
                                  q, k, v, past_k, past_v, pos)
        return self.o_proj(out), (new_k, new_v)


class LlamaDecoderLayer(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__(dtype=cfg.dtype)
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps,
                                          dtype=cfg.dtype)
        self.self_attn = LlamaAttention(cfg)
        self.post_attention_layernorm = nn.RMSNorm(cfg.hidden_size,
                                                   cfg.rms_norm_eps,
                                                   dtype=cfg.dtype)
        self.mlp = LlamaMLP(cfg)
        self.use_recompute = cfg.use_recompute
        self.recompute_granularity = cfg.recompute_granularity

    def _block(self, x):
        h = x + self.self_attn(self.input_layernorm(x))
        return h + self.mlp(self.post_attention_layernorm(h))

    def forward(self, x, past_kv=None, pos=None):
        if past_kv is not None:
            attn, new_kv = self.self_attn(self.input_layernorm(x),
                                          past_kv=past_kv, pos=pos)
            h = x + attn
            return h + self.mlp(self.post_attention_layernorm(h)), new_kv
        if self.use_recompute:
            from ..distributed.fleet import recompute
            gran = self.recompute_granularity
            if gran == "full":
                return recompute(_LayerFn(self), x)
            if gran == "full_attn":
                h = x + recompute(_AttnFn(self), x)
                return h + self.mlp(self.post_attention_layernorm(h))
            if gran == "core_attn":
                # flash backward recomputes scores/probs from q/k/v by
                # construction — the plain forward IS core_attn remat
                return self._block(x)
            raise ValueError(
                f"unknown recompute_granularity {gran!r}; expected "
                "'full', 'full_attn' or 'core_attn'")
        return self._block(x)


class _LayerFn:
    """Adapter giving recompute() access to the layer's parameters."""

    def __init__(self, layer):
        self.layer = layer

    def parameters(self):
        return self.layer.parameters()

    def __call__(self, x):
        return self.layer._block(x)


class _AttnFn:
    """recompute_granularity='full_attn': the rematerialized region is
    ln1 + attention (the residual add and MLP stay stored)."""

    def __init__(self, layer):
        self.layer = layer

    def parameters(self):
        return (list(self.layer.input_layernorm.parameters())
                + list(self.layer.self_attn.parameters()))

    def __call__(self, x):
        return self.layer.self_attn(self.layer.input_layernorm(x))


class LlamaModel(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        if cfg.tensor_parallel and _mp_active():
            from ..distributed.fleet import VocabParallelEmbedding
            self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                       cfg.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = nn.LayerList(
            [LlamaDecoderLayer(cfg) for _ in range(cfg.num_hidden_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps,
                               dtype=cfg.dtype)

    def forward(self, input_ids, caches=None, pos=None):
        h = self.embed_tokens(input_ids)
        if self.cfg.dtype != "float32":
            h = h.astype(self.cfg.dtype)
        if caches is not None:
            new_caches = []
            for layer, kv in zip(self.layers, caches):
                h, nkv = layer(h, past_kv=kv, pos=pos)
                new_caches.append(nkv)
            return self.norm(h), new_caches
        for layer in self.layers:
            h = layer(h)
        return self.norm(h)


class LlamaForCausalLM(nn.Layer):
    def __init__(self, cfg: LlamaConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        self.model = LlamaModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        elif cfg.tensor_parallel and _mp_active():
            from ..distributed.fleet import ColumnParallelLinear
            self.lm_head = ColumnParallelLinear(
                cfg.hidden_size, cfg.vocab_size, has_bias=False,
                gather_output=True)
        else:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids, caches=None, pos=None):
        if caches is not None:
            h, new_caches = self.model(input_ids, caches=caches, pos=pos)
        else:
            h = self.model(input_ids)
        if self.cfg.chunked_ce_tokens and caches is None:
            # chunked-CE training config: loss() owns the head matmul
            return h
        if self.lm_head is None:
            from ..tensor.linalg import matmul
            logits = matmul(h, self.model.embed_tokens.weight,
                            transpose_y=True)
        else:
            logits = self.lm_head(h)
        if caches is not None:
            return logits, new_caches
        return logits

    def loss(self, logits, labels):
        """Shifted causal-LM cross entropy. With
        cfg.chunked_ce_tokens > 0, forward() returns HIDDEN states and
        this computes the head matmul + CE in sequence chunks under
        jax.checkpoint — the [B, S, V] logits (1 GB at b4 s2048 v32k
        f32, the single biggest activation) are never materialized; the
        backward rematerializes one chunk's logits at a time."""
        if self.cfg.chunked_ce_tokens:
            return self._chunked_loss(logits, labels)
        from ..tensor.manipulation import reshape
        v = logits.shape[-1]
        shift_logits = logits[:, :-1, :].reshape([-1, v])
        shift_labels = labels[:, 1:].reshape([-1])
        return F.cross_entropy(shift_logits, shift_labels)

    def _chunked_loss(self, hidden, labels):
        from ..nn.functional.loss import chunked_causal_lm_loss
        return chunked_causal_lm_loss(
            hidden, labels,
            None if self.lm_head is None else self.lm_head.weight,
            self.model.embed_tokens.weight,
            int(self.cfg.chunked_ce_tokens))

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def generate(self, input_ids, max_new_tokens: int = 32,
                 max_length: Optional[int] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 eos_token_id: Optional[int] = None, seed: int = 0,
                 top_p: float = 1.0, repetition_penalty: float = 1.0,
                 num_beams: int = 1, length_penalty: float = 0.0):
        """KV-cached autoregressive generation (the serving decode loop —
        reference analog: the generation path over
        block_multihead_attention). Prefill compiles once, the
        single-token decode step compiles once (static cache shapes,
        traced position), then every step is a fast replay.
        num_beams > 1 switches to deterministic beam search (per-beam
        GNMT length penalty, eos early-stop) — sampling knobs don't
        combine with it and are rejected. (New kwargs append after the
        r2 signature so positional callers keep their meaning.)
        """
        if num_beams > 1:
            if temperature > 0 or top_k > 0 or top_p < 1.0 \
                    or repetition_penalty != 1.0:
                raise ValueError(
                    "num_beams > 1 is deterministic beam search; "
                    "temperature/top_k/top_p/repetition_penalty do not "
                    "apply — drop them or use num_beams=1 sampling")
            from .generation import beam_search as _beam
            return _beam(self, input_ids, num_beams=num_beams,
                         max_new_tokens=max_new_tokens,
                         length_penalty=length_penalty,
                         eos_token_id=eos_token_id,
                         max_length=max_length)
        from .generation import generate as _generate
        return _generate(self, input_ids, max_new_tokens=max_new_tokens,
                         max_length=max_length, temperature=temperature,
                         top_k=top_k, top_p=top_p,
                         repetition_penalty=repetition_penalty,
                         eos_token_id=eos_token_id, seed=seed)


def llama_tiny(**kw) -> LlamaConfig:
    base = dict(vocab_size=512, hidden_size=128, intermediate_size=352,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=2, max_position_embeddings=256)
    base.update(kw)          # callers may override any default
    return LlamaConfig(**base)


def llama_small(**kw) -> LlamaConfig:
    """~0.5B bench config sized for a single v5e chip."""
    base = dict(vocab_size=32000, hidden_size=2048,
                intermediate_size=5632, num_hidden_layers=8,
                num_attention_heads=16, num_key_value_heads=8,
                max_position_embeddings=2048)
    base.update(kw)
    return LlamaConfig(**base)


def llama_1b(**kw) -> LlamaConfig:
    """~1.0B largest-fitting config for one 16GB v5e chip: llama_mid's
    MXU-efficient width at 18 layers; trains with remat + chunked CE
    (BASELINE.md protocol: record the largest fit, not just the sweet
    spot)."""
    base = dict(vocab_size=32000, hidden_size=2048,
                intermediate_size=5632, num_hidden_layers=18,
                num_attention_heads=16, num_key_value_heads=8,
                max_position_embeddings=4096)
    base.update(kw)
    return LlamaConfig(**base)


def llama_mid(**kw) -> LlamaConfig:
    """~0.65B bench config — the largest AdamW(multi_precision) +
    activations footprint that keeps >=70% MFU on one 16GB v5e chip
    (BASELINE.md step toward the Llama-3-8B north star). Width matches
    llama_small (MXU-efficient 2048x5632 matmuls); measured sweep: this
    shape at batch 4, seq 2048 gives 70.3% MFU vs 62.4% for a
    narrow-deep 24-layer 717M variant."""
    base = dict(vocab_size=32000, hidden_size=2048,
                intermediate_size=5632, num_hidden_layers=11,
                num_attention_heads=16, num_key_value_heads=8,
                max_position_embeddings=2048)
    base.update(kw)
    return LlamaConfig(**base)


def llama_3_8b(**kw) -> LlamaConfig:
    base = dict(vocab_size=128256, hidden_size=4096,
                intermediate_size=14336, num_hidden_layers=32,
                num_attention_heads=32, num_key_value_heads=8,
                max_position_embeddings=8192, rope_theta=500000.0)
    base.update(kw)
    return LlamaConfig(**base)
