"""GPT / ERNIE-style decoder-only transformer.

Capability parity target: the ERNIE/GPT stacks trained on the reference
framework (PaddleNLP GPT-3 / ERNIE 4.5 recipes; framework side:
fleet hybrid parallel + fused attention ops per SURVEY.md §2.3). Differs
from the Llama family: learned absolute position embeddings, pre-LN
LayerNorm (not RMSNorm), GELU MLP with biases, no rotary.

Follows the same TP wiring as models/llama.py: Column/RowParallelLinear
and VocabParallelEmbedding activate when a fleet mesh with mp>1 is live.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..framework.core import apply
from .. import nn
from ..nn import functional as F

__all__ = ["GPTConfig", "GPTModel", "GPTForCausalLM", "gpt_tiny",
           "gpt_345m", "ernie_45_dense_3b"]


@dataclass
class GPTConfig:
    vocab_size: int = 50304
    hidden_size: int = 1024
    intermediate_size: int = 4096
    num_hidden_layers: int = 24
    num_attention_heads: int = 16
    max_position_embeddings: int = 1024
    layer_norm_epsilon: float = 1e-5
    attention_dropout: float = 0.0
    hidden_dropout: float = 0.0
    tie_word_embeddings: bool = True
    dtype: str = "float32"
    use_recompute: bool = False
    # 'full' | 'full_attn' | 'core_attn' (see LlamaConfig)
    recompute_granularity: str = "full"
    tensor_parallel: bool = False
    # >0: forward() returns hidden states; loss() runs the chunked
    # head-matmul + CE (see nn.functional.chunked_softmax_cross_entropy)
    chunked_ce_tokens: int = 0


def _mp_active() -> bool:
    from ..distributed.fleet import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    return hcg is not None and hcg.get_model_parallel_world_size() > 1


class GPTAttention(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__(dtype=cfg.dtype)
        self.num_heads = cfg.num_attention_heads
        self.head_dim = cfg.hidden_size // cfg.num_attention_heads
        self.dropout = cfg.attention_dropout
        self._tp = cfg.tensor_parallel and _mp_active()
        if self._tp:
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)
            self.qkv_proj = ColumnParallelLinear(
                cfg.hidden_size, 3 * cfg.hidden_size, has_bias=True,
                gather_output=False)
            self.out_proj = RowParallelLinear(
                cfg.hidden_size, cfg.hidden_size, has_bias=True,
                input_is_parallel=True)
        else:
            self.qkv_proj = nn.Linear(cfg.hidden_size, 3 * cfg.hidden_size)
            self.out_proj = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, x):
        b, s = x.shape[0], x.shape[1]
        qkv = self.qkv_proj(x).reshape([b, s, 3, self.num_heads,
                                        self.head_dim])
        if self._tp:
            from ..distributed.fleet.mpu import _constrain, _get_mesh
            qkv = _constrain(qkv, _get_mesh(),
                             [None, None, None, "mp", None])
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True)
        out = out.reshape([b, s, self.num_heads * self.head_dim])
        if self._tp:
            from ..distributed.fleet.mpu import _constrain, _get_mesh
            out = _constrain(out, _get_mesh(), [None, None, "mp"])
        return self.out_proj(out)


class GPTMLP(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__(dtype=cfg.dtype)
        if cfg.tensor_parallel and _mp_active():
            from ..distributed.fleet import (ColumnParallelLinear,
                                             RowParallelLinear)
            self.fc_in = ColumnParallelLinear(
                cfg.hidden_size, cfg.intermediate_size, has_bias=True,
                gather_output=False)
            self.fc_out = RowParallelLinear(
                cfg.intermediate_size, cfg.hidden_size, has_bias=True,
                input_is_parallel=True)
        else:
            self.fc_in = nn.Linear(cfg.hidden_size, cfg.intermediate_size)
            self.fc_out = nn.Linear(cfg.intermediate_size, cfg.hidden_size)

    def forward(self, x):
        return self.fc_out(F.gelu(self.fc_in(x)))


class GPTDecoderLayer(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__(dtype=cfg.dtype)
        self.ln_1 = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        self.attn = GPTAttention(cfg)
        self.ln_2 = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)
        self.mlp = GPTMLP(cfg)
        self.dropout = cfg.hidden_dropout
        self.use_recompute = cfg.use_recompute
        self.recompute_granularity = cfg.recompute_granularity

    def _block(self, x):
        h = self.attn(self.ln_1(x))
        if self.dropout:
            h = F.dropout(h, p=self.dropout, training=self.training)
        x = x + h
        h = self.mlp(self.ln_2(x))
        if self.dropout:
            h = F.dropout(h, p=self.dropout, training=self.training)
        return x + h

    def _attn_sub(self, x):
        h = self.attn(self.ln_1(x))
        if self.dropout:
            h = F.dropout(h, p=self.dropout, training=self.training)
        return h

    def _mlp_sub(self, x):
        h = self.mlp(self.ln_2(x))
        if self.dropout:
            h = F.dropout(h, p=self.dropout, training=self.training)
        return h

    def forward(self, x):
        if self.use_recompute:
            from ..distributed.fleet import recompute
            from ..distributed.fleet.recompute import _SubFn
            from .llama import _LayerFn
            gran = self.recompute_granularity
            if gran == "full":
                return recompute(_LayerFn(self), x)
            if gran == "full_attn":
                h = x + recompute(
                    _SubFn(self, "_attn_sub",
                           (self.ln_1, self.attn)), x)
                return h + self._mlp_sub(h)
            if gran == "core_attn":
                # flash backward recomputes scores/probs internally
                return self._block(x)
            raise ValueError(
                f"unknown recompute_granularity {gran!r}; expected "
                "'full', 'full_attn' or 'core_attn'")
        return self._block(x)



class GPTModel(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        if cfg.tensor_parallel and _mp_active():
            from ..distributed.fleet import VocabParallelEmbedding
            self.embed_tokens = VocabParallelEmbedding(cfg.vocab_size,
                                                       cfg.hidden_size)
        else:
            self.embed_tokens = nn.Embedding(cfg.vocab_size,
                                             cfg.hidden_size)
        self.embed_positions = nn.Embedding(cfg.max_position_embeddings,
                                            cfg.hidden_size)
        self.layers = nn.LayerList(
            [GPTDecoderLayer(cfg) for _ in range(cfg.num_hidden_layers)])
        self.ln_f = nn.LayerNorm(cfg.hidden_size,
                                 epsilon=cfg.layer_norm_epsilon)

    def forward(self, input_ids):
        s = input_ids.shape[1]
        pos = apply("position_ids",
                    lambda ids: jnp.broadcast_to(
                        jnp.arange(ids.shape[1]), ids.shape), input_ids)
        h = self.embed_tokens(input_ids) + self.embed_positions(pos)
        if self.cfg.dtype != "float32":
            h = h.astype(self.cfg.dtype)
        for layer in self.layers:
            h = layer(h)
        return self.ln_f(h)


class GPTForCausalLM(nn.Layer):
    def __init__(self, cfg: GPTConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        self.gpt = GPTModel(cfg)
        if cfg.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                     bias_attr=False)

    def forward(self, input_ids):
        h = self.gpt(input_ids)
        if self.cfg.chunked_ce_tokens:
            return h          # loss() owns the head matmul (chunked CE)
        if self.lm_head is None:
            from ..tensor.linalg import matmul
            return matmul(h, self.gpt.embed_tokens.weight,
                          transpose_y=True)
        return self.lm_head(h)

    def loss(self, logits, labels):
        if self.cfg.chunked_ce_tokens:
            from ..nn.functional.loss import chunked_causal_lm_loss
            return chunked_causal_lm_loss(
                logits, labels,
                None if self.lm_head is None else self.lm_head.weight,
                self.gpt.embed_tokens.weight,
                int(self.cfg.chunked_ce_tokens))
        v = logits.shape[-1]
        shift_logits = logits[:, :-1, :].reshape([-1, v])
        shift_labels = labels[:, 1:].reshape([-1])
        return F.cross_entropy(shift_logits, shift_labels)

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())


def gpt_tiny(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=512, hidden_size=128,
                     intermediate_size=512, num_hidden_layers=2,
                     num_attention_heads=4, max_position_embeddings=256,
                     **kw)


def gpt_345m(**kw) -> GPTConfig:
    return GPTConfig(vocab_size=50304, hidden_size=1024,
                     intermediate_size=4096, num_hidden_layers=24,
                     num_attention_heads=16,
                     max_position_embeddings=1024, **kw)


def ernie_45_dense_3b(**kw) -> GPTConfig:
    """ERNIE-4.5-style dense config (BASELINE.json 'ERNIE (DP)' entry)."""
    return GPTConfig(vocab_size=103424, hidden_size=2560,
                     intermediate_size=12288, num_hidden_layers=28,
                     num_attention_heads=20,
                     max_position_embeddings=4096,
                     tie_word_embeddings=False, **kw)
