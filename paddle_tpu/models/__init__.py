"""paddle_tpu.models — flagship model zoo (BASELINE.json configs)."""
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, llama_tiny, llama_small,
    llama_mid, llama_1b, llama_3_8b,
)
from .gpt import (  # noqa: F401
    GPTConfig, GPTForCausalLM, GPTModel, gpt_tiny, gpt_345m,
    ernie_45_dense_3b,
)
from .moe_lm import (  # noqa: F401
    MoEConfig, MoEForCausalLM, MoEModel, moe_tiny, deepseek_moe_16b_like,
    qwen2_moe_a14b_like,
)
from .dit import (  # noqa: F401
    DiT, DiTConfig, dit_tiny, dit_s_2, dit_xl_2,
)
from .bert import (  # noqa: F401
    BertConfig, BertForMaskedLM, BertForSequenceClassification, BertModel,
    bert_tiny, bert_base, bert_large,
)
