"""paddle_tpu.models — flagship model zoo (BASELINE.json configs)."""
from .llama import (  # noqa: F401
    LlamaConfig, LlamaForCausalLM, LlamaModel, llama_tiny, llama_small,
    llama_3_8b,
)
