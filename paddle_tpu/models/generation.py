"""Autoregressive generation over static-shape KV caches.

Reference analog: the serving decode loops built on
block_multihead_attention + masked_multihead_attention
(/root/reference/python/paddle/incubate/nn/functional/), plus the
BeamSearchDecoder semantics (/root/reference/python/paddle/nn/
decode.py:153) for beam_search. TPU-native structure: two compiled
programs — prefill (prompt chunk, fills the caches) and a single-token
decode step (traced position into fixed [b, max_len] caches, donated so
updates happen in-place in HBM). The Python loop only replays the
compiled decode step: no per-step recompiles, no dynamic shapes.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..jit import functional_call

__all__ = ["generate", "beam_search"]


def _sample(logits, temperature, top_k, key, top_p=1.0):
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    if top_p < 1.0:
        # nucleus: keep the smallest prefix of the sorted distribution
        # whose mass reaches top_p (the top token always survives)
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        cutoff_mask = cum - probs > top_p         # tokens past the mass
        kth_val = jnp.where(cutoff_mask, jnp.inf, sorted_logits).min(
            axis=-1, keepdims=True)
        logits = jnp.where(logits < kth_val, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def _apply_repetition_penalty(logits, seen_mask, penalty):
    """HF/reference semantics: for already-generated tokens, divide
    positive logits by `penalty` and multiply negative ones."""
    if penalty == 1.0:
        return logits
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(seen_mask, penalized, logits)


def _setup_decode(model, input_ids, max_new_tokens, max_length):
    """Shared generate/beam_search preamble: unwrap ids, bound the new-
    token budget, collect param/buffer arrays (same ordering
    functional_call uses), and allocate the static KV caches."""
    cfg = model.cfg
    ids = input_ids._value if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    b, prompt_len = ids.shape
    max_length = max_length or min(cfg.max_position_embeddings,
                                   prompt_len + max_new_tokens)
    n_new = min(max_new_tokens, max_length - prompt_len)
    model.eval()
    from ..jit import _collect
    params, buffers = _collect(model)
    p_arrays = [p._value for _, p in params]
    b_arrays = [bf._value for _, bf in buffers]
    kv_heads = cfg.num_key_value_heads
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32
    caches = [(jnp.zeros((b, max_length, kv_heads, head_dim), dtype),
               jnp.zeros((b, max_length, kv_heads, head_dim), dtype))
              for _ in range(cfg.num_hidden_layers)]
    return ids, b, prompt_len, n_new, p_arrays, b_arrays, caches


def generate(model, input_ids, max_new_tokens: int = 32,
             max_length: Optional[int] = None, temperature: float = 0.0,
             top_k: int = 0, eos_token_id: Optional[int] = None,
             seed: int = 0, top_p: float = 1.0,
             repetition_penalty: float = 1.0):
    """Returns a Tensor [batch, prompt_len + generated] of token ids
    (prompt included). Greedy when temperature == 0; top_k/top_p
    filtering and repetition penalty follow the reference generate
    semantics. (New kwargs append after the r2 signature so positional
    callers keep their meaning.)"""
    cfg = model.cfg
    ids, b, prompt_len, n_new, p_arrays, b_arrays, caches = \
        _setup_decode(model, input_ids, max_new_tokens, max_length)
    if n_new <= 0:
        return Tensor(ids)

    def step(pa, ba, chunk, caches_in, pos, key, seen_mask):
        (logits, new_caches), _ = functional_call(
            model, pa, ba, (chunk,),
            kwargs={"caches": caches_in, "pos": pos})
        last = _apply_repetition_penalty(logits[:, -1, :], seen_mask,
                                         repetition_penalty)
        next_tok = _sample(last, temperature, top_k, key, top_p)
        return next_tok, new_caches

    prefill_j = jax.jit(step)
    decode_j = jax.jit(step, donate_argnums=(3,))

    # token-presence mask for the repetition penalty (prompt + generated)
    seen = jnp.zeros((b, cfg.vocab_size), bool)
    if repetition_penalty != 1.0:
        seen = seen.at[jnp.arange(b)[:, None], ids].set(True)

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    tok, caches = prefill_j(p_arrays, b_arrays, ids, caches,
                            jnp.int32(0), k0, seen)
    out_tokens = [tok]
    pos = prompt_len
    finished = jnp.zeros((b,), bool)
    if eos_token_id is not None:
        finished = finished | (tok == eos_token_id)
    for _ in range(n_new - 1):
        if eos_token_id is not None and bool(finished.all()):
            break
        if repetition_penalty != 1.0:
            seen = seen.at[jnp.arange(b), tok].set(True)
        key, kd = jax.random.split(key)
        tok, caches = decode_j(p_arrays, b_arrays, tok[:, None], caches,
                               jnp.int32(pos), kd, seen)
        if eos_token_id is not None:
            tok = jnp.where(finished, eos_token_id, tok)
            finished = finished | (tok == eos_token_id)
        out_tokens.append(tok)
        pos += 1
    gen = jnp.stack(out_tokens, axis=1)
    return Tensor(jnp.concatenate([ids, gen], axis=1))


def _lp_array(lengths, alpha):
    """Elementwise GNMT length penalty over a [b, nb] length array."""
    if alpha == 0.0:
        return jnp.ones_like(lengths, dtype=jnp.float32)
    return ((5.0 + lengths.astype(jnp.float32)) / 6.0) ** alpha


def beam_step(scores, logp, finished, eos_token_id, lengths=None,
              length_penalty=0.0):
    """One beam-search expansion (shared by models.beam_search and
    nn.dynamic_decode): scores [b, nb], logp [b, nb, V], finished
    [b, nb], lengths [b, nb] (generated tokens so far, FROZEN at eos)
    → (new_scores, beam_idx, tok_idx, new_finished, new_lengths).

    Finished beams continue by emitting eos at logp 0 with frozen
    length. Candidates rank by score / lp(candidate_length) — a
    per-candidate penalty, so finished short hypotheses genuinely
    compete against longer live ones (the reference BeamSearchDecoder
    ranks by raw score, i.e. length_penalty=0). Accumulated scores stay
    raw; apply the penalty again for the final selection."""
    b, nb, vocab = logp.shape
    if lengths is None:
        lengths = jnp.zeros((b, nb), jnp.int32)
    if eos_token_id is not None:
        eos_row = jnp.full((vocab,), -jnp.inf, jnp.float32) \
            .at[eos_token_id].set(0.0)
        logp = jnp.where(finished[:, :, None], eos_row[None, None], logp)
    cand = scores[:, :, None] + logp
    # candidate length: live beams grow by one, finished stay frozen
    cand_len = jnp.where(finished, lengths, lengths + 1)    # [b, nb]
    rank = cand / _lp_array(cand_len, length_penalty)[:, :, None]
    _, top_idx = jax.lax.top_k(rank.reshape(b, nb * vocab), nb)
    beam_idx = (top_idx // vocab).astype(jnp.int32)
    tok_idx = (top_idx % vocab).astype(jnp.int32)
    new_scores = jnp.take_along_axis(cand.reshape(b, nb * vocab),
                                     top_idx, axis=1)
    new_finished = jnp.take_along_axis(finished, beam_idx, axis=1)
    new_lengths = jnp.take_along_axis(cand_len, beam_idx, axis=1)
    if eos_token_id is not None:
        new_finished = new_finished | (tok_idx == eos_token_id)
    return new_scores, beam_idx, tok_idx, new_finished, new_lengths


def beam_search(model, input_ids, num_beams: int = 4,
                max_new_tokens: int = 32, length_penalty: float = 0.0,
                eos_token_id: Optional[int] = None,
                max_length: Optional[int] = None):
    """Beam-search decode over the KV caches (reference semantics:
    BeamSearchDecoder, /root/reference/python/paddle/nn/decode.py:153 —
    candidates ranked by cumulative log-prob scaled by the GNMT length
    penalty; finished beams propagate by emitting eos at logp 0; early
    stop when every beam is finished).

    Returns a Tensor [batch, prompt_len + generated] with the best beam
    per batch element (prompt included).
    """
    nb = int(num_beams)
    ids, b, prompt_len, n_new, p_arrays, b_arrays, caches = \
        _setup_decode(model, input_ids, max_new_tokens, max_length)
    if n_new <= 0:
        return Tensor(ids)

    def prefill(pa, ba, chunk, caches_in):
        (logits, new_caches), _ = functional_call(
            model, pa, ba, (chunk,),
            kwargs={"caches": caches_in, "pos": jnp.int32(0)})
        return jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32), axis=-1), new_caches

    def decode(pa, ba, toks, caches_in, pos, beam_sel):
        # reorder each cache row to its surviving parent beam, then step
        caches_in = jax.tree_util.tree_map(
            lambda c: c[beam_sel], caches_in)
        (logits, new_caches), _ = functional_call(
            model, pa, ba, (toks[:, None],),
            kwargs={"caches": caches_in, "pos": pos})
        return jax.nn.log_softmax(
            logits[:, -1, :].astype(jnp.float32), axis=-1), new_caches

    prefill_j = jax.jit(prefill)
    decode_j = jax.jit(decode, donate_argnums=(3,))

    logp0, caches = prefill_j(p_arrays, b_arrays, ids, caches)
    vocab = logp0.shape[-1]
    # tile caches across beams: [b, ...] -> [b*nb, ...]
    caches = jax.tree_util.tree_map(
        lambda c: jnp.repeat(c, nb, axis=0), caches)

    # first expansion: top nb continuations of the single prompt
    scores, toks = jax.lax.top_k(logp0, nb)            # [b, nb]
    toks = toks.astype(jnp.int32)
    history = toks[:, :, None]                         # [b, nb, 1]
    finished = jnp.zeros((b, nb), bool)
    if eos_token_id is not None:
        finished = toks == eos_token_id
    lengths = jnp.ones((b, nb), jnp.int32)
    beam_sel = jnp.arange(b * nb, dtype=jnp.int32)
    pos = prompt_len
    for t in range(1, n_new):
        if eos_token_id is not None and bool(finished.all()):
            break
        logp, caches = decode_j(p_arrays, b_arrays,
                                toks.reshape(b * nb), caches,
                                jnp.int32(pos), beam_sel)
        logp = logp.reshape(b, nb, vocab)
        scores, beam_idx, toks, finished, lengths = beam_step(
            scores, logp, finished, eos_token_id, lengths,
            length_penalty)
        history = jnp.concatenate(
            [jnp.take_along_axis(history, beam_idx[:, :, None], axis=1),
             toks[:, :, None]], axis=2)
        beam_sel = (jnp.arange(b, dtype=jnp.int32)[:, None] * nb
                    + beam_idx).reshape(b * nb)
        pos += 1

    final_rank = scores / _lp_array(lengths, length_penalty)
    best = jnp.argmax(final_rank, axis=1)
    best_seq = jnp.take_along_axis(
        history, best[:, None, None], axis=1)[:, 0]    # [b, gen_len]
    return Tensor(jnp.concatenate([ids, best_seq.astype(jnp.int32)],
                                  axis=1))
