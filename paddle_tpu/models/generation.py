"""Autoregressive generation over static-shape KV caches.

Reference analog: the serving decode loops built on
block_multihead_attention + masked_multihead_attention
(/root/reference/python/paddle/incubate/nn/functional/). TPU-native
structure: two compiled programs — prefill (prompt chunk, fills the
caches) and a single-token decode step (traced position into fixed
[b, max_len] caches, donated so updates happen in-place in HBM). The
Python loop only replays the compiled decode step: no per-step
recompiles, no dynamic shapes.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from ..jit import functional_call

__all__ = ["generate"]


def _sample(logits, temperature, top_k, key):
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, -1e30, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def generate(model, input_ids, max_new_tokens: int = 32,
             max_length: Optional[int] = None, temperature: float = 0.0,
             top_k: int = 0, eos_token_id: Optional[int] = None,
             seed: int = 0):
    """Returns a Tensor [batch, prompt_len + generated] of token ids
    (prompt included). Greedy when temperature == 0."""
    cfg = model.cfg
    ids = input_ids._value if isinstance(input_ids, Tensor) \
        else jnp.asarray(input_ids)
    ids = ids.astype(jnp.int32)
    b, prompt_len = ids.shape
    max_length = max_length or min(cfg.max_position_embeddings,
                                   prompt_len + max_new_tokens)
    n_new = min(max_new_tokens, max_length - prompt_len)
    if n_new <= 0:
        return Tensor(ids)

    model.eval()
    # same collection functional_call uses internally — ordering must match
    from ..jit import _collect
    params, buffers = _collect(model)
    p_arrays = [p._value for _, p in params]
    b_arrays = [bf._value for _, bf in buffers]
    n_layers = cfg.num_hidden_layers
    kv_heads = cfg.num_key_value_heads
    head_dim = cfg.hidden_size // cfg.num_attention_heads
    dtype = jnp.bfloat16 if cfg.dtype == "bfloat16" else jnp.float32

    caches = [(jnp.zeros((b, max_length, kv_heads, head_dim), dtype),
               jnp.zeros((b, max_length, kv_heads, head_dim), dtype))
              for _ in range(n_layers)]

    def step(pa, ba, chunk, caches_in, pos, key):
        (logits, new_caches), _ = functional_call(
            model, pa, ba, (chunk,),
            kwargs={"caches": caches_in, "pos": pos})
        next_tok = _sample(logits[:, -1, :], temperature, top_k, key)
        return next_tok, new_caches

    prefill_j = jax.jit(step)
    decode_j = jax.jit(step, donate_argnums=(3,))

    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    tok, caches = prefill_j(p_arrays, b_arrays, ids, caches,
                            jnp.int32(0), k0)
    out_tokens = [tok]
    pos = prompt_len
    finished = jnp.zeros((b,), bool)
    if eos_token_id is not None:
        finished = finished | (tok == eos_token_id)
    for _ in range(n_new - 1):
        if eos_token_id is not None and bool(finished.all()):
            break
        key, kd = jax.random.split(key)
        tok, caches = decode_j(p_arrays, b_arrays, tok[:, None], caches,
                               jnp.int32(pos), kd)
        if eos_token_id is not None:
            tok = jnp.where(finished, eos_token_id, tok)
            finished = finished | (tok == eos_token_id)
        out_tokens.append(tok)
        pos += 1
    gen = jnp.stack(out_tokens, axis=1)
    return Tensor(jnp.concatenate([ids, gen], axis=1))
