"""Pipelined Llama: the flagship 4D-parallel (pp x tp x fsdp x dp) train
step over the table-driven pipeline schedules.

Reference parity: PipelineParallel.train_batch over a hybrid topology
(/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:657 with the 1F1B schedule at :440 and interleaved
VPP at :906, composed with mpu TP layers fleet/layers/mpu/mp_layers.py and
sharding stages).

TPU-native design: the decoder trunk is expressed functionally over
stacked per-chunk parameters [vpp, pp, layers_per_chunk, ...], the pipeline
runs as one lax.scan over static schedule tables (pp_schedule.py) inside a
shard_map manual over only the 'pp' mesh axis, and tp ('mp' axis) + FSDP
('sharding' axis) + dp compose as GSPMD auto axes: weights carry
NamedShardings (column/row-parallel on 'mp', parameter sharding on
'sharding'), activations carry with_sharding_constraint hints, and XLA
inserts the all-gathers / reduce-scatters. Embedding and the lm head +
loss live outside the trunk; their gradients flow through the engine's
custom_vjp (d loss / d microbatch-activations).
"""
from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops.rms_norm import rms_norm
from ..ops.rope import build_rope_cache, rope_reference
from ..ops.flash_attention import flash_attention_reference
from ..distributed.fleet.pp_schedule import (build_pipeline_schedule,
                                             make_pipeline_loss_fn)

__all__ = ["PipelinedLlamaConfig", "build_pipelined_llama_step"]


@dataclass
class PipelinedLlamaConfig:
    vocab_size: int = 256
    hidden_size: int = 64
    intermediate_size: int = 128
    num_attention_heads: int = 4
    num_key_value_heads: int = 2
    layers_per_chunk: int = 1
    vpp_degree: int = 1
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    max_seq_len: int = 128
    dtype: Any = jnp.float32
    schedule_mode: str = "1F1B"

    def num_layers(self, pp: int) -> int:
        return self.vpp_degree * pp * self.layers_per_chunk


def _constraint(mesh, spec):
    # A bare PartitionSpec resolves against the tracing context's mesh —
    # required inside shard_map(axis_names={'pp'}), where the context mesh
    # marks 'pp' Manual and a NamedSharding over the plain mesh mismatches
    # (so NO physical-mesh context manager here on the modern path).
    # On toolchains without partial-manual shard_map support the pipeline
    # body runs fully manual (see pp_schedule.partial_manual_ok): every
    # mesh axis is manual there, in-body GSPMD constraints are meaningless
    # and the specs' axes aren't auto — drop the hints (numerics are
    # unaffected; they only steered auto-axis layout).
    from ..distributed.fleet.pp_schedule import partial_manual_ok
    del mesh
    if not partial_manual_ok():
        return lambda x: x
    return lambda x: jax.lax.with_sharding_constraint(x, spec)


def _decoder_layer(w, x, cos, sin, cfg, batch_c, heads_c, ffn_c):
    """One functional decoder layer. w: dict of unstacked weights."""
    nh, nkv = cfg.num_attention_heads, cfg.num_key_value_heads
    hd = cfg.hidden_size // nh
    b, s, _ = x.shape
    h = rms_norm(x, w["ln1"], cfg.rms_norm_eps)
    q = heads_c((h @ w["wq"]).reshape(b, s, nh, hd))
    k = heads_c((h @ w["wk"]).reshape(b, s, nkv, hd))
    v = heads_c((h @ w["wv"]).reshape(b, s, nkv, hd))
    q = rope_reference(q, cos, sin)
    k = rope_reference(k, cos, sin)
    attn = flash_attention_reference(q, k, v, causal=True)
    x = x + batch_c(attn.reshape(b, s, cfg.hidden_size) @ w["wo"])
    h = rms_norm(x, w["ln2"], cfg.rms_norm_eps)
    gate = ffn_c(h @ w["wg"])
    up = ffn_c(h @ w["wu"])
    x = x + batch_c((jax.nn.silu(gate) * up) @ w["wd"])
    return batch_c(x)


def _init_trunk(key, cfg: PipelinedLlamaConfig, pp: int):
    """Stacked trunk params: leaves [vpp, pp, layers_per_chunk, ...]."""
    d, f = cfg.hidden_size, cfg.intermediate_size
    kv = cfg.num_key_value_heads * (d // cfg.num_attention_heads)
    lead = (cfg.vpp_degree, pp, cfg.layers_per_chunk)
    shapes = {"wq": (d, d), "wk": (d, kv), "wv": (d, kv), "wo": (d, d),
              "wg": (d, f), "wu": (d, f), "wd": (f, d)}
    keys = jax.random.split(key, len(shapes))
    w = {}
    for (name, shp), k in zip(sorted(shapes.items()), keys):
        scale = 1.0 / math.sqrt(shp[0])
        w[name] = (jax.random.normal(k, lead + shp, jnp.float32)
                   * scale).astype(cfg.dtype)
    w["ln1"] = jnp.ones(lead + (d,), cfg.dtype)
    w["ln2"] = jnp.ones(lead + (d,), cfg.dtype)
    return w


def _trunk_shardings(mesh, has_sharding_axis: bool):
    """NamedShardings for the stacked trunk (tp on 'mp', FSDP on
    'sharding'). Column-parallel projections shard the output feature dim
    over mp; row-parallel (wo/wd) shard the input feature dim — the same
    column/row layout the canonical serving table pins
    (distributed/spec_layout.SpecLayout, 'tp' axis); flightcheck FC605
    flags any literal spec that drifts from it, and the comm audit
    (tools/flightcheck/comm_audit.py `llama_pp.train_step`) pins this
    step's collectives."""
    sh = "sharding" if has_sharding_axis else None
    spec = {
        "wq": P(None, "pp", None, sh, "mp"),
        "wk": P(None, "pp", None, sh, "mp"),
        "wv": P(None, "pp", None, sh, "mp"),
        "wo": P(None, "pp", None, "mp", sh),
        "wg": P(None, "pp", None, sh, "mp"),
        "wu": P(None, "pp", None, sh, "mp"),
        "wd": P(None, "pp", None, "mp", sh),
        "ln1": P(None, "pp", None, None),
        "ln2": P(None, "pp", None, None),
    }
    return {k: NamedSharding(mesh, v) for k, v in spec.items()}


def _adamw_update(params, grads, mu, nu, step, lr, b1=0.9, b2=0.95,
                  eps=1e-8, weight_decay=0.01):
    step = step + 1
    c1 = 1.0 - b1 ** step.astype(jnp.float32)
    c2 = 1.0 - b2 ** step.astype(jnp.float32)

    def upd(p, g, m, v):
        g32 = g.astype(jnp.float32)
        m = b1 * m + (1 - b1) * g32
        v = b2 * v + (1 - b2) * jnp.square(g32)
        u = (m / c1) / (jnp.sqrt(v / c2) + eps)
        p32 = p.astype(jnp.float32)
        return (p32 - lr * (u + weight_decay * p32)).astype(p.dtype), m, v

    flat_p, tdef = jax.tree_util.tree_flatten(params)
    flat_g = jax.tree_util.tree_leaves(grads)
    flat_m = jax.tree_util.tree_leaves(mu)
    flat_v = jax.tree_util.tree_leaves(nu)
    out = [upd(p, g, m, v) for p, g, m, v in
           zip(flat_p, flat_g, flat_m, flat_v)]
    new_p = jax.tree_util.tree_unflatten(tdef, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(tdef, [o[1] for o in out])
    new_v = jax.tree_util.tree_unflatten(tdef, [o[2] for o in out])
    return new_p, new_m, new_v, step


def build_pipelined_llama_step(cfg: PipelinedLlamaConfig, mesh,
                               n_micro: int, micro_batch: int, seq: int,
                               lr: float = 1e-4, seed: int = 0,
                               schedule_mode: Optional[str] = None):
    """Build (state, step_fn) for the 4D-parallel pipelined Llama.

    mesh: jax Mesh with a 'pp' axis; 'mp' / 'sharding' / 'dp' axes compose
    when present. step_fn(state, ids, labels) -> (state, loss) is jitted
    with state donation; ids/labels are [n_micro*micro_batch, seq] int32.
    """
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    axes = dict(jmesh.shape)
    pp = axes["pp"]
    has_sh = "sharding" in axes and axes["sharding"] > 1
    mode = schedule_mode or cfg.schedule_mode
    sched = build_pipeline_schedule(pp, n_micro, cfg.vpp_degree, mode)

    d = cfg.hidden_size
    hd = d // cfg.num_attention_heads
    cos, sin = build_rope_cache(seq, hd, cfg.rope_theta, jnp.float32)
    batch_axes = ("dp", "sharding") if has_sh else ("dp",)
    if "dp" not in axes:
        batch_axes = tuple(a for a in batch_axes if a != "dp")
    bspec = batch_axes if batch_axes else None
    batch_c = _constraint(jmesh, P(bspec, None, None))
    heads_c = _constraint(jmesh, P(bspec, None, "mp", None))
    ffn_c = _constraint(jmesh, P(bspec, None, "mp"))

    def stage_fn(chunk_w, x):
        for i in range(cfg.layers_per_chunk):
            wi = {k: v[i] for k, v in chunk_w.items()}
            x = _decoder_layer(wi, x, cos, sin, cfg, batch_c, heads_c,
                               ffn_c)
        return x

    def loss_fn(lp, out, labels):
        h = rms_norm(out, lp["norm"], cfg.rms_norm_eps)
        logits = (h @ lp["head"]).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        ll = jnp.take_along_axis(logp, labels[..., None], axis=-1)
        return -jnp.mean(ll)

    # zb schedules split the backward off stored residuals (B/W slots)
    # and require store-activations mode; everything else defaults to
    # the 1F1B remat memory story
    ploss = make_pipeline_loss_fn(stage_fn, loss_fn, jmesh, sched,
                                  remat=sched.mode != "zb")

    # ---- init ----
    key = jax.random.PRNGKey(seed)
    k_tr, k_emb, k_head = jax.random.split(key, 3)
    trunk = _init_trunk(k_tr, cfg, pp)
    tshards = _trunk_shardings(jmesh, has_sh)
    trunk = {k: jax.device_put(v, tshards[k]) for k, v in trunk.items()}
    # NOTE: embed/head are replicated. Any 'mp' sharding on arrays that
    # enter the manual-'pp' shard_map as replicated-in-pp operands trips
    # an XLA SPMD-partitioner CHECK (spmd_partitioner_util.cc:495) on
    # meshes with >=2 auto axes (jax 0.9) — minimal repro in
    # tests/test_pipeline_schedules.py docstring. The trunk (the bulk of
    # params and FLOPs) dual-shards over 'sharding' x 'mp' fine.
    embed = jax.device_put(
        (jax.random.normal(k_emb, (cfg.vocab_size, d), jnp.float32)
         * 0.02).astype(cfg.dtype),
        NamedSharding(jmesh, P(None, None)))
    head = jax.device_put(
        (jax.random.normal(k_head, (d, cfg.vocab_size), jnp.float32)
         * (1.0 / math.sqrt(d))).astype(cfg.dtype),
        NamedSharding(jmesh, P(None, None)))
    norm = jax.device_put(jnp.ones((d,), cfg.dtype),
                          NamedSharding(jmesh, P(None)))
    params = {"trunk": trunk, "embed": embed, "head": head, "norm": norm}
    zeros32 = lambda t: jax.tree_util.tree_map(
        lambda a: jnp.zeros(a.shape, jnp.float32), t)
    state = {"params": params, "mu": zeros32(params), "nu": zeros32(params),
             "step": jnp.zeros((), jnp.int32)}

    m, b = n_micro, micro_batch

    @partial(jax.jit, donate_argnums=(0,))
    def step_fn(state, ids, labels):
        ids_mb = ids.reshape(m, b, seq)
        lab_mb = labels.reshape(m, b, seq)

        def total_loss(p):
            xs = jnp.take(p["embed"], ids_mb, axis=0)
            xs = jax.lax.with_sharding_constraint(
                xs, NamedSharding(jmesh, P(None, bspec, None, None)))
            return ploss(p["trunk"],
                         {"norm": p["norm"], "head": p["head"]},
                         xs, lab_mb)

        loss, grads = jax.value_and_grad(total_loss)(state["params"])
        new_p, new_m, new_v, step = _adamw_update(
            state["params"], grads, state["mu"], state["nu"],
            state["step"], lr)
        return {"params": new_p, "mu": new_m, "nu": new_v,
                "step": step}, loss

    return state, step_fn, sched
