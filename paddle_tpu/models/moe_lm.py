"""Mixture-of-Experts causal LM (DeepSeekMoE / Qwen2-MoE style).

Capability parity target: the reference's MoE stack
(/root/reference/python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 + global_scatter/gather alltoall comm) as used by
DeepSeek/Qwen MoE recipes (BASELINE.json EP config).

TPU-native: Llama-style decoder blocks whose MLP is an nn.MoELayer
(top-k gating, capacity-bounded dispatch expressed as one-hot matmuls —
MXU-friendly — with the expert dim sharded over the mesh 'ep'/'mp' axis
under fleet; the all-to-all is GSPMD-inserted). A DeepSeek-style shared
expert runs densely alongside the routed experts.
"""
from __future__ import annotations

from dataclasses import dataclass

from .. import nn
from ..nn import functional as F
from .llama import LlamaAttention, LlamaConfig, _LayerFn

__all__ = ["MoEConfig", "MoEForCausalLM", "MoEModel", "moe_tiny",
           "deepseek_moe_16b_like", "qwen2_moe_a14b_like"]


@dataclass
class MoEConfig:
    vocab_size: int = 32000
    hidden_size: int = 2048
    intermediate_size: int = 5632        # shared-expert/dense FFN width
    moe_intermediate_size: int = 1408    # per-expert FFN width
    num_hidden_layers: int = 8
    num_attention_heads: int = 16
    num_key_value_heads: int = 16
    num_experts: int = 8
    num_experts_per_tok: int = 2
    num_shared_experts: int = 1          # DeepSeek-style dense experts
    first_k_dense_replace: int = 1       # first k layers use dense MLP
    capacity_factor: float = 1.25
    # 'dense' = GShard one-hot dispatch (EP-shardable); 'ragged' =
    # sort-based dropless grouped-matmul dispatch (the large-E on-chip
    # path — memory O(T*k*D) instead of O(T*E*C))
    moe_dispatch_mode: str = "dense"
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-6
    rope_theta: float = 10000.0
    aux_loss_weight: float = 0.01
    dtype: str = "float32"
    use_recompute: bool = False
    # 'full' | 'full_attn' | 'core_attn' (see LlamaConfig)
    recompute_granularity: str = "full"
    tensor_parallel: bool = False
    # >0: forward() returns hidden states; loss() runs the chunked
    # head-matmul + CE (see nn.functional.chunked_softmax_cross_entropy)
    chunked_ce_tokens: int = 0

    def _attn_cfg(self) -> LlamaConfig:
        return LlamaConfig(
            vocab_size=self.vocab_size, hidden_size=self.hidden_size,
            intermediate_size=self.intermediate_size,
            num_hidden_layers=self.num_hidden_layers,
            num_attention_heads=self.num_attention_heads,
            num_key_value_heads=self.num_key_value_heads,
            max_position_embeddings=self.max_position_embeddings,
            rms_norm_eps=self.rms_norm_eps, rope_theta=self.rope_theta,
            dtype=self.dtype, tensor_parallel=self.tensor_parallel)


class _DenseMLP(nn.Layer):
    def __init__(self, d_model, d_hidden, dtype):
        super().__init__(dtype=dtype)
        self.gate_proj = nn.Linear(d_model, d_hidden, bias_attr=False)
        self.up_proj = nn.Linear(d_model, d_hidden, bias_attr=False)
        self.down_proj = nn.Linear(d_hidden, d_model, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.silu(self.gate_proj(x)) * self.up_proj(x))


class MoEBlock(nn.Layer):
    """Routed experts + optional shared (always-on) expert."""

    def __init__(self, cfg: MoEConfig):
        super().__init__(dtype=cfg.dtype)
        self.moe = nn.MoELayer(
            d_model=cfg.hidden_size,
            d_hidden=cfg.moe_intermediate_size,
            num_experts=cfg.num_experts, gate="gshard",
            top_k=cfg.num_experts_per_tok,
            capacity_factor=cfg.capacity_factor,
            dispatch_mode=cfg.moe_dispatch_mode)
        self.shared = _DenseMLP(
            cfg.hidden_size,
            cfg.moe_intermediate_size * cfg.num_shared_experts,
            cfg.dtype) if cfg.num_shared_experts > 0 else None

    def forward(self, x):
        routed = self.moe(x)
        if self.shared is not None:
            routed = routed + self.shared(x)
        return routed

    @property
    def aux_loss(self):
        return self.moe.aux_loss


class MoEDecoderLayer(nn.Layer):
    def __init__(self, cfg: MoEConfig, layer_idx: int):
        super().__init__(dtype=cfg.dtype)
        acfg = cfg._attn_cfg()
        self.input_layernorm = nn.RMSNorm(cfg.hidden_size,
                                          cfg.rms_norm_eps,
                                          dtype=cfg.dtype)
        self.self_attn = LlamaAttention(acfg)
        self.post_attention_layernorm = nn.RMSNorm(
            cfg.hidden_size, cfg.rms_norm_eps, dtype=cfg.dtype)
        self.is_dense = layer_idx < cfg.first_k_dense_replace
        if self.is_dense:
            self.mlp = _DenseMLP(cfg.hidden_size, cfg.intermediate_size,
                                 cfg.dtype)
        else:
            self.mlp = MoEBlock(cfg)
        self.use_recompute = cfg.use_recompute
        self.recompute_granularity = cfg.recompute_granularity

    def _block(self, x):
        h = x + self.self_attn(self.input_layernorm(x))
        return h + self.mlp(self.post_attention_layernorm(h))

    def forward(self, x):
        if self.use_recompute:
            from ..distributed.fleet import recompute
            from .llama import _AttnFn
            gran = self.recompute_granularity
            if gran == "full":
                if isinstance(self.mlp, MoEBlock):
                    # the router aux-loss must cross the checkpoint
                    # boundary as an OUTPUT — a side-channel store from
                    # inside jax.checkpoint leaks an escaped tracer
                    out, aux = recompute(_MoEBlockFn(self), x)
                    self.mlp.moe._aux_loss = aux
                    return out
                return recompute(_LayerFn(self), x)
            if gran == "full_attn":
                h = x + recompute(_AttnFn(self), x)
                return h + self.mlp(self.post_attention_layernorm(h))
            if gran == "core_attn":
                return self._block(x)
            raise ValueError(
                f"unknown recompute_granularity {gran!r}; expected "
                "'full', 'full_attn' or 'core_attn'")
        return self._block(x)


class _MoEBlockFn:
    """recompute() adapter for an MoE decoder layer: returns
    (block_output, router_aux_loss) so the aux-loss is a real
    checkpoint output with a grad path, not an escaped tracer."""

    def __init__(self, layer):
        self.layer = layer

    def parameters(self):
        return self.layer.parameters()

    def __call__(self, x):
        out = self.layer._block(x)
        return out, self.layer.mlp.aux_loss


class MoEModel(nn.Layer):
    def __init__(self, cfg: MoEConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        self.embed_tokens = nn.Embedding(cfg.vocab_size, cfg.hidden_size)
        self.layers = nn.LayerList(
            [MoEDecoderLayer(cfg, i)
             for i in range(cfg.num_hidden_layers)])
        self.norm = nn.RMSNorm(cfg.hidden_size, cfg.rms_norm_eps,
                               dtype=cfg.dtype)

    def forward(self, input_ids):
        h = self.embed_tokens(input_ids)
        if self.cfg.dtype != "float32":
            h = h.astype(self.cfg.dtype)
        for layer in self.layers:
            h = layer(h)
        return self.norm(h)

    def aux_losses(self):
        out = []
        for layer in self.layers:
            if isinstance(layer.mlp, MoEBlock) and \
                    layer.mlp.aux_loss is not None:
                out.append(layer.mlp.aux_loss)
        return out


class MoEForCausalLM(nn.Layer):
    def __init__(self, cfg: MoEConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        self.model = MoEModel(cfg)
        self.lm_head = nn.Linear(cfg.hidden_size, cfg.vocab_size,
                                 bias_attr=False)

    def forward(self, input_ids):
        h = self.model(input_ids)
        if self.cfg.chunked_ce_tokens:
            return h          # loss() owns the head matmul (chunked CE)
        return self.lm_head(h)

    def loss(self, logits, labels):
        """Shifted CE + router load-balance auxiliary loss."""
        if self.cfg.chunked_ce_tokens:
            from ..nn.functional.loss import chunked_causal_lm_loss
            ce = chunked_causal_lm_loss(
                logits, labels, self.lm_head.weight, None,
                int(self.cfg.chunked_ce_tokens))
        else:
            v = logits.shape[-1]
            shift_logits = logits[:, :-1, :].reshape([-1, v])
            shift_labels = labels[:, 1:].reshape([-1])
            ce = F.cross_entropy(shift_logits, shift_labels)
        aux = self.model.aux_losses()
        if aux and self.cfg.aux_loss_weight:
            total_aux = aux[0]
            for a in aux[1:]:
                total_aux = total_aux + a
            ce = ce + self.cfg.aux_loss_weight * total_aux
        return ce

    def num_params(self) -> int:
        return sum(p.size for p in self.parameters())

    def num_activated_params(self) -> int:
        """Per-token activated parameters (MoE efficiency metric)."""
        total = 0
        for name, p in self.named_parameters():
            if ".moe." in name and ("w1" in name or "w2" in name
                                    or "experts" in name):
                total += p.size * self.cfg.num_experts_per_tok \
                    // self.cfg.num_experts
            else:
                total += p.size
        return total


def moe_tiny(**kw) -> MoEConfig:
    base = dict(vocab_size=512, hidden_size=128,
                intermediate_size=256, moe_intermediate_size=64,
                num_hidden_layers=2, num_attention_heads=4,
                num_key_value_heads=4, num_experts=4,
                num_experts_per_tok=2, first_k_dense_replace=1,
                max_position_embeddings=256)
    base.update(kw)          # callers may override any default
    return MoEConfig(**base)


def deepseek_moe_16b_like(**kw) -> MoEConfig:
    return MoEConfig(vocab_size=102400, hidden_size=2048,
                     intermediate_size=10944, moe_intermediate_size=1408,
                     num_hidden_layers=28, num_attention_heads=16,
                     num_key_value_heads=16, num_experts=64,
                     num_experts_per_tok=6, num_shared_experts=2,
                     first_k_dense_replace=1,
                     max_position_embeddings=4096, **kw)


def qwen2_moe_a14b_like(**kw) -> MoEConfig:
    return MoEConfig(vocab_size=151936, hidden_size=3584,
                     intermediate_size=18944, moe_intermediate_size=2560,
                     num_hidden_layers=28, num_attention_heads=28,
                     num_key_value_heads=4, num_experts=64,
                     num_experts_per_tok=8, num_shared_experts=1,
                     first_k_dense_replace=0,
                     max_position_embeddings=8192, **kw)
