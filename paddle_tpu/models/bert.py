"""BERT encoder family (masked-LM / classification).

Capability parity target: the reference's encoder stacks (PaddleNLP BERT
on the framework's nn.TransformerEncoder,
/root/reference/python/paddle/nn/layer/transformer.py). Word+position+
token-type embeddings with LayerNorm, post-LN encoder blocks, pooler,
MLM and sequence-classification heads.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..framework.core import apply
from .. import nn
from ..nn import functional as F

__all__ = ["BertConfig", "BertModel", "BertForMaskedLM",
           "BertForSequenceClassification", "bert_tiny", "bert_base",
           "bert_large"]


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0
    dtype: str = "float32"


class BertEmbeddings(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__(dtype=cfg.dtype)
        self.word_embeddings = nn.Embedding(cfg.vocab_size,
                                            cfg.hidden_size)
        self.position_embeddings = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self.token_type_embeddings = nn.Embedding(cfg.type_vocab_size,
                                                  cfg.hidden_size)
        self.layer_norm = nn.LayerNorm(cfg.hidden_size,
                                       epsilon=cfg.layer_norm_eps)
        self.dropout = cfg.hidden_dropout_prob

    def forward(self, input_ids, token_type_ids=None):
        pos = apply("position_ids",
                    lambda ids: jnp.broadcast_to(
                        jnp.arange(ids.shape[1]), ids.shape), input_ids)
        emb = self.word_embeddings(input_ids) + \
            self.position_embeddings(pos)
        if token_type_ids is not None:
            emb = emb + self.token_type_embeddings(token_type_ids)
        emb = self.layer_norm(emb)
        if self.dropout:
            emb = F.dropout(emb, p=self.dropout, training=self.training)
        return emb


class BertModel(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        self.embeddings = BertEmbeddings(cfg)
        enc_layer = nn.TransformerEncoderLayer(
            cfg.hidden_size, cfg.num_attention_heads,
            cfg.intermediate_size, dropout=cfg.hidden_dropout_prob,
            activation=cfg.hidden_act,
            attn_dropout=cfg.attention_probs_dropout_prob,
            normalize_before=False)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             cfg.num_hidden_layers)
        self.pooler = nn.Linear(cfg.hidden_size, cfg.hidden_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        """Returns (sequence_output [B,S,H], pooled_output [B,H])."""
        h = self.embeddings(input_ids, token_type_ids)
        if attention_mask is not None:
            # [B, S] of 1/0 → additive mask broadcastable to attention
            mask = apply(
                "attn_mask",
                lambda m: (1.0 - m.astype(jnp.float32))[:, None, None, :]
                * -1e9, attention_mask)
        else:
            mask = None
        h = self.encoder(h, src_mask=mask)
        pooled = F.tanh(self.pooler(h[:, 0]))
        return h, pooled


class BertForMaskedLM(nn.Layer):
    def __init__(self, cfg: BertConfig):
        super().__init__(dtype=cfg.dtype)
        self.cfg = cfg
        self.bert = BertModel(cfg)
        self.transform = nn.Linear(cfg.hidden_size, cfg.hidden_size)
        self.transform_ln = nn.LayerNorm(cfg.hidden_size,
                                         epsilon=cfg.layer_norm_eps)
        self.decoder_bias = None  # tied to word embeddings

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        seq, _ = self.bert(input_ids, token_type_ids, attention_mask)
        h = self.transform_ln(F.gelu(self.transform(seq)))
        from ..tensor.linalg import matmul
        return matmul(h, self.bert.embeddings.word_embeddings.weight,
                      transpose_y=True)

    def loss(self, logits, labels, ignore_index: int = -100):
        """MLM loss over positions where labels != ignore_index."""
        v = logits.shape[-1]
        flat_logits = logits.reshape([-1, v])
        flat_labels = labels.reshape([-1])

        def f(lg, lb):
            valid = lb != ignore_index
            lb_safe = jnp.where(valid, lb, 0)
            logp = jax.nn.log_softmax(lg, axis=-1)
            nll = -jnp.take_along_axis(logp, lb_safe[:, None],
                                       axis=1)[:, 0]
            return (nll * valid).sum() / jnp.maximum(valid.sum(), 1)
        import jax
        return apply("mlm_loss", f, flat_logits, flat_labels)


class BertForSequenceClassification(nn.Layer):
    def __init__(self, cfg: BertConfig, num_classes: int = 2):
        super().__init__(dtype=cfg.dtype)
        self.bert = BertModel(cfg)
        self.dropout = cfg.hidden_dropout_prob
        self.classifier = nn.Linear(cfg.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None,
                attention_mask=None):
        _, pooled = self.bert(input_ids, token_type_ids, attention_mask)
        if self.dropout:
            pooled = F.dropout(pooled, p=self.dropout,
                               training=self.training)
        return self.classifier(pooled)

    def loss(self, logits, labels):
        return F.cross_entropy(logits, labels)


def bert_tiny(**kw) -> BertConfig:
    return BertConfig(vocab_size=512, hidden_size=128,
                      num_hidden_layers=2, num_attention_heads=4,
                      intermediate_size=256,
                      max_position_embeddings=128, **kw)


def bert_base(**kw) -> BertConfig:
    return BertConfig(**kw)


def bert_large(**kw) -> BertConfig:
    return BertConfig(hidden_size=1024, num_hidden_layers=24,
                      num_attention_heads=16, intermediate_size=4096,
                      **kw)
