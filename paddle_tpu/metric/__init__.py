"""paddle_tpu.metric — metric parity with the reference
(/root/reference/python/paddle/metric/metrics.py: Metric base, Accuracy,
Precision, Recall, Auc).

TPU-native note: ``compute`` runs in traced/jitted code and stays purely
functional (returns arrays); ``update`` runs on host with concrete numpy
values and mutates Python accumulator state — the same split the reference
draws between graph-side compute and host-side bookkeeping.
"""
from __future__ import annotations

import abc
from typing import List, Optional, Sequence, Union

import numpy as np

from ..framework.core import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


def _to_np(x) -> np.ndarray:
    if isinstance(x, Tensor):
        return x.numpy()
    return np.asarray(x)


class Metric(abc.ABC):
    """Base metric (reference python/paddle/metric/metrics.py:47)."""

    def __init__(self):
        pass

    @abc.abstractmethod
    def reset(self):
        raise NotImplementedError

    @abc.abstractmethod
    def update(self, *args):
        raise NotImplementedError

    @abc.abstractmethod
    def accumulate(self):
        raise NotImplementedError

    @abc.abstractmethod
    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        """Optional graph-side pre-processing: maps (pred, label) to the
        statistics ``update`` consumes. Default: identity pass-through."""
        return args


class Accuracy(Metric):
    """Top-k accuracy (reference metrics.py:178)."""

    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        super().__init__()
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        pred_np = _to_np(pred)
        label_np = _to_np(label)
        # top-maxk indices along the last dim
        idx = np.argsort(-pred_np, axis=-1)[..., : self.maxk]
        if label_np.ndim == pred_np.ndim:
            if label_np.shape[-1] == 1:  # [N, 1] class indices
                label_np = label_np[..., 0]
            else:  # one-hot / soft label
                label_np = np.argmax(label_np, axis=-1)
        correct = (idx == label_np[..., None]).astype(np.float32)
        return correct

    def update(self, correct, *args):
        correct = _to_np(correct)
        accs = []
        num = int(np.prod(correct.shape[:-1]))
        for i, k in enumerate(self.topk):
            c = float(correct[..., :k].sum())
            accs.append(c / max(num, 1))
            self.total[i] += c
            self.count[i] += num
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Binary precision (reference metrics.py:327)."""

    def __init__(self, name="precision", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).reshape(-1)
        labels = _to_np(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fp += int(np.sum(pred_pos & (labels != 1)))

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        ap = self.tp + self.fp
        return self.tp / ap if ap else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    """Binary recall (reference metrics.py:425)."""

    def __init__(self, name="recall", *args, **kwargs):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds).reshape(-1)
        labels = _to_np(labels).reshape(-1)
        pred_pos = preds > 0.5
        self.tp += int(np.sum(pred_pos & (labels == 1)))
        self.fn += int(np.sum(~pred_pos & (labels == 1)))

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        p = self.tp + self.fn
        return self.tp / p if p else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """ROC-AUC via thresholded confusion buckets (reference metrics.py:523)."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc",
                 *args, **kwargs):
        super().__init__()
        self._curve = curve
        self._num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = _to_np(preds)
        labels = _to_np(labels).reshape(-1)
        if preds.ndim == 2:  # [N, 2] class probs -> positive-class prob
            pos_prob = preds[:, -1]
        else:
            pos_prob = preds.reshape(-1)
        bins = np.minimum(
            (pos_prob * self._num_thresholds).astype(np.int64),
            self._num_thresholds)
        pos = labels.astype(bool)
        n = self._num_thresholds + 1
        self._stat_pos += np.bincount(bins[pos], minlength=n)
        self._stat_neg += np.bincount(bins[~pos], minlength=n)

    def reset(self):
        self._stat_pos = np.zeros(self._num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self._num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self._num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2.0
            tot_pos, tot_neg = new_pos, new_neg
        denom = tot_pos * tot_neg
        return float(auc / denom) if denom else 0.0

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional top-k accuracy (reference python/paddle/metric/metrics.py:
    800 ``paddle.metric.accuracy``). Jit-safe: pure jnp/lax."""
    import jax
    import jax.numpy as jnp
    from ..framework.core import to_tensor

    x = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    y = label._value if isinstance(label, Tensor) else jnp.asarray(label)
    if y.ndim == x.ndim:
        if y.shape[-1] == 1:  # [N, 1] class indices
            y = y[..., 0]
        else:  # one-hot / soft label
            y = jnp.argmax(y, axis=-1)
    _, idx = jax.lax.top_k(x, k)
    correct_mask = (idx == y[..., None]).any(axis=-1)
    return to_tensor(jnp.mean(correct_mask.astype(jnp.float32)))
