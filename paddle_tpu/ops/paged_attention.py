"""Paged (block) KV-cache attention — the serving decode path.

Reference: block_multi_head_attention
(/root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention
kernel + python/paddle/incubate/nn/functional/block_multihead_attention.py):
the KV cache lives in fixed-size blocks; a per-sequence block table maps
logical positions to physical blocks, so sequences grow without
reallocation and memory fragments are reclaimed per-block (vLLM-style).

TPU-native: the decode gather is expressed as one jnp.take over the
block axis followed by a flash-style softmax over the gathered window —
XLA lowers the gather efficiently and fuses the rest; everything is
fixed-shape (max_blocks per sequence) so one compiled program serves all
lengths, with masking by context length.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PagedKVCache", "paged_attention_decode", "reshape_and_cache"]


def reshape_and_cache(k, v, k_cache, v_cache, slot_mapping):
    """Scatter this step's K/V ([batch, kv_heads, head_dim]) into the
    block pool at flat slot ids (block_id * block_size + offset).
    Returns updated caches. Cache layout: [num_blocks, block_size,
    kv_heads, head_dim]."""
    nb, bs, h, d = k_cache.shape
    flat_k = k_cache.reshape(nb * bs, h, d)
    flat_v = v_cache.reshape(nb * bs, h, d)
    flat_k = flat_k.at[slot_mapping].set(k)
    flat_v = flat_v.at[slot_mapping].set(v)
    return flat_k.reshape(nb, bs, h, d), flat_v.reshape(nb, bs, h, d)


def paged_attention_decode(q, k_cache, v_cache, block_tables, context_lens,
                           scale: Optional[float] = None):
    """One-token decode attention over the paged cache.

    q:            [batch, num_heads, head_dim]  (this step's query)
    k_cache/v_cache: [num_blocks, block_size, kv_heads, head_dim]
    block_tables: [batch, max_blocks] int32 physical block ids
    context_lens: [batch] int32 — valid tokens per sequence (incl. this)
    Returns [batch, num_heads, head_dim].
    """
    b, nh, d = q.shape
    nb, bs, kvh, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    group = nh // kvh  # GQA: queries per kv head

    # gather each sequence's blocks: [b, max_blocks, bs, kvh, d]
    k = jnp.take(k_cache, block_tables, axis=0)
    v = jnp.take(v_cache, block_tables, axis=0)
    k = k.reshape(b, max_blocks * bs, kvh, d)
    v = v.reshape(b, max_blocks * bs, kvh, d)

    qg = q.reshape(b, kvh, group, d)
    # scores: [b, kvh, group, S]
    scores = jnp.einsum("bkgd,bskd->bkgs", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, None, :]
    mask = pos < context_lens[:, None, None, None]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bskd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, nh, d).astype(q.dtype)


class PagedKVCache:
    """Host-side block allocator + device block pool (the cache manager
    half of the reference's block_multihead_attention serving path).

    One instance per layer set: caches are stacked [num_layers, ...] so a
    decode step updates all layers functionally.
    """

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32):
        self.num_layers = num_layers
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.k = jnp.zeros((num_layers, num_blocks, block_size, kv_heads,
                            head_dim), dtype)
        self.v = jnp.zeros_like(self.k)
        self._free = list(range(num_blocks - 1, -1, -1))
        self._tables: dict = {}   # seq_id → [block ids]
        self._lens: dict = {}     # seq_id → context length

    # -- allocation ---------------------------------------------------------
    def allocate(self, seq_id: int, num_tokens: int):
        """Reserve blocks for a sequence of num_tokens (prefill)."""
        needed = -(-num_tokens // self.block_size)
        if len(self._free) < needed:
            raise RuntimeError(
                f"KV cache exhausted: need {needed} blocks, "
                f"{len(self._free)} free")
        self._tables[seq_id] = [self._free.pop() for _ in range(needed)]
        self._lens[seq_id] = 0
        return self._tables[seq_id]

    def extend(self, seq_id: int):
        """Ensure room for one more token; returns the flat slot id."""
        pos = self._lens[seq_id]
        blocks = self._tables[seq_id]
        if pos >= len(blocks) * self.block_size:
            if not self._free:
                raise RuntimeError("KV cache exhausted on extend")
            blocks.append(self._free.pop())
        self._lens[seq_id] = pos + 1
        block = blocks[pos // self.block_size]
        return block * self.block_size + pos % self.block_size

    def free(self, seq_id: int):
        self._free.extend(reversed(self._tables.pop(seq_id, [])))
        self._lens.pop(seq_id, None)

    def context_len(self, seq_id: int) -> int:
        return self._lens.get(seq_id, 0)

    def block_table(self, seq_id: int, max_blocks: int) -> np.ndarray:
        t = self._tables[seq_id]
        out = np.zeros(max_blocks, np.int32)
        out[:len(t)] = t
        return out

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    # -- device updates -----------------------------------------------------
    def write(self, layer: int, k, v, slot_mapping):
        """Write one step's K/V for `layer` at the given flat slots."""
        nk, nv = reshape_and_cache(k, v, self.k[layer], self.v[layer],
                                   slot_mapping)
        self.k = self.k.at[layer].set(nk)
        self.v = self.v.at[layer].set(nv)
