"""Paged (block) KV-cache attention — the serving decode path.

Reference: block_multi_head_attention
(/root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention
kernel + python/paddle/incubate/nn/functional/block_multihead_attention.py):
the KV cache lives in fixed-size blocks; a per-sequence block table maps
logical positions to physical blocks, so sequences grow without
reallocation and memory fragments are reclaimed per-block (vLLM-style).

TPU-native: on TPU the decode runs a Pallas kernel
(ops/pallas/paged_attention.py) whose K/V BlockSpec index maps consume a
scalar-prefetched block table — each grid step DMAs one physical page
from the HBM pool, no gathered [batch, window, ...] materialization, with
an online-softmax accumulated across pages in VMEM scratch. The jnp.take
composition below is the reference oracle + CPU path; everything is
fixed-shape (max_blocks per sequence) so one compiled program serves all
lengths, with masking by context length.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["PagedKVCache", "paged_attention_decode",
           "paged_attention_decode_reference", "reshape_and_cache"]


def reshape_and_cache(k, v, k_cache, v_cache, slot_mapping):
    """Scatter this step's K/V ([batch, kv_heads, head_dim]) into the
    block pool at flat slot ids (block_id * block_size + offset).
    Returns updated caches. Cache layout: [num_blocks, kv_heads,
    block_size, head_dim] — a physical page is one contiguous
    [kv_heads, block_size, head_dim] region, so the Pallas decode kernel
    fetches a whole page (all kv heads) with a single DMA."""
    nb, h, bs, d = k_cache.shape
    blocks = slot_mapping // bs
    offs = slot_mapping % bs
    heads = jnp.arange(h)[None, :]
    k_cache = k_cache.at[blocks[:, None], heads, offs[:, None]].set(k)
    v_cache = v_cache.at[blocks[:, None], heads, offs[:, None]].set(v)
    return k_cache, v_cache


def paged_attention_decode_reference(q, k_cache, v_cache, block_tables,
                                     context_lens,
                                     scale: Optional[float] = None):
    """One-token decode attention over the paged cache (jnp oracle).

    q:            [batch, num_heads, head_dim]  (this step's query)
    k_cache/v_cache: [num_blocks, kv_heads, block_size, head_dim]
    block_tables: [batch, max_blocks] int32 physical block ids
    context_lens: [batch] int32 — valid tokens per sequence (incl. this)
    Returns [batch, num_heads, head_dim].
    """
    b, nh, d = q.shape
    nb, kvh, bs, _ = k_cache.shape
    max_blocks = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    group = nh // kvh  # GQA: queries per kv head

    # gather each sequence's blocks: [b, max_blocks, kvh, bs, d]
    k = jnp.take(k_cache, block_tables, axis=0)
    v = jnp.take(v_cache, block_tables, axis=0)
    k = k.transpose(0, 2, 1, 3, 4).reshape(b, kvh, max_blocks * bs, d)
    v = v.transpose(0, 2, 1, 3, 4).reshape(b, kvh, max_blocks * bs, d)

    qg = q.reshape(b, kvh, group, d)
    # scores: [b, kvh, group, S]
    scores = jnp.einsum("bkgd,bksd->bkgs", qg.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_blocks * bs)[None, None, None, :]
    mask = pos < context_lens[:, None, None, None]
    scores = jnp.where(mask, scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bkgs,bksd->bkgd", probs, v.astype(jnp.float32))
    return out.reshape(b, nh, d).astype(q.dtype)


class PagedKVCache:
    """Host-side block allocator + device block pool (the cache manager
    half of the reference's block_multihead_attention serving path).

    One instance per layer set: caches are stacked [num_layers, ...] so a
    decode step updates all layers functionally.
    """

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32,
                 kv_sharding=None):
        self.num_layers = num_layers
        self.num_blocks = num_blocks
        self.block_size = block_size
        # per-layer pools as a LIST pytree: updating one layer swaps a
        # list element — no [L, ...] slice/update copies in the compiled
        # decode step. kv_sharding (a NamedSharding over the kv-head
        # dim) places the pool for tensor-parallel serving.
        self.k = [jnp.zeros((num_blocks, kv_heads, block_size, head_dim),
                            dtype) for _ in range(num_layers)]
        self.v = [jnp.zeros_like(self.k[0]) for _ in range(num_layers)]
        if kv_sharding is not None:
            import jax
            self.k = [jax.device_put(a, kv_sharding) for a in self.k]
            self.v = [jax.device_put(a, kv_sharding) for a in self.v]
        self._free = list(range(num_blocks - 1, -1, -1))
        self._tables: dict = {}   # seq_id → [block ids]
        self._lens: dict = {}     # seq_id → context length

    # -- allocation ---------------------------------------------------------
    def allocate(self, seq_id: int, num_tokens: int):
        """Reserve blocks for a sequence of num_tokens (prefill)."""
        needed = -(-num_tokens // self.block_size)
        if len(self._free) < needed:
            raise RuntimeError(
                f"KV cache exhausted: need {needed} blocks, "
                f"{len(self._free)} free")
        self._tables[seq_id] = [self._free.pop() for _ in range(needed)]
        self._lens[seq_id] = 0
        return self._tables[seq_id]

    def extend(self, seq_id: int):
        """Ensure room for one more token; returns the flat slot id."""
        pos = self._lens[seq_id]
        blocks = self._tables[seq_id]
        if pos >= len(blocks) * self.block_size:
            if not self._free:
                raise RuntimeError("KV cache exhausted on extend")
            blocks.append(self._free.pop())
        self._lens[seq_id] = pos + 1
        block = blocks[pos // self.block_size]
        return block * self.block_size + pos % self.block_size

    def free(self, seq_id: int):
        self._free.extend(reversed(self._tables.pop(seq_id, [])))
        self._lens.pop(seq_id, None)

    def context_len(self, seq_id: int) -> int:
        return self._lens.get(seq_id, 0)

    def block_table(self, seq_id: int, max_blocks: int) -> np.ndarray:
        t = self._tables[seq_id]
        out = np.zeros(max_blocks, np.int32)
        out[:len(t)] = t
        return out

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    # -- device updates -----------------------------------------------------
    def write(self, layer: int, k, v, slot_mapping):
        """Write one step's K/V for `layer` at the given flat slots."""
        nk, nv = reshape_and_cache(k, v, self.k[layer], self.v[layer],
                                   slot_mapping)
        self.k[layer] = nk
        self.v[layer] = nv


def _pallas_decode_ok(q, k_cache):
    if jax.default_backend() in ("cpu", "gpu"):
        return False
    from ..utils.flags import FLAGS
    if not getattr(FLAGS, "use_pallas_kernels", True):
        return False
    d = q.shape[-1]
    bs = k_cache.shape[2]   # layout [num_blocks, kv_heads, block_size, d]
    return d in (64, 128, 256) and bs % 8 == 0


def paged_attention_decode(q, k_cache, v_cache, block_tables, context_lens,
                           scale: Optional[float] = None):
    """One-token decode attention over the paged cache; Pallas
    scalar-prefetch kernel on TPU, jnp reference elsewhere. See
    paged_attention_decode_reference for the signature."""
    if _pallas_decode_ok(q, k_cache):
        from .pallas.paged_attention import paged_attention_decode_pallas
        return paged_attention_decode_pallas(q, k_cache, v_cache,
                                             block_tables, context_lens,
                                             scale)
    return paged_attention_decode_reference(q, k_cache, v_cache,
                                            block_tables, context_lens,
                                            scale)
