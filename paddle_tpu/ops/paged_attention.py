"""Paged (block) KV-cache attention — the serving decode path.

Reference: block_multi_head_attention
(/root/reference/paddle/phi/kernels/fusion/gpu/block_multi_head_attention
kernel + python/paddle/incubate/nn/functional/block_multihead_attention.py):
the KV cache lives in fixed-size blocks; a per-sequence block table maps
logical positions to physical blocks, so sequences grow without
reallocation and memory fragments are reclaimed per-block (vLLM-style).

TPU-native: on TPU the decode runs a Pallas kernel
(ops/pallas/paged_attention.py) whose K/V BlockSpec index maps consume a
scalar-prefetched block table — each grid step DMAs one physical page
from the HBM pool, no gathered [batch, window, ...] materialization, with
an online-softmax accumulated across pages in VMEM scratch. The jnp.take
composition below is the reference oracle + CPU path; everything is
fixed-shape (max_blocks per sequence) so one compiled program serves all
lengths, with masking by context length.
"""
from __future__ import annotations

from collections import Counter, OrderedDict
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["KVCacheExhausted", "PagedKVCache", "paged_attention_decode",
           "paged_attention_decode_reference", "quantize_kv_rows",
           "ragged_paged_attention", "ragged_paged_attention_reference",
           "reshape_and_cache"]


# ---------------------------------------------------------------------------
# Quantized KV pool (ISSUE 13): a pool plane is either a dense array
# [num_blocks, kv_heads, block_size, head_dim] (fp32/bf16 — the
# original layout, bitwise unchanged) or an (int8 values, f32 scales)
# TUPLE with the scales in a per-slot-per-kv-head sidecar plane
# [num_blocks, kv_heads, block_size] — one absmax scale per written
# K/V row per head, living inside the page so the Pallas kernel's
# per-physical-page DMA fetches values + scales together. The tuple
# rides every existing pytree path (jit args, donation, shard_map
# specs, lax.scan carries) without new plumbing: quantize is fused
# into reshape_and_cache (the only pool write), dequant into the
# attention gathers (the only pool reads).
# ---------------------------------------------------------------------------

def _plane_values(plane):
    """The value array of a pool plane (tuple-aware)."""
    return plane[0] if isinstance(plane, tuple) else plane


def quantize_kv_rows(x):
    """Per-row-per-kv-head symmetric absmax int8 for a K/V append
    batch ``x`` [n, kv_heads, head_dim] (same math as the weight
    quantizer _quantize_w, but over the head_dim axis — each written
    slot carries its own scale, so appending never re-scales already
    written tokens and a page mixes tokens of any magnitude).
    Returns (int8 [n, kv_heads, head_dim], f32 scales [n, kv_heads])."""
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / 127.0
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127) \
        .astype(jnp.int8)
    return q, scale


def _dequantize_gather(plane, idx):
    """jnp.take over a pool plane's leading (page) axis with dequant
    fused at the gather: tuple planes come back as f32
    values * per-slot scales, dense planes gather as-is.

    mode="clip": unused table slots hold sentinel page ids; the
    default out-of-bounds mode fills float gathers with NaN, which a
    downstream mask multiplies to NaN, not zero. Clipped reads land on
    a real page and the per-position mask discards them."""
    if isinstance(plane, tuple):
        vals, scales = plane
        return jnp.take(vals, idx, axis=0, mode="clip") \
            .astype(jnp.float32) \
            * jnp.take(scales, idx, axis=0, mode="clip")[..., None]
    return jnp.take(plane, idx, axis=0, mode="clip")


class KVCacheExhausted(RuntimeError):
    """The block pool cannot satisfy an allocation — free list dry and
    nothing evictable. A RuntimeError subclass so pre-existing callers
    catching RuntimeError keep working; the ServingEngine catches THIS
    type specifically to trigger preemption-with-recompute instead of
    failing the request. The chaos harness (utils/chaos.py) raises it
    from the allocator fault hook to simulate pool pressure."""


def reshape_and_cache(k, v, k_cache, v_cache, slot_mapping):
    """Scatter this step's K/V ([batch, kv_heads, head_dim]) into the
    block pool at flat slot ids (block_id * block_size + offset).
    Returns updated caches. Cache layout: [num_blocks, kv_heads,
    block_size, head_dim] — a physical page is one contiguous
    [kv_heads, block_size, head_dim] region, so the Pallas decode kernel
    fetches a whole page (all kv heads) with a single DMA.

    Quantized pools (kv_quant="int8"): a cache passed as an
    (int8 values, f32 scales) tuple gets the QUANTIZE FUSED INTO THE
    APPEND — per-row-per-kv-head absmax int8 plus a scale scatter into
    the sidecar plane, one functional update each, no fp32 staging
    copy of the pool. Under tp the per-shard kv-head slice quantizes
    its own heads with its own scales, so the append path stays at
    zero collectives on the quantized layout too."""
    if isinstance(k_cache, tuple):
        kc, kcs = k_cache
        vc, vcs = v_cache
        nb, h, bs, d = kc.shape
        blocks = slot_mapping // bs
        offs = slot_mapping % bs
        heads = jnp.arange(h)[None, :]
        kq, ks = quantize_kv_rows(k)
        vq, vs = quantize_kv_rows(v)
        kc = kc.at[blocks[:, None], heads, offs[:, None]].set(kq)
        kcs = kcs.at[blocks[:, None], heads, offs[:, None]].set(ks)
        vc = vc.at[blocks[:, None], heads, offs[:, None]].set(vq)
        vcs = vcs.at[blocks[:, None], heads, offs[:, None]].set(vs)
        return (kc, kcs), (vc, vcs)
    nb, h, bs, d = k_cache.shape
    blocks = slot_mapping // bs
    offs = slot_mapping % bs
    heads = jnp.arange(h)[None, :]
    k_cache = k_cache.at[blocks[:, None], heads, offs[:, None]].set(k)
    v_cache = v_cache.at[blocks[:, None], heads, offs[:, None]].set(v)
    return k_cache, v_cache


def paged_attention_decode_reference(q, k_cache, v_cache, block_tables,
                                     context_lens,
                                     scale: Optional[float] = None):
    """One-token decode attention over the paged cache (jnp oracle).

    q:            [batch, num_heads, head_dim]  (this step's query)
    k_cache/v_cache: [num_blocks, kv_heads, block_size, head_dim]
                  (or (int8, scales) tuples — dequant at the gather)
    block_tables: [batch, max_blocks] int32 physical block ids
    context_lens: [batch] int32 — valid tokens per sequence (incl. this)
    Returns [batch, num_heads, head_dim].
    """
    # A pure decode batch is the ragged program with one row per
    # sequence and the identity row->table mapping; delegating reuses
    # the online-softmax page walk, so the dense oracle no longer
    # materializes every row's whole [max_blocks * bs] K/V (the flat
    # _dequantize_gather this function used to do — FC701's
    # pool-traffic class; a decode row always has context_lens >= 1,
    # so the refs agree everywhere the dense path is defined).
    b = q.shape[0]
    return ragged_paged_attention_reference(
        q, k_cache, v_cache, block_tables,
        jnp.arange(b, dtype=jnp.int32), context_lens, scale)


def ragged_paged_attention_reference(q, k_cache, v_cache, block_tables,
                                     row_seq, row_ctx,
                                     scale: Optional[float] = None):
    """Ragged mixed prefill+decode attention over the paged cache (jnp
    oracle + CPU path).

    One call covers a FLATTENED token batch mixing rows from many
    sequences — decode rows (one token of a running sequence) and
    prefill-chunk rows (consecutive prompt positions of a prefilling
    sequence) side by side, no [max_batch] padding:

    q:            [total_rows, num_heads, head_dim]
    k_cache/v_cache: [num_blocks, kv_heads, block_size, head_dim]
    block_tables: [num_seqs, max_pages] int32 physical page ids
    row_seq:      [total_rows] int32 — which table row each q row reads
    row_ctx:      [total_rows] int32 — keys VISIBLE to the row: pool
                  positions < row_ctx attend (the row's own K/V is
                  already in the pool, so a decode row passes ctx+1 and
                  chunk row j of a prefill at offset `off` passes
                  off+j+1 — that per-row bound IS the causal mask
                  between same-sequence rows of one call; speculative
                  DRAFT row i of a verify window rides the same
                  contract at ctx+i+1, so it sees the context, the
                  column's carried token, drafts 0..i-1 and itself —
                  never a later draft)
    Rows with row_ctx <= 0 (grid padding) return exact zeros.
    Quantized pools ((int8, scales) tuples) dequantize INSIDE the page
    walk — the per-page gather fetches values + sidecar scales and
    multiplies before the score matmul, exactly the Pallas kernel's
    fused per-page-DMA dequant, so the oracle stays the kernel's
    ground truth on the int8 layout too.
    Returns [total_rows, num_heads, head_dim].
    """
    r, nh, d = q.shape
    nb, kvh, bs, _ = _plane_values(k_cache).shape
    max_pages = block_tables.shape[1]
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    group = nh // kvh

    tables_r = jnp.take(block_tables, row_seq, axis=0)   # [r, P]
    qg = q.reshape(r, kvh, group, d).astype(jnp.float32)
    ctx = row_ctx[:, None, None, None]

    # ONLINE softmax over a page walk — the kernel's structure, not
    # just its math: a one-page-per-iteration gather keeps peak memory
    # at [r, kvh, bs, d] instead of materializing every row's whole
    # [max_pages * bs] K/V (a wide idle-drain ragged program has
    # hundreds of rows — a flat gather is gigabytes of traffic per
    # layer on the CPU path), and the trip count is bounded by the
    # batch's LONGEST visible context, not the table width (the
    # kernel's n_pages bound; a traced fori_loop limit, so short-row
    # batches in a long-bucket table skip the empty tail). Masking is
    # per position (pos < row_ctx); fully-masked rows keep l == 0 and
    # come out EXACTLY zero below, matching the Pallas kernel's guard,
    # instead of averaging V over a uniform distribution.
    def page_step(p, carry):
        m_prev, l_prev, acc = carry
        pids = jnp.take(tables_r, p, axis=1)             # [r]
        k = _dequantize_gather(k_cache, pids).astype(jnp.float32)
        v = _dequantize_gather(v_cache, pids)            # [r, kvh, bs, d]
        sc = jnp.einsum("rkgd,rksd->rkgs", qg, k) * scale
        pos = p * bs + jnp.arange(bs)[None, None, None, :]
        mask = pos < ctx
        sc = jnp.where(mask, sc, -1e30)
        m_new = jnp.maximum(m_prev, jnp.max(sc, axis=-1))
        prob = jnp.where(mask, jnp.exp(sc - m_new[..., None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(prob, axis=-1)
        acc = acc * corr[..., None] + jnp.einsum(
            "rkgs,rksd->rkgd", prob, v.astype(jnp.float32))
        return m_new, l_new, acc

    n_pages = jnp.minimum((jnp.max(row_ctx) + bs - 1) // bs, max_pages)
    m, l, acc = jax.lax.fori_loop(
        0, n_pages, page_step,
        (jnp.full((r, kvh, group), -1e30, jnp.float32),
         jnp.zeros((r, kvh, group), jnp.float32),
         jnp.zeros((r, kvh, group, d), jnp.float32)))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.reshape(r, nh, d).astype(q.dtype)


def ragged_paged_attention(q, k_cache, v_cache, block_tables, row_seq,
                           row_ctx, scale: Optional[float] = None):
    """Ragged mixed prefill+decode attention; Pallas scalar-prefetch
    kernel on TPU, jnp oracle elsewhere (CPU, or
    FLAGS.use_pallas_kernels=False). Kernel eligibility is the decode
    kernel's policy — same pool layout, same tiling constraints.
    Quantized pools ((int8, scales) tuples) route to the kernel too:
    the sidecar scales ride each page's DMA and dequant happens in
    VMEM (see pallas/ragged_paged_attention.py)."""
    if _pallas_decode_ok(q, k_cache):
        from .pallas.ragged_paged_attention import \
            ragged_paged_attention_pallas
        return ragged_paged_attention_pallas(q, k_cache, v_cache,
                                             block_tables, row_seq,
                                             row_ctx, scale)
    return ragged_paged_attention_reference(q, k_cache, v_cache,
                                            block_tables, row_seq,
                                            row_ctx, scale)


class PagedKVCache:
    """Host-side block allocator + device block pool (the cache manager
    half of the reference's block_multihead_attention serving path).

    One instance per layer set: caches are stacked [num_layers, ...] so a
    decode step updates all layers functionally.

    Automatic prefix caching (vLLM-style): every block carries a ref
    count, and FULL blocks whose token content is known get a chain hash
    ``hash(parent_hash, block_tokens)`` registered in a hash→block
    index. Because full blocks are immutable once written, a new request
    whose prompt shares a block-aligned prefix with previously seen
    content can splice the physical blocks into its table
    (``allocate_with_prefix``) instead of re-prefilling — a ref-count
    bump, no copy. Freed blocks that still carry a valid hash are PARKED
    in an LRU of cached-but-unreferenced blocks rather than zeroed; they
    are only truly evicted (hash invalidated) when the free list runs
    dry, so hot prefixes survive across requests at zero capacity cost.
    """

    def __init__(self, num_layers: int, num_blocks: int, block_size: int,
                 kv_heads: int, head_dim: int, dtype=jnp.float32,
                 kv_sharding=None, kv_quant=None,
                 kv_scale_sharding=None):
        if kv_quant not in (None, "int8"):
            raise ValueError(
                f"kv_quant must be None or 'int8', got {kv_quant!r}")
        self.num_layers = num_layers
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.kv_quant = kv_quant
        # per-layer pools as a LIST pytree: updating one layer swaps a
        # list element — no [L, ...] slice/update copies in the compiled
        # decode step. kv_sharding (a NamedSharding over the kv-head
        # dim) places the pool for tensor-parallel serving.
        # kv_quant="int8" (ISSUE 13): each plane becomes an
        # (int8 values, f32 scales) tuple — values keep the page
        # layout, scales live in a per-slot-per-kv-head sidecar
        # [num_blocks, kv_heads, block_size] whose kv-head dim shards
        # EXACTLY like the values' (kv_scale_sharding; the canonical
        # cache_k_scale spec), so tp adds zero collectives. All-zero
        # init matches the dense pools' zeros bit-for-bit (0 * 0 = 0).
        if kv_quant == "int8":
            def _plane():
                return (jnp.zeros((num_blocks, kv_heads, block_size,
                                   head_dim), jnp.int8),
                        jnp.zeros((num_blocks, kv_heads, block_size),
                                  jnp.float32))
        else:
            def _plane():
                return jnp.zeros((num_blocks, kv_heads, block_size,
                                  head_dim), dtype)
        self.k = [_plane() for _ in range(num_layers)]
        self.v = [_plane() for _ in range(num_layers)]
        if kv_sharding is not None:
            import jax
            if kv_quant == "int8":
                if kv_scale_sharding is None:
                    raise ValueError(
                        "a sharded int8 pool needs kv_scale_sharding "
                        "(the sidecar scales must shard with their kv "
                        "heads, or every read pays an implicit gather)")

                def _put(plane):
                    return (jax.device_put(plane[0], kv_sharding),
                            jax.device_put(plane[1], kv_scale_sharding))
            else:
                def _put(plane):
                    return jax.device_put(plane, kv_sharding)
            self.k = [_put(a) for a in self.k]
            self.v = [_put(a) for a in self.v]
        self._free = list(range(num_blocks - 1, -1, -1))
        self._tables: dict = {}   # seq_id → [block ids]
        self._lens: dict = {}     # seq_id → context length
        self._ref: dict = {}      # block → ref count (present iff > 0)
        # prefix-cache index: chain hash ↔ physical block, plus the LRU
        # of cached-but-unreferenced blocks (insertion order = park
        # order; oldest evicted first when the free list runs dry)
        self._hash_of: dict = {}        # block → chain hash
        self._block_of: dict = {}       # chain hash → block
        self._lru: OrderedDict = OrderedDict()   # block → None
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0
        self.prefix_evictions = 0
        # optional fault-injection hook (utils/chaos.py): called at the
        # top of every _take_block, BEFORE any mutation, so an injected
        # KVCacheExhausted leaves the pool untouched
        self.fault_hook = None
        # optional telemetry tracer (utils/telemetry.py; ISSUE 12):
        # alloc/evict/splice/rollback land as flight-recorder events.
        # Attached by ServingEngine.set_telemetry; trace_pid is the
        # owning engine's replica id. None = zero-overhead no-op.
        self.tracer = None
        self.trace_pid = 0
        # optional LoRA adapter plane (ISSUE 10): a [num_blocks,
        # page_elems] f32 device array sharing THIS allocator's block
        # ids — a block either holds KV (rows of self.k/self.v) or an
        # adapter page (its row here); ownership is whatever the
        # ref-count says. None until enable_lora_pool.
        self.lora_pool = None
        self.lora_page_elems = 0

    # -- allocation ---------------------------------------------------------
    def _take_block(self) -> int:
        """Pop a writable block: the free list first, then (free list
        dry) evict the least-recently-parked cached block, invalidating
        its hash so it can never be spliced again."""
        if self.fault_hook is not None:
            self.fault_hook()
        if self._free:
            return self._free.pop()
        if self._lru:
            blk, _ = self._lru.popitem(last=False)
            h = self._hash_of.pop(blk)
            self._block_of.pop(h, None)
            self.prefix_evictions += 1
            if self.tracer is not None:
                self.tracer.event("kv_evict", pid=self.trace_pid,
                                  block=int(blk))
            return blk
        raise KVCacheExhausted("KV cache exhausted")

    def _take_blocks(self, n: int) -> List[int]:
        """Pop n blocks TRANSACTIONALLY: a mid-loop failure (free list
        drained between the capacity check and the take — only possible
        via an injected allocator fault) returns the already-taken
        blocks to the free list before re-raising, so no block is ever
        stranded outside the three pools."""
        taken: List[int] = []
        try:
            for _ in range(n):
                taken.append(self._take_block())
        except RuntimeError:
            self._free.extend(taken)
            raise
        return taken

    def allocate(self, seq_id: int, num_tokens: int):
        """Reserve blocks for a sequence of num_tokens (prefill)."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id} already allocated")
        needed = -(-num_tokens // self.block_size)
        if self.available_blocks < needed:
            raise KVCacheExhausted(
                f"KV cache exhausted: need {needed} blocks, "
                f"{self.available_blocks} free")
        blocks = self._take_blocks(needed)
        for b in blocks:
            self._ref[b] = 1
        self._tables[seq_id] = blocks
        self._lens[seq_id] = 0
        if self.tracer is not None:
            self.tracer.event("kv_alloc", pid=self.trace_pid,
                              seq=int(seq_id), blocks=int(needed),
                              dtype=self.pool_dtype)
        return self._tables[seq_id]

    # -- prefix caching ------------------------------------------------------
    def _chain_hashes(self, tokens, salt=None) -> List[int]:
        """Chain hash per FULL block of `tokens`:
        h_i = hash(h_{i-1}, tokens[i*bs:(i+1)*bs]); the chain makes a
        block's identity cover its whole prefix, so equal hashes mean
        equal content AND equal position history. ``salt`` seeds the
        chain root (multi-tenant serving passes the request's adapter
        id): equal prompts under different salts hash to disjoint
        chains, so prefix splices can never cross tenants — a block
        prefilled through adapter X holds X's K/V, which is junk to
        any other adapter's attention. salt=None (the default) keeps
        the original chain values bit-for-bit."""
        bs = self.block_size
        toks = [int(t) for t in tokens]
        out: List[int] = []
        h = None if salt is None else ("#tenant", salt)
        for i in range(len(toks) // bs):
            h = hash((h, tuple(toks[i * bs:(i + 1) * bs])))
            out.append(h)
        return out

    def _match(self, hashes: List[int],
               n_tokens: int) -> List[Tuple[int, int]]:
        matched: List[Tuple[int, int]] = []
        for h in hashes:
            blk = self._block_of.get(h)
            if blk is None:
                break
            matched.append((h, blk))
        if matched and len(matched) * self.block_size >= n_tokens:
            matched.pop()
        return matched

    def match_prefix(self, tokens, salt=None) -> List[Tuple[int, int]]:
        """Longest chain of already-cached full blocks covering a
        prefix of `tokens` — [(hash, block)], non-mutating. Capped so at
        least one token is left uncovered: the caller always prefills a
        non-empty suffix (the last position's logits must be computed).
        ``salt`` namespaces the chain (see _chain_hashes)."""
        return self._match(self._chain_hashes(tokens, salt),
                           len(tokens))

    def _prefix_capacity(self, matched, num_tokens: int):
        """(fresh blocks needed, blocks claimable) for an allocation
        splicing `matched`: matched blocks cost nothing (ref bump), and
        cached blocks not part of the match are evictable on demand."""
        needed = -(-num_tokens // self.block_size) - len(matched)
        evictable = len(self._lru) - sum(1 for _, b in matched
                                         if b in self._lru)
        return needed, len(self._free) + evictable

    def can_allocate_with_prefix(self, tokens, num_tokens: int,
                                 salt=None) -> bool:
        """Worst-case admission check that credits reusable blocks."""
        needed, avail = self._prefix_capacity(
            self.match_prefix(tokens, salt), num_tokens)
        return avail >= needed

    def allocate_with_prefix(self, seq_id: int, tokens,
                             num_tokens: Optional[int] = None,
                             salt=None):
        """Reserve blocks for a prompt of `tokens` (worst-case capacity
        `num_tokens` ≥ len(tokens)), splicing in every cached block of
        the longest matching block-aligned prefix (ref++, no copy).
        Returns (reused_blocks, n_cached_tokens); the sequence's context
        length starts at n_cached_tokens, so `extend` hands out slots
        for the uncovered suffix only. The suffix's own full prompt
        blocks are registered in the hash index immediately — their
        content is fully determined by the prompt, so later requests may
        splice them as soon as the owning prefill has been dispatched
        (dispatch ordering is the caller's job; see ServingEngine's
        admission waves)."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id} already allocated")
        n_tok = len(tokens) if num_tokens is None else int(num_tokens)
        hashes = self._chain_hashes(tokens, salt)
        matched = self._match(hashes, len(tokens))
        needed_new, avail = self._prefix_capacity(matched, n_tok)
        if avail < needed_new:
            raise KVCacheExhausted(
                f"KV cache exhausted: need {needed_new} blocks, "
                f"{avail} free")
        reused = []
        for _, blk in matched:          # revive/ref BEFORE taking fresh
            self._lru.pop(blk, None)    # blocks so eviction can't steal
            self._ref[blk] = self._ref.get(blk, 0) + 1   # a matched one
            reused.append(blk)
        try:
            fresh = self._take_blocks(needed_new)
        except RuntimeError:
            # injected fault mid-take: undo the revive so the matched
            # blocks return to ref-0 parked state and the pool invariant
            # holds (the refusal must leave the pool unchanged)
            for blk in reused:
                self._ref[blk] -= 1
                if self._ref[blk] == 0:
                    del self._ref[blk]
                    self._lru[blk] = None
            raise
        for b in fresh:
            self._ref[b] = 1
        table = reused + fresh
        self._tables[seq_id] = table
        n_cached = len(reused) * self.block_size
        self._lens[seq_id] = n_cached
        self.prefix_query_tokens += len(tokens)
        self.prefix_hit_tokens += n_cached
        if self.tracer is not None:
            self.tracer.event("kv_alloc", pid=self.trace_pid,
                              seq=int(seq_id), blocks=int(needed_new),
                              spliced=len(reused),
                              dtype=self.pool_dtype)
            if reused:
                self.tracer.event(
                    "kv_splice", pid=self.trace_pid, seq=int(seq_id),
                    blocks=len(reused), tokens=int(n_cached))
        # register the suffix's full prompt blocks for future reuse
        for i in range(len(reused), len(hashes)):
            h, b = hashes[i], table[i]
            if h not in self._block_of and b not in self._hash_of:
                self._block_of[h] = b
                self._hash_of[b] = h
        return reused, n_cached

    def clear_prefix_cache(self):
        """Drop every cached (unreferenced) block back to the free list
        and forget all hashes — e.g. between warmup phases so throwaway
        traffic cannot splice into real requests' programs."""
        for blk in self._lru:
            self._free.append(blk)
        self._lru.clear()
        self._hash_of.clear()
        self._block_of.clear()

    def reset_prefix_stats(self):
        self.prefix_hit_tokens = 0
        self.prefix_query_tokens = 0
        self.prefix_evictions = 0

    def unregister_block_hashes(self, blocks):
        """Invalidate the hash registrations of `blocks` — used when a
        prefill is unwound (cancel / failure / preemption) before the
        dispatch covering those blocks was issued: their registered
        content will never be written, so they must not be spliceable.
        Only registrations actually pointing at the block are removed
        (another request may have re-registered the same hash onto a
        different block). No-op for unhashed blocks."""
        for b in blocks:
            h = self._hash_of.get(b)
            if h is not None and self._block_of.get(h) == b:
                del self._hash_of[b]
                del self._block_of[h]
                if b in self._lru:
                    # a parked block losing its hash is no longer
                    # spliceable — return it to the free list (cached
                    # blocks must all be hash-registered)
                    del self._lru[b]
                    self._free.append(b)

    # -- LoRA adapter paging (ISSUE 10; see inference/lora.py) --------------
    def enable_lora_pool(self, page_elems: int, sharding=None):
        """Attach the adapter-page plane: [num_blocks, page_elems]
        f32, zero-initialized (the scratch block's row stays zero
        forever — it IS the null adapter every base-only row reads).
        ``sharding`` replicates the plane over a tp mesh. Idempotent
        for a matching page size; a mismatch raises (two registries
        with different layouts cannot share one pool)."""
        if self.lora_pool is not None:
            if self.lora_page_elems != int(page_elems):
                raise ValueError(
                    f"lora pool already enabled with page_elems="
                    f"{self.lora_page_elems}, got {page_elems}")
            return
        self.lora_page_elems = int(page_elems)
        pool = jnp.zeros((self.num_blocks, self.lora_page_elems),
                         jnp.float32)
        if sharding is not None:
            import jax
            pool = jax.device_put(pool, sharding)
        self.lora_pool = pool

    def write_lora_pages(self, blocks: List[int], pages):
        """Upload host page data ([n, page_elems]) into the plane rows
        of ``blocks`` — the adapter fault-in path. Functional scatter:
        the plane is never donated, so a retried upload is safe."""
        idx = jnp.asarray(np.asarray(blocks, np.int32))
        self.lora_pool = self.lora_pool.at[idx].set(
            jnp.asarray(np.asarray(pages, np.float32)))

    def lookup_hash(self, h) -> Optional[int]:
        """The block currently registered under chain hash ``h`` (KV
        prefix or synthetic adapter-page hash), else None."""
        return self._block_of.get(h)

    def register_page_hashes(self, blocks: List[int], hashes):
        """Register synthetic hashes onto referenced blocks (adapter
        fault-in): when the owning pseudo-sequence later frees, the
        pages PARK in the cached-LRU instead of dropping to the free
        list — resident-but-cold, revivable via adopt_cached_blocks,
        evictable by anyone. Skips hashes/blocks already taken (same
        contract as the prompt-suffix registration path)."""
        for b, h in zip(blocks, hashes):
            if h not in self._block_of and b not in self._hash_of:
                self._block_of[h] = b
                self._hash_of[b] = h

    def adopt_cached_blocks(self, seq_id: int, blocks: List[int]):
        """Claim PARKED (cached, ref-0) blocks as ``seq_id``'s table —
        the adapter-revival fast path (a cold adapter's pages come
        straight back out of the LRU; no upload, no allocation).
        All-or-nothing: every block must currently be parked."""
        if seq_id in self._tables:
            raise ValueError(f"seq {seq_id} already allocated")
        for b in blocks:
            if b not in self._lru:
                raise KeyError(f"block {b} is not parked in the "
                               f"cached-LRU")
        for b in blocks:
            del self._lru[b]
            self._ref[b] = 1
        self._tables[seq_id] = list(blocks)
        self._lens[seq_id] = 0
        return self._tables[seq_id]

    def extend(self, seq_id: int):
        """Ensure room for one more token; returns the flat slot id."""
        pos = self._lens[seq_id]
        blocks = self._tables[seq_id]
        if pos >= len(blocks) * self.block_size:
            if self.available_blocks == 0:
                raise KVCacheExhausted("KV cache exhausted on extend")
            blk = self._take_block()
            self._ref[blk] = 1
            blocks.append(blk)
        self._lens[seq_id] = pos + 1
        block = blocks[pos // self.block_size]
        return block * self.block_size + pos % self.block_size

    def rollback(self, seq_id: int, new_len: int,
                 min_blocks: int = 0):
        """Roll a live sequence's context length BACK to ``new_len`` —
        the speculative-decoding unwind: slots handed out (via extend)
        for draft tokens past the accepted prefix are rescinded, so the
        next extend re-issues them and overwrites the rejected tail's
        K/V. Slots between new_len and the old length are masked by
        every reader until then (attention visibility is bounded by
        context length), so the junk they hold is unreachable.

        Blocks now WHOLLY past the new length leave the table (ref--;
        at ref 0 they return straight to the free list, never the
        cached-LRU — their content was never valid) and any hash
        registration pointing at them is invalidated (a block that held
        rejected drafts must not be spliceable). ``min_blocks`` FLOORS
        the truncation: the caller passes the table length from before
        its speculative extends, so only blocks those extends appended
        are ever dropped — an up-front worst-case admission
        reservation (whose tail the sequence has not reached yet) must
        survive every rollback, or the "a running request can never
        exhaust the pool" guarantee silently dies. Shared (ref > 1)
        blocks cannot appear in the dropped tail in practice — splices
        cover prompt prefixes, and speculative slots are past the whole
        emitted history — but the ref discipline handles them anyway.
        """
        blocks = self._tables[seq_id]
        cur = self._lens[seq_id]
        new_len = int(new_len)
        if not 0 <= new_len <= cur:
            raise ValueError(
                f"rollback(seq {seq_id}) to {new_len} outside "
                f"[0, {cur}]")
        keep = max(1, -(-new_len // self.block_size), int(min_blocks))
        dropped = blocks[keep:]
        del blocks[keep:]
        self._lens[seq_id] = new_len
        returned = []
        for b in reversed(dropped):
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                h = self._hash_of.pop(b, None)
                if h is not None:
                    self._block_of.pop(h, None)
                returned.append(b)
        self._free.extend(returned)
        if self.tracer is not None:
            self.tracer.event(
                "kv_rollback", pid=self.trace_pid, seq=int(seq_id),
                new_len=new_len, dropped=len(dropped))

    def free(self, seq_id: int):
        """Release a sequence: ref-- on each of its blocks; blocks
        reaching ref 0 are parked in the cached-LRU when they carry a
        valid hash (contents stay reusable) or returned to the free
        list otherwise. A no-op for unknown / already-freed seq_ids —
        a double free must not decrement someone else's refs."""
        blocks = self._tables.pop(seq_id, None)
        self._lens.pop(seq_id, None)
        if blocks is None:
            return
        returned = []
        # park LEAF-first: eviction pops oldest-parked, and a chain dies
        # from its head — parking the head last keeps the hot prefix
        # matchable longest (evicting a head orphans every descendant)
        for b in reversed(blocks):
            self._ref[b] -= 1
            if self._ref[b] == 0:
                del self._ref[b]
                if b in self._hash_of:
                    self._lru[b] = None      # park: newest at the end
                else:
                    returned.append(b)
        self._free.extend(returned)

    def context_len(self, seq_id: int) -> int:
        return self._lens.get(seq_id, 0)

    def block_table(self, seq_id: int, max_blocks: int) -> np.ndarray:
        t = self._tables[seq_id]
        out = np.zeros(max_blocks, np.int32)
        out[:len(t)] = t
        return out

    def seq_blocks(self, seq_id: int) -> List[int]:
        """The sequence's physical block list (read-only view)."""
        return list(self._tables[seq_id])

    # -- pool-footprint introspection (ISSUE 13) ----------------------------
    @property
    def pool_dtype(self) -> str:
        """The pool's storage dtype as stats()/telemetry report it:
        'int8' for the quantized layout, else the plane dtype name."""
        if self.kv_quant == "int8":
            return "int8"
        return str(np.dtype(_plane_values(self.k[0]).dtype))

    def pool_bytes(self) -> int:
        """Total device bytes of the K/V planes (sidecar scales
        included) — the logical (global, unsharded) footprint."""
        total = 0
        for plane in list(self.k) + list(self.v):
            leaves = plane if isinstance(plane, tuple) else (plane,)
            for a in leaves:
                total += int(np.prod(a.shape, dtype=np.int64)
                             * np.dtype(a.dtype).itemsize)
        return total

    def bytes_per_token(self) -> float:
        """KV bytes one token slot costs across all layers (k + v,
        scales included) — pool_bytes over the pool's slot count; the
        capacity headline kv_quant halves."""
        return self.pool_bytes() / float(self.num_blocks
                                         * self.block_size)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    @property
    def cached_blocks(self) -> int:
        """Blocks parked in the prefix-cache LRU (reusable, evictable)."""
        return len(self._lru)

    @property
    def available_blocks(self) -> int:
        """Blocks a fresh allocation can claim: free + evictable."""
        return len(self._free) + len(self._lru)

    def debug_check(self):
        """Pool invariant: free + cached + referenced == num_blocks,
        the three sets disjoint, table refs exactly matching the ref
        counts (no leak, no double free), the hash index a bijection
        with every cached block hash-registered, and every live
        sequence's context length inside its table's capacity —
        PARTIALLY-PREFILLED sequences included (a chunked prefill
        extends its length over several scheduler steps; between any
        two chunks the length must sit within the blocks reserved at
        admission and never go negative). Raises AssertionError on
        violation; cheap enough to run after every scheduler step in
        tests."""
        free = set(self._free)
        cached = set(self._lru)
        referenced = set(self._ref)
        assert len(free) == len(self._free), "duplicate free blocks"
        assert not free & cached and not free & referenced \
            and not cached & referenced, "block in two pools at once"
        assert len(free) + len(cached) + len(referenced) \
            == self.num_blocks, (
                f"pool leak: free={len(free)} cached={len(cached)} "
                f"referenced={len(referenced)} != {self.num_blocks}")
        counts = Counter()
        for t in self._tables.values():
            counts.update(t)
        assert dict(counts) == self._ref, "ref counts out of sync"
        assert all(self._block_of.get(h) == b
                   for b, h in self._hash_of.items()) \
            and len(self._block_of) == len(self._hash_of), \
            "hash index not a bijection"
        assert all(b in self._hash_of for b in cached), \
            "cached block without a hash"
        # per-sequence consistency, incl. partially-prefilled sequences
        assert set(self._lens) == set(self._tables), \
            "length/table bookkeeping out of sync"
        for s, t in self._tables.items():
            ln = self._lens[s]
            assert t and 0 <= ln <= len(t) * self.block_size, (
                f"seq {s}: context length {ln} outside its "
                f"{len(t)}-block table (partial-prefill bound)")
            assert all(0 <= b < self.num_blocks for b in t), \
                f"seq {s}: block id out of range"

    # -- device updates -----------------------------------------------------
    def write(self, layer: int, k, v, slot_mapping):
        """Write one step's K/V for `layer` at the given flat slots."""
        nk, nv = reshape_and_cache(k, v, self.k[layer], self.v[layer],
                                   slot_mapping)
        self.k[layer] = nk
        self.v[layer] = nv


def _pallas_decode_ok(q, k_cache):
    if jax.default_backend() in ("cpu", "gpu"):
        return False
    from ..utils.flags import FLAGS
    if not getattr(FLAGS, "use_pallas_kernels", True):
        return False
    d = q.shape[-1]
    # layout [num_blocks, kv_heads, block_size, d] (tuple-aware)
    bs = _plane_values(k_cache).shape[2]
    return d in (64, 128, 256) and bs % 8 == 0


def paged_attention_decode(q, k_cache, v_cache, block_tables, context_lens,
                           scale: Optional[float] = None):
    """One-token decode attention over the paged cache; Pallas
    scalar-prefetch kernel on TPU, jnp reference elsewhere. See
    paged_attention_decode_reference for the signature. Quantized
    pools run the reference path everywhere: the DENSE decode kernel
    predates the sidecar-scale layout, and serving's TPU hot path is
    the ragged program (whose kernel fuses the dequant) — the dense
    per-phase scheduler is the CPU/debug fallback there."""
    if not isinstance(k_cache, tuple) and _pallas_decode_ok(q, k_cache):
        from .pallas.paged_attention import paged_attention_decode_pallas
        return paged_attention_decode_pallas(q, k_cache, v_cache,
                                             block_tables, context_lens,
                                             scale)
    return paged_attention_decode_reference(q, k_cache, v_cache,
                                            block_tables, context_lens,
                                            scale)
