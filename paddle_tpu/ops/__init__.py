"""paddle_tpu.ops — fused-op inventory on raw jax arrays.

TPU-native replacement for the reference's CUDA fusion kernels
(/root/reference/paddle/phi/kernels/fusion/): flash attention, rms_norm,
rope, paged attention. Each op has a jnp reference implementation (used on
CPU and as the numerics oracle) and, where profitable, a Pallas TPU kernel
selected at runtime. All functions here take/return jax.Array (not Tensor) —
the nn.functional layer adapts them onto the autograd tape.
"""
from .flash_attention import flash_attention, flash_attention_reference
from .rms_norm import rms_norm
from .rope import apply_rotary_pos_emb, rope_reference

__all__ = [
    "flash_attention", "flash_attention_reference", "rms_norm",
    "apply_rotary_pos_emb", "rope_reference",
]
