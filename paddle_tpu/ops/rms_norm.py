"""RMSNorm on raw arrays (reference:
/root/reference/python/paddle/incubate/nn/functional/fused_rms_norm.py).
Simple enough that XLA's fusion is already optimal on TPU — a handwritten
Pallas kernel buys nothing here, so this stays a jnp composition (float32
accumulation, bf16 in/out friendly)."""
from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x, weight=None, epsilon: float = 1e-6, axis: int = -1):
    acc = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(acc), axis=axis, keepdims=True)
    out = acc * jax.lax.rsqrt(ms + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight.astype(x.dtype)
    return out
