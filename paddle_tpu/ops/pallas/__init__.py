"""Pallas TPU kernels (the reference's CUDA fusion inventory, TPU-native:
/root/reference/paddle/phi/kernels/fusion/ + third_party/flashattn)."""
