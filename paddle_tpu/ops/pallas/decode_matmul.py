"""Weight-streaming matmul for decode-shaped activations (few rows).

Why: serving decode multiplies a tiny activation [b<=32, K] against
huge weights [K, N] — the op is pure weight-bandwidth. Measured r5 on
the v5e at the Llama-3-8B MLP shape ([8, 4096] x [4096, 14336]), XLA's
stock lowering streams weights at only ~150-250 GB/s of the chip's
~800 GB/s (it picks compute-shaped tilings for an M=8 problem). This
kernel tiles N x K with the activation resident in VMEM, streams weight
tiles through the automatic Pallas pipeline, accumulates in an f32
VMEM scratch, and dequantizes int8 / nibble-packed int4 tiles on the
fly — so quantization's bandwidth win survives at any width.

Reference analog: the fused weight-only GEMV CUDA kernels behind the
serving path (/root/reference/paddle/phi/kernels/fusion/ +
python/paddle/incubate/nn/functional/block_multihead_attention.py:19
neighborhood); TPU-native form, shared by PagedLlamaDecoder/_mm.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["decode_matmul", "decode_matmul_supported"]

_MAX_ROWS = 32
# per-buffer VMEM budget for one weight tile (double-buffered by the
# pipeline; keep well under half of ~16 MB)
_TILE_BYTES = 2 * 1024 * 1024


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _pick_tile(dim: int, limit: int, cap: int = 2048,
               mult: int = 128) -> int:
    """Largest multiple of `mult` <= min(cap, limit) dividing dim (a
    fixed shortlist missed shapes like N=32000, whose best tile is
    1280 — the 256 fallback ran the head matmul at 1/4 bandwidth)."""
    top = min(cap, limit, dim)
    for t in range(top - top % mult, mult - 1, -mult):
        if dim % t == 0:
            return t
    return 0


def _tiles(K: int, N: int, w_bytes_per_elem: float):
    """(TK, TN) or None when the shape doesn't tile cleanly. int4's
    half-activation blocks are [b, TK/2], so TK must be a multiple of
    256 there (the lane rule applies to the HALVED tile)."""
    tn = _pick_tile(N, 1024)
    if not tn:
        return None
    # weight tile = TK x TN x bytes; bound by the VMEM budget
    tk_mult = 256 if w_bytes_per_elem == 0.5 else 128
    tk_limit = int(_TILE_BYTES / (tn * w_bytes_per_elem))
    tk = _pick_tile(K, max(tk_mult, tk_limit), mult=tk_mult)
    if not tk:
        return None
    return tk, tn


def decode_matmul_supported(x, w) -> bool:
    """True when (x, w) fits this kernel: TPU backend, 2-d x with few
    rows, and a cleanly tiling K x N (w dense, or (int8, scale) /
    (int4-packed, scale) pairs)."""
    if not _on_tpu() or x.ndim != 2 or x.shape[0] > _MAX_ROWS:
        return False
    K = x.shape[1]
    if isinstance(w, tuple):
        wq, _ = w
        if wq.ndim != 2:
            return False
        if wq.shape[0] * 2 == K:      # int4 nibble-packed
            return _tiles(K, wq.shape[1], 0.5) is not None
        if wq.shape[0] != K:
            return False
        return _tiles(K, wq.shape[1], 1) is not None
    return (w.ndim == 2 and w.shape[0] == K
            and _tiles(K, w.shape[1], jnp.dtype(w.dtype).itemsize)
            is not None)


def _make_kernel(nk: int, kind: str, out_dtype):
    from jax.experimental import pallas as pl

    def kernel(*refs):
        # program_id(1) is the k step (grid = (n, k), k minor)
        ki = pl.program_id(1)
        if kind == "int4":
            # halves packing: packed row r encodes in-rows r (low
            # nibble) and r + K/2 (high) — the two activation views
            # are CONTIGUOUS halves, addressed by block specs over the
            # same x input (no host-side strided slicing; the old
            # even/odd layout burned 1.6 ms/step in slice fusions at
            # 8B). Mosaic can't shape-cast/stride in-kernel, which is
            # why the layout carries the split.
            xe_ref, xo_ref, w_ref, s_ref, o_ref, acc_ref = refs
        else:
            x_ref, w_ref, s_ref, o_ref, acc_ref = refs

        @pl.when(ki == 0)
        def _init():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        if kind == "int4":
            # Mosaic has no int8 vector shifts: unpack via int32 with
            # branch-free sign extension of the low nibble
            w32 = w_ref[...].astype(jnp.int32)
            xe, xo = xe_ref[...], xo_ref[...]
            lo = (((w32 & 15) ^ 8) - 8).astype(xe.dtype)
            hi = (w32 >> 4).astype(xe.dtype)
            acc_ref[...] += (
                jax.lax.dot(xe, lo, preferred_element_type=jnp.float32)
                + jax.lax.dot(xo, hi,
                              preferred_element_type=jnp.float32))
        else:
            xb = x_ref[...]
            wb = w_ref[...]
            if kind == "int8":
                wb = wb.astype(xb.dtype)
            acc_ref[...] += jax.lax.dot(
                xb, wb, preferred_element_type=jnp.float32)

        @pl.when(ki == nk - 1)
        def _done():
            acc = acc_ref[...]
            if kind in ("int8", "int4"):
                acc = acc * s_ref[...].astype(jnp.float32)
            o_ref[...] = acc.astype(out_dtype)

    return kernel


def decode_matmul(x, w):
    """x [b, K] @ w -> [b, N]; w is dense [K, N], (int8 [K, N], scale
    [N]) or (int4-packed [K/2, N], scale [N]). int4 packing MUST be
    the HALVES layout (_quantize_w4_halves: packed row r = in-rows r
    and r + K/2); the interleaved even/odd layout is not detectable
    from the tuple and would silently produce wrong results. Caller
    must have checked decode_matmul_supported."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, K = x.shape
    if isinstance(w, tuple):
        wq, scale = w
        if wq.shape[0] * 2 == K:
            kind, wbytes = "int4", 0.5
        else:
            kind, wbytes = "int8", 1
        N = wq.shape[1]
    else:
        wq, scale = w, jnp.ones((w.shape[1],), jnp.float32)
        kind, wbytes = "dense", jnp.dtype(w.dtype).itemsize
        N = w.shape[1]
    tk, tn = _tiles(K, N, wbytes)
    nk, nn = K // tk, N // tn
    wtk = tk // 2 if kind == "int4" else tk

    kernel = _make_kernel(nk, kind, x.dtype)
    if kind == "int4":
        # the same x feeds two specs: k-th block of the FIRST half
        # (low nibbles) and of the SECOND half (block index k + nk)
        ins = (x, x, wq, scale.reshape(1, N))
        in_specs = [
            pl.BlockSpec((b, tk // 2), lambda j, k: (0, k)),
            pl.BlockSpec((b, tk // 2), lambda j, k, _nk=nk: (0, k + _nk)),
            pl.BlockSpec((wtk, tn), lambda j, k: (k, j)),
            pl.BlockSpec((1, tn), lambda j, k: (0, j)),
        ]
    else:
        ins = (x, wq, scale.reshape(1, N))
        in_specs = [
            pl.BlockSpec((b, tk), lambda j, k: (0, k)),
            pl.BlockSpec((wtk, tn), lambda j, k: (k, j)),
            pl.BlockSpec((1, tn), lambda j, k: (0, j)),
        ]
    return pl.pallas_call(
        kernel,
        grid=(nn, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, tn), lambda j, k: (0, j)),
        out_shape=jax.ShapeDtypeStruct((b, N), x.dtype),
        scratch_shapes=[pltpu.VMEM((b, tn), jnp.float32)],
    )(*ins)
