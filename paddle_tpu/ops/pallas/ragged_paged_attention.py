"""Ragged paged attention — mixed prefill+decode rows, Pallas TPU kernel.

Reference design: "Ragged Paged Attention: A High-Performance and
Flexible LLM Inference Kernel for TPU" (PAPERS.md) — one kernel over a
FLATTENED token batch [total_rows, heads, head_dim] whose rows mix
decode tokens (one per running sequence) and prefill-chunk tokens
(consecutive prompt positions of a prefilling sequence). The grid is
sized by the actual rows, not [max_batch]: inactive batch slots simply
have no rows, so the dense path's scratch-page padding disappears at the
source.

TPU-native structure (same skeleton as the decode kernel in
paged_attention.py): the KV pool stays in HBM (memory_space=ANY);
per-row sequence ids (`row_seq`), per-row visible-context lengths
(`row_ctx`) and the per-sequence page tables are SCALAR-PREFETCHED into
SMEM. One grid step covers a block of `tq` rows: the kernel walks the
block's DISTINCT sequences (first-occurrence dedup over the prefetched
row_seq scalars — a prefill chunk contributes many rows of ONE sequence,
so its pages are DMA'd once per block, not once per row), manually
double-buffer-DMA-ing each physical page — [kv_heads, block_size,
head_dim], one contiguous copy per page — into VMEM while the previous
page's flash-style online-softmax update runs. Per-row causal masking is
pure data: pool positions >= row_ctx[row] are masked, which is both the
context-length bound AND the intra-chunk causal mask (chunk row j at
offset `off` passes row_ctx = off + j + 1).

Pool layout: [num_blocks, kv_heads, block_size, head_dim].
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu" and not _on_tpu()


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu", "gpu")
    except Exception:
        return False


def _ragged_kernel(rowseq_ref, rowctx_ref, tables_ref, q_ref, *refs,
                   block_size, scale, pages_per_iter, max_pages, tq,
                   group, quantized):
    # ref unpacking is static on `quantized` (ISSUE 13): the int8 pool
    # carries two extra HBM operands (the per-slot-per-kv-head scale
    # sidecars), two extra VMEM scale buffers and their DMA semaphores
    # — each physical page's [kvh, bs] scale row rides the SAME
    # double-buffered pipeline as its values, and dequant happens in
    # VMEM right before the score/value matmuls (quantize-the-pool,
    # dequant-at-the-DMA: the EQuARX wire idea applied to storage).
    if quantized:
        (k_hbm, v_hbm, ks_hbm, vs_hbm, o_ref, k_buf, v_buf, ks_buf,
         vs_buf, sem_k, sem_v, sem_ks, sem_vs) = refs
    else:
        k_hbm, v_hbm, o_ref, k_buf, v_buf, sem_k, sem_v = refs
    g = pl.program_id(0)
    base = g * tq
    P = pages_per_iter
    bs = block_size
    kvh, rows, d = q_ref.shape[0], q_ref.shape[2], q_ref.shape[3]
    q = q_ref[:, 0].astype(jnp.float32) * scale        # [kvh, rows, d]

    # per-lane row maps (lane -> its row's seq id / visible ctx), built
    # once per block from tq scalar SMEM reads; lane = row * group + gi
    lane_row = jax.lax.broadcasted_iota(
        jnp.int32, (1, rows, 1), 1) // group
    seq_map = jnp.zeros((1, rows, 1), jnp.int32)
    ctx_map = jnp.zeros((1, rows, 1), jnp.int32)
    for j in range(tq):
        seq_map = jnp.where(lane_row == j, rowseq_ref[base + j], seq_map)
        ctx_map = jnp.where(lane_row == j, rowctx_ref[base + j], ctx_map)

    def _page_copies(s, it, slot, pj):
        page = tables_ref[s, jnp.minimum(it * P + pj, max_pages - 1)]
        copies = [
            pltpu.make_async_copy(
                k_hbm.at[page],
                k_buf.at[slot, :, pl.ds(pj * bs, bs), :],
                sem_k.at[slot, pj]),
            pltpu.make_async_copy(
                v_hbm.at[page],
                v_buf.at[slot, :, pl.ds(pj * bs, bs), :],
                sem_v.at[slot, pj]),
        ]
        if quantized:
            copies.append(pltpu.make_async_copy(
                ks_hbm.at[page],
                ks_buf.at[slot, :, pl.ds(pj * bs, bs)],
                sem_ks.at[slot, pj]))
            copies.append(pltpu.make_async_copy(
                vs_hbm.at[page],
                vs_buf.at[slot, :, pl.ds(pj * bs, bs)],
                sem_vs.at[slot, pj]))
        return copies

    def copy_in(s, it, slot):
        """Issue the page DMAs of sequence `s`'s iteration group `it`
        into buffer `slot` (tail groups read a clamped table entry —
        masked in compute); values + sidecar scales together."""
        for pj in range(P):
            for c in _page_copies(s, it, slot, pj):
                c.start()

    def wait_group(s, it, slot):
        for pj in range(P):
            for c in _page_copies(s, it, slot, pj):
                c.wait()

    def seq_body(j, carry):
        """Process the block's j-th row's sequence IF row j is its
        first live occurrence in the block (dedup: one page walk per
        distinct sequence per block)."""
        acc, m_prev, l_prev = carry
        s = rowseq_ref[base + j]
        ctx_j = rowctx_ref[base + j]

        def occ(i, c):
            fo, mx = c
            si = rowseq_ref[base + i]
            ci = rowctx_ref[base + i]
            fo = jnp.logical_and(
                fo, jnp.logical_or(i >= j,
                                   jnp.logical_or(si != s, ci <= 0)))
            mx = jnp.where(si == s, jnp.maximum(mx, ci), mx)
            return fo, mx

        fo, maxctx = jax.lax.fori_loop(
            0, tq, occ, (jnp.asarray(True), jnp.asarray(0, jnp.int32)))
        process = jnp.logical_and(fo, ctx_j > 0)
        n_pages = jnp.where(
            process, jax.lax.div(maxctx + bs - 1, bs), 0)
        n_iters = jax.lax.div(n_pages + P - 1, P)
        belongs = seq_map == s                         # [1, rows, 1]

        @pl.when(n_iters > 0)
        def _prologue():
            copy_in(s, 0, 0)

        def page_body(it, c):
            acc, m_prev, l_prev = c
            slot = jax.lax.rem(it, 2)

            @pl.when(it + 1 < n_iters)
            def _prefetch():
                copy_in(s, it + 1, jax.lax.rem(it + 1, 2))

            wait_group(s, it, slot)
            k = k_buf[slot].astype(jnp.float32)        # [kvh, P*bs, d]
            v = v_buf[slot].astype(jnp.float32)
            if quantized:
                # dequant in VMEM, per element, exactly the oracle's
                # gather-time math (value * its slot's scale) so
                # kernel-vs-oracle parity holds bit-tight on the int8
                # layout; the scale buffers are [kvh, P*bs]
                k = k * ks_buf[slot][..., None]
                v = v * vs_buf[slot][..., None]
            sc = jax.lax.dot_general(
                q, k, (((2,), (2,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)    # [kvh, rows, P*bs]
            pos = it * (P * bs) + jax.lax.broadcasted_iota(
                jnp.int32, sc.shape, 2)
            ok = jnp.logical_and(belongs, pos < ctx_map)
            sc = jnp.where(ok, sc, _NEG_INF)
            m_cur = jnp.max(sc, axis=-1)
            m_new = jnp.maximum(m_prev, m_cur)
            prob = jnp.where(sc > _NEG_INF * 0.5,
                             jnp.exp(sc - m_new[..., None]), 0.0)
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(prob, axis=-1)
            acc = acc * corr[..., None] + jax.lax.dot_general(
                prob, v, (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=jnp.float32)    # [kvh, rows, d]
            return acc, m_new, l_new

        return jax.lax.fori_loop(0, n_iters, page_body,
                                 (acc, m_prev, l_prev))

    acc0 = jnp.zeros((kvh, rows, d), jnp.float32)
    m0 = jnp.full((kvh, rows), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((kvh, rows), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, tq, seq_body, (acc0, m0, l0))
    # rows no sequence claimed (grid padding, row_ctx <= 0) have l == 0
    # and come out exactly zero
    o_ref[:, 0] = (acc / jnp.maximum(l, 1e-30)[..., None]) \
        .astype(o_ref.dtype)


def ragged_paged_attention_pallas(q, k_cache, v_cache, block_tables,
                                  row_seq, row_ctx,
                                  scale: Optional[float] = None,
                                  rows_per_block: int = 8):
    """Ragged mixed prefill+decode attention over the paged pool.

    q [total_rows, num_heads, head_dim]; caches [num_blocks, kv_heads,
    block_size, head_dim] — or (int8 values, f32 scales [num_blocks,
    kv_heads, block_size]) tuples for the quantized pool (ISSUE 13),
    whose sidecar scales ride each page's DMA and dequantize in VMEM;
    block_tables [num_seqs, max_pages] int32;
    row_seq/row_ctx [total_rows] int32 (see
    ops.paged_attention.ragged_paged_attention_reference).
    Returns [total_rows, num_heads, head_dim]."""
    quantized = isinstance(k_cache, tuple)
    if quantized:
        k_cache, k_scales = k_cache
        v_cache, v_scales = v_cache
    r, nh, d = q.shape
    nb, kvh, bs, _ = k_cache.shape
    max_pages = block_tables.shape[1]
    if nh % kvh:
        # would otherwise surface as an opaque reshape error below;
        # matters doubly under TP sharding, where SpecLayout shards the
        # pool over the kv-head dim and each shard's nh/kvh must still
        # group evenly
        raise ValueError(
            f"num_heads ({nh}) must be a multiple of kv_heads ({kvh}) "
            f"for the GQA head grouping")
    group = nh // kvh
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    tq = max(1, int(rows_per_block))
    g = -(-r // tq)
    r_pad = g * tq
    qp = jnp.pad(q, ((0, r_pad - r), (0, 0), (0, 0)))
    rs = jnp.pad(row_seq.astype(jnp.int32), (0, r_pad - r))
    rc = jnp.pad(row_ctx.astype(jnp.int32), (0, r_pad - r),
                 constant_values=0)
    # [kvh, grid, tq*group, d]: kv-head-major so the kernel's score
    # matmul is the decode kernel's 3-D batched dot, no in-kernel
    # transposes
    q4 = qp.reshape(r_pad, kvh, group, d).transpose(1, 0, 2, 3) \
        .reshape(kvh, g, tq * group, d)
    # widen each DMA iteration to ~TOKENS_PER_ITER kv positions (deep
    # pipeline + MXU-sized score matmuls), same knob as the decode kernel
    import os
    tpi = int(os.environ.get("PT_PAGED_TOKENS_PER_ITER", "128"))
    P = max(1, min(max_pages, tpi // bs))

    in_specs = [
        pl.BlockSpec((kvh, 1, tq * group, d),
                     lambda gi, rs_, rc_, tb_: (0, gi, 0, 0)),
        pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays in HBM
        pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays in HBM
    ]
    scratch_shapes = [
        pltpu.VMEM((2, kvh, P * bs, d), k_cache.dtype),
        pltpu.VMEM((2, kvh, P * bs, d), v_cache.dtype),
    ]
    sems = [pltpu.SemaphoreType.DMA((2, P)),
            pltpu.SemaphoreType.DMA((2, P))]
    operands = [k_cache, v_cache]
    if quantized:
        # scale sidecars: HBM-resident like the pools, double-buffered
        # [kvh, P*bs] f32 VMEM slices, one DMA semaphore pair more
        in_specs += [pl.BlockSpec(memory_space=pltpu.ANY),
                     pl.BlockSpec(memory_space=pltpu.ANY)]
        scratch_shapes += [pltpu.VMEM((2, kvh, P * bs), jnp.float32),
                           pltpu.VMEM((2, kvh, P * bs), jnp.float32)]
        sems += [pltpu.SemaphoreType.DMA((2, P)),
                 pltpu.SemaphoreType.DMA((2, P))]
        operands += [k_scales, v_scales]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(g,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((kvh, 1, tq * group, d),
                               lambda gi, rs_, rc_, tb_: (0, gi, 0, 0)),
        scratch_shapes=scratch_shapes + sems,
    )
    out = pl.pallas_call(
        functools.partial(_ragged_kernel, block_size=bs, scale=scale,
                          pages_per_iter=P, max_pages=max_pages, tq=tq,
                          group=group, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kvh, g, tq * group, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(rs, rc, block_tables.astype(jnp.int32), q4, *operands)
    out = out.reshape(kvh, r_pad, group, d).transpose(1, 0, 2, 3) \
        .reshape(r_pad, nh, d)
    return out[:r]
