"""Paged-attention decode, Pallas TPU kernel.

Reference: block_multi_head_attention decode
(/root/reference/paddle/phi/kernels/fusion/gpu/
block_multi_head_attention_kernel.cu; python API
python/paddle/incubate/nn/functional/block_multihead_attention.py).

TPU-native design: the KV pool stays in HBM (memory_space=ANY); the
per-sequence block table and context lengths are SCALAR-PREFETCHED into
SMEM. One grid step per sequence runs a fori_loop whose trip count is the
sequence's ACTUAL page count (no work on empty pages), manually DMA-ing
each physical page — [kv_heads, block_size, head_dim], one contiguous
copy serving every kv head — into a double-buffered VMEM scratch so the
next page's DMA overlaps the current page's flash-style online-softmax
update. This is the latency story jnp.take can't express: the gather
composition materializes [batch, max_pages*block_size, ...] windows and
always pays for max_pages.

Pool layout: [num_blocks, kv_heads, block_size, head_dim].
"""
from __future__ import annotations

import functools
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _interpret() -> bool:
    return jax.default_backend() != "tpu" and not _on_tpu()


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu", "gpu")
    except Exception:
        return False


def _decode_kernel(tables_ref, ctx_ref, q_ref, k_hbm, v_hbm, o_ref,
                   k_buf, v_buf, sem_k, sem_v, *, block_size, scale,
                   pages_per_iter, max_pages):
    bi = pl.program_id(0)
    ctx = ctx_ref[bi]
    P = pages_per_iter
    n_pages = jax.lax.div(ctx + block_size - 1, block_size)
    n_iters = jax.lax.div(n_pages + P - 1, P)
    kvh, group, d = q_ref.shape[1], q_ref.shape[2], q_ref.shape[3]
    q = q_ref[0].astype(jnp.float32) * scale          # [kvh, group, d]

    def copy_in(g, slot):
        """Issue P page DMAs for iteration group g into buffer `slot`;
        each page lands in its strip of the [kvh, P*bs, d] buffer."""
        for j in range(P):
            # tail groups read a clamped table entry; masked in compute
            pj = jnp.minimum(g * P + j, max_pages - 1)
            page = tables_ref[bi, pj]
            pltpu.make_async_copy(
                k_hbm.at[page],
                k_buf.at[slot, :, pl.ds(j * block_size, block_size), :],
                sem_k.at[slot, j]).start()
            pltpu.make_async_copy(
                v_hbm.at[page],
                v_buf.at[slot, :, pl.ds(j * block_size, block_size), :],
                sem_v.at[slot, j]).start()

    def wait_group(g, slot):
        for j in range(P):
            page = tables_ref[bi, jnp.minimum(g * P + j, max_pages - 1)]
            pltpu.make_async_copy(
                k_hbm.at[page],
                k_buf.at[slot, :, pl.ds(j * block_size, block_size), :],
                sem_k.at[slot, j]).wait()
            pltpu.make_async_copy(
                v_hbm.at[page],
                v_buf.at[slot, :, pl.ds(j * block_size, block_size), :],
                sem_v.at[slot, j]).wait()

    @pl.when(n_iters > 0)
    def _prologue():
        copy_in(0, 0)

    def body(g, carry):
        acc, m_prev, l_prev = carry
        slot = jax.lax.rem(g, 2)

        @pl.when(g + 1 < n_iters)
        def _prefetch():
            copy_in(g + 1, jax.lax.rem(g + 1, 2))

        wait_group(g, slot)
        k = k_buf[slot].astype(jnp.float32)            # [kvh, P*bs, d]
        v = v_buf[slot].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [kvh, group, P*bs]
        pos = g * (P * block_size) + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 2)
        s = jnp.where(pos < ctx, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)
        m_new = jnp.maximum(m_prev, m_cur)
        prob = jnp.where(s > _NEG_INF * 0.5,
                         jnp.exp(s - m_new[..., None]), 0.0)
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(prob, axis=-1)
        acc = acc * corr[..., None] + jax.lax.dot_general(
            prob, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)        # [kvh, group, d]
        return acc, m_new, l_new

    acc0 = jnp.zeros((kvh, group, d), jnp.float32)
    m0 = jnp.full((kvh, group), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((kvh, group), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_iters, body, (acc0, m0, l0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l_safe[..., None]).astype(o_ref.dtype)


def paged_attention_decode_pallas(q, k_cache, v_cache, block_tables,
                                  context_lens,
                                  scale: Optional[float] = None):
    """One-token decode over the paged pool.

    q [batch, num_heads, head_dim]; caches [num_blocks, kv_heads,
    block_size, head_dim]; block_tables [batch, max_pages] int32;
    context_lens [batch] int32. Returns [batch, num_heads, head_dim]."""
    b, nh, d = q.shape
    nb, kvh, bs, _ = k_cache.shape
    max_pages = block_tables.shape[1]
    group = nh // kvh
    if scale is None:
        scale = 1.0 / np.sqrt(d)
    q4 = q.reshape(b, kvh, group, d)
    # widen each loop iteration to ~TOKENS_PER_ITER kv positions: deep
    # DMA pipeline + MXU-sized score matmuls
    import os
    tpi = int(os.environ.get("PT_PAGED_TOKENS_PER_ITER", "128"))
    P = max(1, min(max_pages, tpi // bs))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b,),
        in_specs=[
            pl.BlockSpec((1, kvh, group, d),
                         lambda bi, tbl, ctx: (bi, 0, 0, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),   # K pool stays in HBM
            pl.BlockSpec(memory_space=pltpu.ANY),   # V pool stays in HBM
        ],
        out_specs=pl.BlockSpec((1, kvh, group, d),
                               lambda bi, tbl, ctx: (bi, 0, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((2, kvh, P * bs, d), k_cache.dtype),
            pltpu.VMEM((2, kvh, P * bs, d), v_cache.dtype),
            pltpu.SemaphoreType.DMA((2, P)),
            pltpu.SemaphoreType.DMA((2, P)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_size=bs, scale=scale,
                          pages_per_iter=P, max_pages=max_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, kvh, group, d), q.dtype),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=_interpret(),
    )(block_tables.astype(jnp.int32), context_lens.astype(jnp.int32),
      q4, k_cache, v_cache)
    return out.reshape(b, nh, d)
