"""Flash attention, Pallas TPU implementation (fwd + bwd), with optional
segment-ids (varlen/packed-sequence) masking.

Replaces the reference's third_party/flashattn CUDA kernels
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu; varlen API
/root/reference/python/paddle/nn/functional/flash_attention.py:302).
Blocked online-softmax over KV tiles; LSE saved for the backward; causal
masking with early loop exit.

GQA is handled WITHOUT expanding K/V in HBM: forward and dq kernels read
the shared kv-head block via index maps (hi // group), and the dk/dv
kernel accumulates the query-head group in-place by revisiting the same
output block across the innermost grid dimension — no jnp.repeat, no
group-expanded HBM traffic.

Segment ids (int32, [batch, seq]) restrict attention to tokens of equal
id — the packed-sequence ("varlen"/"unpadded") training path. Negative or
mismatched ids are fully masked; fully-masked query rows produce zero
output (guarded online softmax, not NaN).

Layout contract (paddle convention at the API): q/k/v [batch, seq, heads,
head_dim]; kernels internally run [batch, heads, seq, head_dim]. head_dim
should be a multiple of 128 for MXU efficiency (64 works, half-utilized).

VMEM budget: K and V are held whole per (batch, kv-head) — fine up to
seq*dim*2B*2 ≈ 8MB (seq 16k @ d=128 bf16). Longer sequences belong to ring
attention (paddle_tpu.distributed.ring_attention) which shards seq over
the mesh.
"""
from __future__ import annotations

import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    # interpreter mode on non-TPU backends (CPU tests / numerics oracle)
    return jax.default_backend() != "tpu" and not _on_tpu()


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu", "gpu")
    except Exception:
        return False


def _sds(shape, dtype, like):
    """ShapeDtypeStruct carrying `like`'s varying-manual-axes tag: when
    a kernel runs inside a check_vma shard_map (e.g. ring attention
    manual over 'sep' with dp/mp auto), pallas_call demands the output
    vma be stated explicitly — propagate it from an input operand."""
    vma = getattr(getattr(like, "aval", None), "vma", None)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    return jax.ShapeDtypeStruct(shape, dtype)

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _fwd_kernel(*refs, scale, causal, block_k, seq_q, seq_k, segmented):
    if segmented:
        q_ref, k_ref, v_ref, qseg_ref, kseg_ref, o_ref, lse_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref = refs
    # block shapes: q [1, 1, bq, d]; k/v [1, 1, seq_k, d]
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, d]
    bq = q.shape[0]
    qi = pl.program_id(2)
    q_offset = qi * bq
    if segmented:
        qseg = qseg_ref[0]                                # [bq]

    num_kv = pl.cdiv(seq_k, block_k)
    off = seq_k - seq_q   # causal aligns queries to the END of the keys
    if causal:
        # only blocks whose start <= last query row's global position
        num_kv_run = jnp.maximum(
            jax.lax.div(q_offset + bq - 1 + off, block_k) + 1, 0)
    else:
        num_kv_run = num_kv

    def body(kj, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        if causal:
            rows = q_offset + off + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if segmented:
            kseg = kseg_ref[0, pl.ds(kj * block_k, block_k)]  # [bk]
            s = jnp.where(qseg[:, None] == kseg[None, :], s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)                          # [bq]
        m_new = jnp.maximum(m_prev, m_cur)
        # guard: fully-masked rows keep p == 0 (else exp(-inf - -inf) = 1)
        p = jnp.where(s > _NEG_INF * 0.5,
                      jnp.exp(s - m_new[:, None]), 0.0)      # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                       # [bq]
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q.shape[-1]
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv_run, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, :, 0] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd(q, k, v, q_seg, kv_seg, causal, scale, block_q, block_k):
    """q [b,h,sq,d]; k/v [b,hk,sk,d]; segs [b,s] or None
    → out [b,h,sq,d], lse [b,h,sq]."""
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    group = h // hk
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    grid = (b, h, pl.cdiv(sq, bq))
    segmented = q_seg is not None

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=bk, seq_q=sq, seq_k=sk,
                               segmented=segmented)
    in_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, sk, d),
                     lambda bi, hi, qi, _g=group: (bi, hi // _g, 0, 0)),
        pl.BlockSpec((1, 1, sk, d),
                     lambda bi, hi, qi, _g=group: (bi, hi // _g, 0, 0)),
    ]
    args = [q, k, v]
    if segmented:
        in_specs += [
            pl.BlockSpec((1, bq), lambda bi, hi, qi: (bi, qi)),
            pl.BlockSpec((1, sk), lambda bi, hi, qi: (bi, 0)),
        ]
        args += [q_seg, kv_seg]
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            _sds((b, h, sq, d), q.dtype, q),
            _sds((b, h, sq, 1), jnp.float32, q),
        ],
        interpret=_interpret(),
    )(*args)
    return out, lse[..., 0]


def _bwd_dq_kernel(*refs, scale, causal, block_k, seq_q, seq_k,
                   segmented, q_base, k_base):
    if segmented:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dq_ref) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref = refs
    q = q_ref[0, 0].astype(jnp.float32)                     # [bq, d]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]                               # [bq]
    delta = delta_ref[0, 0, :, 0]                           # [bq]
    bq = q.shape[0]
    qi = pl.program_id(2)
    q_offset = qi * bq
    if segmented:
        qseg = qseg_ref[0]

    # q_base/k_base: GLOBAL sequence positions of this call's first
    # query/key row — the wrapper may be feeding a [q-chunk, k-chunk]
    # slice of a longer sequence (VMEM-bounded long-seq backward)
    num_kv = pl.cdiv(seq_k, block_k)
    if causal:
        num_kv_run = jnp.clip(
            jax.lax.div(q_base + q_offset + bq - 1 - k_base, block_k)
            + 1, 0, num_kv)
    else:
        num_kv_run = num_kv

    def body(kj, dq):
        k_blk = k_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_base + q_offset + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_base + kj * block_k + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if segmented:
            kseg = kseg_ref[0, pl.ds(kj * block_k, block_k)]
            s = jnp.where(qseg[:, None] == kseg[None, :], s, _NEG_INF)
        p = jnp.where(s > _NEG_INF * 0.5,
                      jnp.exp(s - lse[:, None]), 0.0)        # [bq, bk]
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale               # [bq, bk]
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq0 = jnp.zeros_like(q)
    dq = jax.lax.fori_loop(0, num_kv_run, body, dq0)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, block_q, seq_q, seq_k, group,
                    segmented, q_base, k_base):
    """Grid (b, hk, n_kblocks, group): the innermost `group` dimension
    revisits the same dk/dv output block, accumulating the kv-head's query
    group in VMEM (GQA without expanding K/V or group-partial HBM writes)."""
    if segmented:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, qseg_ref,
         kseg_ref, dk_ref, dv_ref) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref,
         dv_ref) = refs
    k_blk = k_ref[0, 0].astype(jnp.float32)                  # [bk, d]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    bk = k_blk.shape[0]
    kj = pl.program_id(2)
    gi = pl.program_id(3)
    k_offset = kj * bk
    if segmented:
        kseg = kseg_ref[0, pl.ds(k_offset, bk)]

    num_q = pl.cdiv(seq_q, block_q)
    if causal:
        # first q block whose END global position can see this k block
        first_q = jax.lax.div(
            jnp.maximum(k_base + k_offset - q_base, 0), block_q)
    else:
        first_q = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_base + qi * block_q + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_base + k_offset + \
                jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        if segmented:
            qseg = qseg_ref[0, pl.ds(qi * block_q, block_q)]
            s = jnp.where(qseg[:, None] == kseg[None, :], s, _NEG_INF)
        p = jnp.where(s > _NEG_INF * 0.5,
                      jnp.exp(s - lse[:, None]), 0.0)        # [bq, bk]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    d = k_blk.shape[-1]
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q, body, (dk0, dv0))

    @pl.when(gi == 0)
    def _init():
        dk_ref[0, 0] = dk
        dv_ref[0, 0] = dv

    @pl.when(gi > 0)
    def _accum():
        dk_ref[0, 0] += dk
        dv_ref[0, 0] += dv


def _bwd_pair_call(q, k, v, do, lse4, delta, q_seg, kv_seg, causal,
                   scale, bq, bk, group, q_base, k_base, dq_dtype):
    """dq + dk/dv pallas calls for one (q-slice, k-slice) pair whose
    first rows sit at GLOBAL positions q_base/k_base."""
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    segmented = q_seg is not None

    dq_specs = [
        pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, sk, d),
                     lambda bi, hi, qi, _g=group: (bi, hi // _g, 0, 0)),
        pl.BlockSpec((1, 1, sk, d),
                     lambda bi, hi, qi, _g=group: (bi, hi // _g, 0, 0)),
        pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
    ]
    dq_args = [q, k, v, do, lse4, delta]
    if segmented:
        dq_specs += [
            pl.BlockSpec((1, bq), lambda bi, hi, qi: (bi, qi)),
            pl.BlockSpec((1, sk), lambda bi, hi, qi: (bi, 0)),
        ]
        dq_args += [q_seg, kv_seg]
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_q=sq, seq_k=sk,
                          segmented=segmented, q_base=q_base,
                          k_base=k_base),
        grid=(b, h, pl.cdiv(sq, bq)),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=_sds((b, h, sq, d), dq_dtype, q),
        interpret=_interpret(),
    )(*dq_args)

    # dk/dv: grid (b, hk, kblocks, group); q-head = hk_index*group + g
    def qmap(bi, hki, kj, g, _g=group):
        return (bi, hki * _g + g, 0, 0)

    dkv_specs = [
        pl.BlockSpec((1, 1, sq, d), qmap),
        pl.BlockSpec((1, 1, bk, d), lambda bi, hki, kj, g: (bi, hki, kj, 0)),
        pl.BlockSpec((1, 1, bk, d), lambda bi, hki, kj, g: (bi, hki, kj, 0)),
        pl.BlockSpec((1, 1, sq, d), qmap),
        pl.BlockSpec((1, 1, sq, 1), qmap),
        pl.BlockSpec((1, 1, sq, 1), qmap),
    ]
    dkv_args = [q, k, v, do, lse4, delta]
    if segmented:
        dkv_specs += [
            pl.BlockSpec((1, sq), lambda bi, hki, kj, g: (bi, 0)),
            pl.BlockSpec((1, sk), lambda bi, hki, kj, g: (bi, 0)),
        ]
        dkv_args += [q_seg, kv_seg]
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, seq_q=sq, seq_k=sk, group=group,
                          segmented=segmented, q_base=q_base,
                          k_base=k_base),
        grid=(b, hk, pl.cdiv(sk, bk), group),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hki, kj, g: (bi, hki, kj, 0)),
            pl.BlockSpec((1, 1, bk, d),
                         lambda bi, hki, kj, g: (bi, hki, kj, 0)),
        ],
        out_shape=[
            _sds((b, hk, sk, d), jnp.float32, q),
            _sds((b, hk, sk, d), jnp.float32, q),
        ],
        interpret=_interpret(),
    )(*dkv_args)
    return dq, dk, dv


# backward VMEM story: each dq call holds its k-slice (and each dkv call
# its q-slice) whole in VMEM, so slices past ~2k at d=128 blow the
# ~16MB scoped-vmem budget (measured: a 4096 slice needs 16.6MB).
# Above this length the wrapper tiles the backward into
# [q-chunk, k-chunk] pair calls (global offsets keep the causal mask
# exact; fully-invisible pairs are skipped outright).
BWD_SEQ_CHUNK = 2048


def _flash_bwd(q, k, v, out, lse, do, q_seg, kv_seg, causal, scale,
               block_q, block_k):
    """q/do [b,h,sq,d]; k/v [b,hk,sk,d] (NOT expanded). Returns dq [b,h,..]
    and group-summed dk/dv [b,hk,sk,d] (float32)."""
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    group = h // hk
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)[..., None]                      # [b,h,sq,1]
    lse4 = lse[..., None]                                    # [b,h,sq,1]

    cs = BWD_SEQ_CHUNK
    base = sk - sq     # causal aligns queries to the END of the keys
    if sq <= cs and sk <= cs:
        return _bwd_pair_call(q, k, v, do, lse4, delta, q_seg, kv_seg,
                              causal, scale, bq, bk, group,
                              q_base=base, k_base=0, dq_dtype=q.dtype)

    dq = jnp.zeros((b, h, sq, d), jnp.float32)
    dk = jnp.zeros((b, hk, sk, d), jnp.float32)
    dv = jnp.zeros((b, hk, sk, d), jnp.float32)
    for q0 in range(0, sq, cs):
        qe = min(q0 + cs, sq)
        for k0 in range(0, sk, cs):
            ke = min(k0 + cs, sk)
            if causal and k0 > base + qe - 1:
                continue                       # fully invisible pair
            pair_causal = causal and (ke - 1 > base + q0)
            dq_p, dk_p, dv_p = _bwd_pair_call(
                q[:, :, q0:qe], k[:, :, k0:ke], v[:, :, k0:ke],
                do[:, :, q0:qe], lse4[:, :, q0:qe],
                delta[:, :, q0:qe],
                None if q_seg is None else q_seg[:, q0:qe],
                None if kv_seg is None else kv_seg[:, k0:ke],
                pair_causal, scale, min(bq, qe - q0),
                min(bk, ke - k0), group,
                q_base=base + q0, k_base=k0, dq_dtype=jnp.float32)
            dq = dq.at[:, :, q0:qe].add(dq_p)
            dk = dk.at[:, :, k0:ke].add(dk_p)
            dv = dv.at[:, :, k0:ke].add(dv_p)
    return dq.astype(q.dtype), dk, dv


# ---------------------------------------------------------------------------
# public custom-vjp entry points
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_pallas(q, k, v, causal=False, scale=None,
                           block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """q/k/v: [batch, seq, heads, head_dim] (kv heads may be fewer: GQA)."""
    out, _ = _fa_fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)   # [b,h,s,d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t, lse = _flash_fwd(qt, kt, vt, None, None, causal, scale,
                            block_q, block_k)
    out = jnp.swapaxes(out_t, 1, 2)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t = jnp.swapaxes(out, 1, 2)
    do_t = jnp.swapaxes(g, 1, 2)
    dq_t, dk_t, dv_t = _flash_bwd(qt, kt, vt, out_t, lse, do_t, None, None,
                                  causal, scale, block_q, block_k)
    dq = jnp.swapaxes(dq_t, 1, 2).astype(q.dtype)
    dk = jnp.swapaxes(dk_t, 1, 2).astype(k.dtype)
    dv = jnp.swapaxes(dv_t, 1, 2).astype(v.dtype)
    return dq, dk, dv


flash_attention_pallas.defvjp(_fa_fwd, _fa_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def flash_attention_pallas_segmented(q, k, v, q_segment_ids, kv_segment_ids,
                                     causal=False, scale=None,
                                     block_q=DEFAULT_BLOCK_Q,
                                     block_k=DEFAULT_BLOCK_K):
    """Segment-masked (varlen/packed) flash attention.

    q/k/v: [batch, seq, heads, head_dim]; segment ids [batch, seq] int32.
    Tokens attend only to equal segment ids (intersected with causal);
    rows with no visible keys output zeros."""
    out, _ = _fas_fwd(q, k, v, q_segment_ids, kv_segment_ids, causal,
                      scale, block_q, block_k)
    return out


def _fas_fwd(q, k, v, q_seg, kv_seg, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t, lse = _flash_fwd(qt, kt, vt, q_seg, kv_seg, causal, scale,
                            block_q, block_k)
    out = jnp.swapaxes(out_t, 1, 2)
    return out, (q, k, v, q_seg, kv_seg, out, lse)


def _fas_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, q_seg, kv_seg, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t = jnp.swapaxes(out, 1, 2)
    do_t = jnp.swapaxes(g, 1, 2)
    dq_t, dk_t, dv_t = _flash_bwd(qt, kt, vt, out_t, lse, do_t, q_seg,
                                  kv_seg, causal, scale, block_q, block_k)
    dq = jnp.swapaxes(dq_t, 1, 2).astype(q.dtype)
    dk = jnp.swapaxes(dk_t, 1, 2).astype(k.dtype)
    dv = jnp.swapaxes(dv_t, 1, 2).astype(v.dtype)
    zseg = lambda s: np.zeros(s.shape, jax.dtypes.float0)
    return dq, dk, dv, zseg(q_seg), zseg(kv_seg)


flash_attention_pallas_segmented.defvjp(_fas_fwd, _fas_bwd)


def flash_attention_with_lse(q, k, v, causal=False, scale=None,
                             block_q=DEFAULT_BLOCK_Q,
                             block_k=DEFAULT_BLOCK_K):
    """Raw forward returning (out, lse) — the ring-attention inner block
    (online-softmax merge across ring steps needs the lse). [b,s,h,d] in,
    out [b,s,h,d], lse [b,h,s]. Not differentiable; ring attention
    implements its own backward over the ring."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t, lse = _flash_fwd(qt, kt, vt, None, None, causal, scale,
                            block_q, block_k)
    return jnp.swapaxes(out_t, 1, 2), lse


def flash_attention_bwd_block(q, k, v, out, lse, do, causal=False,
                              scale=None, block_q=DEFAULT_BLOCK_Q,
                              block_k=DEFAULT_BLOCK_K):
    """Raw backward for one (q-shard, kv-shard) block given the MERGED lse
    — the ring-attention backward inner step. Layouts as
    flash_attention_with_lse; returns (dq, dk, dv) with dk/dv float32
    [b, s, hk, d]."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t = jnp.swapaxes(out, 1, 2)
    do_t = jnp.swapaxes(do, 1, 2)
    dq_t, dk_t, dv_t = _flash_bwd(qt, kt, vt, out_t, lse, do_t, None, None,
                                  causal, scale, block_q, block_k)
    return (jnp.swapaxes(dq_t, 1, 2), jnp.swapaxes(dk_t, 1, 2),
            jnp.swapaxes(dv_t, 1, 2))
