"""Flash attention, Pallas TPU implementation (fwd + bwd).

Replaces the reference's third_party/flashattn CUDA kernels
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu). Blocked
online-softmax over KV tiles; LSE saved for the backward; causal masking
with early loop exit. GQA handled by head-index mapping in the forward and
group-summed dk/dv in the backward.

Layout contract (paddle convention at the API): q/k/v [batch, seq, heads,
head_dim]; kernels internally run [batch, heads, seq, head_dim]. head_dim
should be a multiple of 128 for MXU efficiency (64 works, half-utilized).

VMEM budget: K and V are held whole per (batch, kv-head) — fine up to
seq*dim*2B*2 ≈ 8MB (seq 16k @ d=128 bf16). Longer sequences belong to ring
attention (paddle_tpu.distributed.ring_attention) which shards seq over
the mesh.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret() -> bool:
    # interpreter mode on non-TPU backends (CPU tests / numerics oracle)
    return jax.default_backend() != "tpu" and not _on_tpu()


def _on_tpu() -> bool:
    try:
        return jax.devices()[0].platform not in ("cpu", "gpu")
    except Exception:
        return False

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, scale, causal,
                block_k, seq_k):
    # block shapes: q [1, 1, bq, d]; k/v [1, 1, seq_k, d]
    q = q_ref[0, 0].astype(jnp.float32) * scale          # [bq, d]
    bq = q.shape[0]
    qi = pl.program_id(2)
    q_offset = qi * bq

    num_kv = pl.cdiv(seq_k, block_k)
    if causal:
        # only blocks whose start <= last query row
        num_kv_run = jax.lax.div(q_offset + bq - 1, block_k) + 1
    else:
        num_kv_run = num_kv

    def body(kj, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)              # [bq, bk]
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_cur = jnp.max(s, axis=-1)                          # [bq]
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])                      # [bq, bk]
        corr = jnp.exp(m_prev - m_new)                       # [bq]
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        acc = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    d = q.shape[-1]
    acc0 = jnp.zeros((bq, d), jnp.float32)
    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, num_kv_run, body, (acc0, m0, l0))

    l_safe = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, 0, :, 0] = (m + jnp.log(l_safe)).astype(jnp.float32)


def _flash_fwd(q, k, v, causal, scale, block_q, block_k):
    """q [b,h,sq,d]; k/v [b,hk,sk,d] → out [b,h,sq,d], lse [b,h,sq]."""
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    group = h // hk
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    grid = (b, h, pl.cdiv(sq, bq))

    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               block_k=bk, seq_k=sk)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi, _g=group: (bi, hi // _g, 0, 0)),
            pl.BlockSpec((1, 1, sk, d),
                         lambda bi, hi, qi, _g=group: (bi, hi // _g, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ],
        interpret=_interpret(),
    )(q, k, v)
    return out, lse[..., 0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   *, scale, causal, block_k, seq_k):
    q = q_ref[0, 0].astype(jnp.float32)                     # [bq, d]
    do = do_ref[0, 0].astype(jnp.float32)
    lse = lse_ref[0, 0, :, 0]                               # [bq]
    delta = delta_ref[0, 0, :, 0]                           # [bq]
    bq = q.shape[0]
    qi = pl.program_id(2)
    q_offset = qi * bq

    num_kv = pl.cdiv(seq_k, block_k)
    if causal:
        num_kv_run = jax.lax.div(q_offset + bq - 1, block_k) + 1
    else:
        num_kv_run = num_kv

    def body(kj, dq):
        k_blk = k_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, 0, pl.ds(kj * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = q_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = kj * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                        # [bq, bk]
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale               # [bq, bk]
        return dq + jax.lax.dot_general(ds, k_blk, (((1,), (0,)), ((), ())),
                                        preferred_element_type=jnp.float32)

    dq0 = jnp.zeros_like(q)
    dq = jax.lax.fori_loop(0, num_kv_run, body, dq0)
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, *, scale, causal, block_q, seq_q):
    k_blk = k_ref[0, 0].astype(jnp.float32)                  # [bk, d]
    v_blk = v_ref[0, 0].astype(jnp.float32)
    bk = k_blk.shape[0]
    kj = pl.program_id(2)
    k_offset = kj * bk

    num_q = pl.cdiv(seq_q, block_q)
    if causal:
        # first q block that can see this k block
        first_q = jax.lax.div(k_offset, block_q)
    else:
        first_q = 0

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, 0, pl.ds(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = k_offset + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows >= cols, s, _NEG_INF)
        p = jnp.exp(s - lse[:, None])                        # [bq, bk]
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    d = k_blk.shape[-1]
    dk0 = jnp.zeros((bk, d), jnp.float32)
    dv0 = jnp.zeros((bk, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(first_q, num_q, body, (dk0, dv0))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd(q, k, v, out, lse, do, causal, scale, block_q, block_k):
    """All [b,h,s,d] (kv already expanded to h heads). Returns dq,dk,dv."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq = min(block_q, sq)
    bk = min(block_k, sk)
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32),
                    axis=-1)[..., None]                      # [b,h,sq,1]
    lse4 = lse[..., None]                                    # [b,h,sq,1]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_k=bk, seq_k=sk),
        grid=(b, h, pl.cdiv(sq, bq)),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sk, d), lambda bi, hi, qi: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bq, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, sq, d), q.dtype),
        interpret=_interpret(),
    )(q, k, v, do, lse4, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=bq, seq_q=sq),
        grid=(b, h, pl.cdiv(sk, bk)),
        in_specs=[
            pl.BlockSpec((1, 1, sq, d), lambda bi, hi, kj: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, kj: (bi, hi, kj, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, kj: (bi, hi, kj, 0)),
            pl.BlockSpec((1, 1, sq, d), lambda bi, hi, kj: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, kj: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, sq, 1), lambda bi, hi, kj: (bi, hi, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, kj: (bi, hi, kj, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, kj: (bi, hi, kj, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, sk, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, sk, d), q.dtype),
        ],
        interpret=_interpret(),
    )(q, k, v, do, lse4, delta)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_pallas(q, k, v, causal=False, scale=None,
                           block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K):
    """q/k/v: [batch, seq, heads, head_dim] (kv heads may be fewer: GQA)."""
    out, _ = _fa_fwd(q, k, v, causal, scale, block_q, block_k)
    return out


def _fa_fwd(q, k, v, causal, scale, block_q, block_k):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    qt = jnp.swapaxes(q, 1, 2)   # [b,h,s,d]
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    out_t, lse = _flash_fwd(qt, kt, vt, causal, scale, block_q, block_k)
    out = jnp.swapaxes(out_t, 1, 2)
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, res, g):
    q, k, v, out, lse = res
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    h = q.shape[2]
    hk = k.shape[2]
    group = h // hk
    qt = jnp.swapaxes(q, 1, 2)
    kt = jnp.swapaxes(k, 1, 2)
    vt = jnp.swapaxes(v, 1, 2)
    if group > 1:  # expand kv heads for the backward kernels
        kt = jnp.repeat(kt, group, axis=1)
        vt = jnp.repeat(vt, group, axis=1)
    out_t = jnp.swapaxes(out, 1, 2)
    do_t = jnp.swapaxes(g, 1, 2)
    dq_t, dk_t, dv_t = _flash_bwd(qt, kt, vt, out_t, lse, do_t, causal,
                                  scale, block_q, block_k)
    if group > 1:  # sum grads over each kv-head's query group
        b, _, sk, d = dk_t.shape
        dk_t = dk_t.reshape(b, hk, group, sk, d).sum(axis=2)
        dv_t = dv_t.reshape(b, hk, group, sk, d).sum(axis=2)
    dq = jnp.swapaxes(dq_t, 1, 2).astype(q.dtype)
    dk = jnp.swapaxes(dk_t, 1, 2).astype(k.dtype)
    dv = jnp.swapaxes(dv_t, 1, 2).astype(v.dtype)
    return dq, dk, dv


flash_attention_pallas.defvjp(_fa_fwd, _fa_bwd)
