"""Mixture-of-Experts dispatch/combine on raw arrays (GShard algorithm).

Replaces the reference's MoE stack
(/root/reference/python/paddle/incubate/distributed/models/moe/
moe_layer.py:263 MoELayer, MoEScatter/MoEGather PyLayers, global_scatter/
global_gather comm ops): instead of index-based scatter over NCCL
all-to-all, the TPU-native form is the dense dispatch/combine einsum —
one-hot capacity-slotted routing whose expert dimension GSPMD shards over
the 'ep' mesh axis, lowering the dispatch to an ICI all-to-all
automatically.
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

__all__ = ["topk_gating", "moe_dispatch_combine", "moe_mlp_forward",
           "moe_ragged_forward"]


def topk_gating(logits, top_k: int, capacity: int):
    """GShard top-k gating with capacity slots.

    logits [T, E] → (dispatch [T, E, C] bool-ish f32,
                     combine  [T, E, C] f32 weights,
                     aux_loss scalar,
                     stats dict: tokens_per_expert [E] (routed within
                     capacity), assigned_per_expert [E] (pre-capacity),
                     dropped_fraction scalar — the capacity-overflow
                     diagnostics the reference MoE surfaces via
                     moe/grad_clip + utils counters)
    """
    t, e = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)

    gates_list = []
    masks = []
    remaining = probs
    for _ in range(top_k):
        idx = jnp.argmax(remaining, axis=-1)
        mask = jax.nn.one_hot(idx, e, dtype=jnp.float32)
        gates_list.append((probs * mask).sum(-1))
        masks.append(mask)
        remaining = remaining * (1.0 - mask)

    # load-balancing aux loss (GShard eq. Switch-style): E * sum(me * ce)
    me = probs.mean(axis=0)                      # mean prob per expert
    ce = masks[0].mean(axis=0)                   # top-1 assignment fraction
    aux_loss = (me * ce).sum() * e

    # capacity assignment: position of each token within its expert queue
    dispatch = jnp.zeros((t, e, capacity), jnp.float32)
    combine = jnp.zeros((t, e, capacity), jnp.float32)
    # running per-expert fill across the k choices
    prior_fill = jnp.zeros((e,), jnp.float32)
    denom = sum(gates_list)
    denom = jnp.maximum(denom, 1e-9)
    for mask, gate in zip(masks, gates_list):
        pos = jnp.cumsum(mask, axis=0) - mask + prior_fill[None, :]  # [T,E]
        in_cap = (pos < capacity).astype(jnp.float32) * mask
        pos_idx = jnp.minimum(pos, capacity - 1).astype(jnp.int32)
        slot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)  # [T,E,C]
        d = in_cap[..., None] * slot
        dispatch = dispatch + d
        combine = combine + d * (gate / denom)[:, None, None]
        prior_fill = prior_fill + mask.sum(axis=0)

    assigned = sum(m.sum(axis=0) for m in masks)       # [E] pre-capacity
    routed = dispatch.sum(axis=(0, 2))                 # [E] within capacity
    dropped = 1.0 - routed.sum() / jnp.maximum(assigned.sum(), 1.0)
    stats = {"tokens_per_expert": routed,
             "assigned_per_expert": assigned,
             "dropped_fraction": dropped}
    return dispatch, combine, aux_loss, stats


def moe_dispatch_combine(x, gate_w, w1, w2, top_k: int,
                         capacity_factor: float, activation=jax.nn.gelu,
                         ep_sharding=None):
    """Full MoE FFN: x [B, S, D] → (out [B, S, D], aux_loss, stats).

    w1 [E, D, H], w2 [E, H, D]. When ep_sharding (a NamedSharding for the
    [E, C, D] expert-batch layout) is given, the dispatched tensor gets a
    sharding constraint so GSPMD all-to-alls tokens to expert shards.
    stats: see topk_gating (expert utilization + token-drop counters).
    """
    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    e = w1.shape[0]
    t = tokens.shape[0]
    capacity = max(1, int(capacity_factor * top_k * t / e))
    # round capacity to a lane-friendly multiple
    capacity = -(-capacity // 8) * 8

    logits = tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    dispatch, combine, aux, stats = topk_gating(logits, top_k, capacity)
    stats = dict(stats, capacity=jnp.float32(capacity))

    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(x.dtype), tokens)
    if ep_sharding is not None:
        expert_in = jax.lax.with_sharding_constraint(expert_in, ep_sharding)
    h = activation(jnp.einsum("ecd,edh->ech", expert_in, w1.astype(x.dtype)))
    expert_out = jnp.einsum("ech,ehd->ecd", h, w2.astype(x.dtype))
    if ep_sharding is not None:
        expert_out = jax.lax.with_sharding_constraint(expert_out, ep_sharding)
    out = jnp.einsum("tec,ecd->td", combine.astype(x.dtype), expert_out)
    return out.reshape(b, s, d), aux, stats


def _grouped_mm(lhs, rhs, group_sizes):
    """Grouped matmul over contiguous per-expert row segments:
    lhs [R, K] x rhs [E, K, N] -> [R, N], rows partitioned into E
    segments by group_sizes.

    lax.ragged_dot: measured r5 on the v5e at the bench geometry
    ([16384, 1024] x [8, 1024, 1408]), XLA's native lowering runs at
    121 TF/s (62% of peak) — faster than the Pallas megablox gmm
    kernel on this backend (6.6 ms default tiling, 1.7 ms best tiling
    vs 0.39 ms here), so the hand kernel is NOT used. The ragged MFU
    gap lives in dispatch/combine, not the matmuls.
    """
    return jax.lax.ragged_dot(lhs, rhs, group_sizes)


def moe_ragged_forward(x, gate_w, w1, w2, top_k: int,
                       activation=jax.nn.gelu, capacity_factor=None):
    """Sort-based DROPLESS MoE FFN (the large-E path, VERDICT r3 #7):
    x [B, S, D] → (out [B, S, D], aux_loss, stats).

    The dense GShard dispatch materializes [T, E, C] one-hot tensors —
    fine at E=4, ruinous at DeepSeek-scale E (the dispatch tensor dwarfs
    the activations). Here token→expert assignments are SORTED by
    expert id (a [T*k] argsort, static shape) and the expert FFNs run
    as grouped matmuls via jax.lax.ragged_dot over the contiguous
    per-expert segments — memory is O(T*k*D) regardless of E, and no
    token is ever dropped (no capacity), so dropped_fraction ≡ 0.

    Reference analog: the index-based MoEScatter/MoEGather path
    (/root/reference/python/paddle/incubate/distributed/models/moe/
    moe_layer.py:263) — the reference also routes by index, over NCCL;
    this is its on-chip form. For expert-parallel GSPMD sharding use
    the dense path (moe_dispatch_combine): ragged segment sizes are
    data-dependent, which GSPMD cannot shard over an 'ep' axis.
    capacity_factor is accepted for signature parity and ignored
    (dropless has no capacity).
    """
    b, s, d = x.shape
    tokens = x.reshape(b * s, d)
    t = tokens.shape[0]
    e = w1.shape[0]

    logits = tokens.astype(jnp.float32) @ gate_w.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)                    # [T, E]
    top_p, top_i = jax.lax.top_k(probs, top_k)                 # [T, k]
    gates = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)

    # aux loss: same Switch-style formula as the dense path (top-1 mask)
    ce = jax.nn.one_hot(top_i[:, 0], e, dtype=jnp.float32).mean(axis=0)
    aux_loss = (probs.mean(axis=0) * ce).sum() * e

    flat_expert = top_i.reshape(t * top_k)                     # [T*k]
    # flat layout is token-major (flat slot i = token i//k, choice i%k),
    # so the token index needs no stored array — int32 metadata only
    order = jnp.argsort(flat_expert, stable=True).astype(jnp.int32)
    sorted_tok = order // top_k
    xs = jnp.take(tokens, sorted_tok, axis=0)                  # [T*k, D]
    group_sizes = jnp.bincount(flat_expert, length=e).astype(jnp.int32)

    h = activation(_grouped_mm(xs, w1.astype(xs.dtype), group_sizes))
    ys = _grouped_mm(h, w2.astype(xs.dtype), group_sizes)
    # combine: weighted scatter-ADD back to token rows. Measured r5 on
    # the v5e (model-level A/B at the bench geometry): this XLA-fused
    # form runs the whole ragged model at 66.2k tok/s vs 53.6k for a
    # scatter-free rewrite (bijective-inverse Pallas permute + reshape
    # reduce, custom vjps) and 58.1k for a hybrid — the fused
    # multiply-into-scatter and its cheap gather transpose beat
    # "faster" index plumbing that breaks XLA fusion at custom_vjp
    # boundaries. Keep this form; don't re-learn the lesson.
    wsorted = gates.reshape(t * top_k)[order].astype(ys.dtype)
    out = jnp.zeros((t, d), ys.dtype).at[sorted_tok].add(
        ys * wsorted[:, None])

    stats = {"tokens_per_expert": group_sizes.astype(jnp.float32),
             "assigned_per_expert": group_sizes.astype(jnp.float32),
             "dropped_fraction": jnp.float32(0.0)}
    return out.reshape(b, s, d).astype(x.dtype), aux_loss, stats


moe_mlp_forward = moe_dispatch_combine
