"""Rotary position embedding on raw arrays (reference:
/root/reference/python/paddle/incubate/nn/functional/fused_rotary_position_embedding.py).
Pure jnp: XLA fuses the mul/add chain into surrounding ops; layout is
[batch, seq, heads, head_dim] (paddle convention)."""
from __future__ import annotations

import jax.numpy as jnp


def _rotate_half(x):
    x1, x2 = jnp.split(x, 2, axis=-1)
    return jnp.concatenate([-x2, x1], axis=-1)


def rope_reference(x, cos, sin):
    """x: [b, s, h, d]; cos/sin: broadcastable [1, s, 1, d]."""
    return x * cos + _rotate_half(x) * sin


def build_rope_cache(seq_len: int, head_dim: int, base: float = 10000.0,
                     dtype=jnp.float32):
    inv_freq = 1.0 / (base ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(t, inv_freq)  # [s, d/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)  # [s, d]
    cos = jnp.cos(emb)[None, :, None, :].astype(dtype)
    sin = jnp.sin(emb)[None, :, None, :].astype(dtype)
    return cos, sin


def apply_rotary_pos_emb(q, k, cos=None, sin=None, position_ids=None,
                         base: float = 10000.0):
    """Fused-RoPE API parity: q/k [b, s, h, d]; builds cache if absent."""
    if cos is None:
        cos, sin = build_rope_cache(q.shape[1], q.shape[-1], base, q.dtype)
    if position_ids is not None:
        cos = jnp.take(cos[0], position_ids, axis=0)[:, :, None, :] if cos.shape[0] == 1 else cos
        sin = jnp.take(sin[0], position_ids, axis=0)[:, :, None, :] if sin.shape[0] == 1 else sin
    q_out = rope_reference(q, cos.astype(q.dtype), sin.astype(q.dtype))
    k_out = rope_reference(k, cos.astype(k.dtype), sin.astype(k.dtype))
    return q_out, k_out
