"""Flash attention on raw arrays.

Replaces the reference's third_party/flashattn CUDA binding
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu; python API
/root/reference/python/paddle/nn/functional/flash_attention.py:146).

Two paths:
- ``flash_attention_reference``: jnp online-softmax-free reference (numerics
  oracle + CPU/test path). XLA fuses this well for moderate sequence
  lengths.
- Pallas TPU kernel (paddle_tpu/ops/pallas/flash_attention.py): blocked
  fwd/bwd with online softmax, used automatically on TPU backends for
  long sequences.

Layout is paddle's: q/k/v [batch, seq, num_heads, head_dim].
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _sdpa_core(q, k, v, bias, causal, scale):
    """[b, s, h, d] reference attention with f32 softmax accumulation."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    if kv_heads != h:  # grouped-query attention: repeat kv heads
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        qi = jnp.arange(sq)[:, None] + (sk - sq)
        ki = jnp.arange(sk)[None, :]
        mask = qi >= ki
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def flash_attention_reference(q, k, v, attn_mask=None, causal=False,
                              dropout=0.0, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _sdpa_core(q, k, v, attn_mask, causal, scale)


def _pick_block(seq: int):
    for blk in (512, 256, 128):
        if seq % blk == 0:
            return blk
    return None


def flash_attention(q, k, v, attn_mask=None, causal=False, dropout=0.0,
                    scale=None, return_softmax=False):
    """Differentiable flash attention on raw arrays.

    On TPU backends dispatches to the Pallas kernel (custom VJP) when
    shapes qualify (no mask, seq divisible by a block size, head_dim MXU
    friendly); otherwise the jnp reference (XLA still fuses well). Both
    paths match numerically up to f32 accumulation order.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    from ..utils.flags import FLAGS
    use_pallas = (getattr(FLAGS, "use_pallas_kernels", True)
                  and jax.default_backend() not in ("cpu", "gpu")
                  and attn_mask is None and dropout == 0.0
                  and q.shape[-1] in (64, 128, 256)
                  and q.shape[1] >= 512 and k.shape[1] >= 512)
    if use_pallas:
        bq = _pick_block(q.shape[1])
        bk = _pick_block(k.shape[1])
        if bq is not None and bk is not None:
            from .pallas.flash_attention import flash_attention_pallas
            return flash_attention_pallas(q, k, v, causal, scale, bq, bk)
    return _sdpa_core(q, k, v, attn_mask, causal, scale)
