"""Flash attention on raw arrays.

Replaces the reference's third_party/flashattn CUDA binding
(/root/reference/paddle/phi/kernels/gpu/flash_attn_kernel.cu; python API
/root/reference/python/paddle/nn/functional/flash_attention.py:146).

Two paths:
- ``flash_attention_reference``: jnp online-softmax-free reference (numerics
  oracle + CPU/test path). XLA fuses this well for moderate sequence
  lengths.
- Pallas TPU kernel (paddle_tpu/ops/pallas/flash_attention.py): blocked
  fwd/bwd with online softmax, used automatically on TPU backends for
  long sequences.

Layout is paddle's: q/k/v [batch, seq, num_heads, head_dim].
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def _sdpa_core(q, k, v, bias, causal, scale, dropout=0.0,
               dropout_key=None):
    """[b, s, h, d] reference attention with f32 softmax accumulation.
    dropout (with a key) is applied to the attention probabilities,
    upscale-in-train — the reference flashattn semantics."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    if kv_heads != h:  # grouped-query attention: repeat kv heads
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if bias is not None:
        logits = logits + bias.astype(jnp.float32)
    if causal:
        qi = jnp.arange(sq)[:, None] + (sk - sq)
        ki = jnp.arange(sk)[None, :]
        mask = qi >= ki
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    if dropout and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout,
                                    probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0)
    out = jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)
    return out


def flash_attention_reference(q, k, v, attn_mask=None, causal=False,
                              dropout=0.0, scale=None):
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    return _sdpa_core(q, k, v, attn_mask, causal, scale)


def _pick_block(seq: int):
    for blk in (512, 256, 128):
        if seq % blk == 0:
            return blk
    return None


def pallas_attention_plan(q, k, min_seq: int = 512):
    """THE eligibility gate for the Pallas attention kernels (single
    source of truth — flash_attention, flash_attention_segmented, and
    ring attention all route through here). Returns (block_q, block_k)
    when the kernel applies, else None."""
    if jax.default_backend() in ("cpu", "gpu"):
        return None
    from ..utils.flags import FLAGS
    if not getattr(FLAGS, "use_pallas_kernels", True):
        return None
    if q.shape[-1] not in (64, 128, 256):
        return None
    if q.shape[1] < min_seq or k.shape[1] < min_seq:
        return None
    bq = _pick_block(q.shape[1])
    bk = _pick_block(k.shape[1])
    if bq is None or bk is None:
        return None
    return bq, bk


def flash_attention(q, k, v, attn_mask=None, causal=False, dropout=0.0,
                    scale=None, return_softmax=False, dropout_key=None):
    """Differentiable flash attention on raw arrays.

    On TPU backends dispatches to the Pallas kernel (custom VJP) when
    shapes qualify (no mask, no dropout, seq divisible by a block size,
    head_dim MXU friendly); otherwise the jnp reference (XLA still fuses
    well). Both paths match numerically up to f32 accumulation order.
    Attention dropout requires a dropout_key (the dense path applies it
    to the probs); dropout > 0 without a key is an error — never a
    silent no-op.
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if dropout and dropout_key is None:
        raise ValueError(
            "flash_attention: dropout > 0 needs dropout_key (the "
            "nn.functional wrappers pass one from the RNG stream when "
            "training)")
    plan = pallas_attention_plan(q, k) if (attn_mask is None
                                           and dropout == 0.0) else None
    if plan is not None:
        from .pallas.flash_attention import flash_attention_pallas
        return flash_attention_pallas(q, k, v, causal, scale, *plan)
    return _sdpa_core(q, k, v, attn_mask, causal, scale, dropout,
                      dropout_key)


# ---------------------------------------------------------------------------
# segment-masked (varlen / packed-sequence) attention
# ---------------------------------------------------------------------------

def _sdpa_segmented_core(q, k, v, q_seg, kv_seg, causal, scale):
    """Dense oracle for segment-masked attention. q/k/v [b,s,h,d]; segment
    ids [b,s]. Fully-masked query rows yield zero output."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    kv_heads = k.shape[2]
    if kv_heads != h:
        rep = h // kv_heads
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    mask = (q_seg[:, None, :, None] == kv_seg[:, None, None, :])  # [b,1,q,k]
    if causal:
        qi = jnp.arange(sq)[:, None] + (sk - sq)
        ki = jnp.arange(sk)[None, :]
        mask = jnp.logical_and(mask, (qi >= ki)[None, None])
    logits = jnp.where(mask, logits, _NEG_INF)
    # guarded softmax: rows with no visible keys -> zeros, not NaN
    m = jnp.max(logits, axis=-1, keepdims=True)
    p = jnp.where(mask, jnp.exp(logits - m), 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    probs = p / jnp.maximum(l, 1e-30)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(v.dtype), v)


def flash_attention_segmented(q, k, v, q_segment_ids, kv_segment_ids,
                              causal=False, scale=None):
    """Segment-masked attention, Pallas on TPU / dense reference elsewhere.
    Parity: the varlen CUDA path of
    /root/reference/python/paddle/nn/functional/flash_attention.py:302."""
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    plan = pallas_attention_plan(q, k)
    if plan is not None:
        from .pallas.flash_attention import (
            flash_attention_pallas_segmented)
        return flash_attention_pallas_segmented(
            q, k, v, q_segment_ids, kv_segment_ids, causal, scale, *plan)
    return _sdpa_segmented_core(q, k, v, q_segment_ids, kv_segment_ids,
                                causal, scale)


def segments_from_cu_seqlens(cu_seqlens, total: int, pad_id: int = -1):
    """cu_seqlens [n+1] (cumulative lengths, cu[0]=0) -> per-token segment
    ids [total]; tokens at/after cu[-1] get pad_id (attend nothing when
    pad ids differ between q and kv)."""
    pos = jnp.arange(total, dtype=jnp.int32)
    seg = jnp.searchsorted(cu_seqlens[1:].astype(jnp.int32), pos,
                           side="right").astype(jnp.int32)
    return jnp.where(pos < cu_seqlens[-1], seg, jnp.int32(pad_id))


def flash_attn_varlen(q, k, v, cu_seqlens_q, cu_seqlens_k,
                      max_seqlen_q=None, max_seqlen_k=None, scale=None,
                      causal=False):
    """Unpadded (packed) flash attention. q [total_q, h, d]; k/v
    [total_k, hk, d]; cu_seqlens_* [n+1] int32. Causal masking is
    per-sequence (requires the usual self-attention packing where q and k
    positions align). Returns packed out [total_q, h, d].

    Parity: flash_attn_unpadded
    (/root/reference/python/paddle/nn/functional/flash_attention.py:302,
    CUDA kernels paddle/phi/kernels/gpu/flash_attn_kernel.cu)."""
    total_q, total_k = q.shape[0], k.shape[0]
    seg_q = segments_from_cu_seqlens(cu_seqlens_q, total_q, pad_id=-1)
    seg_k = segments_from_cu_seqlens(cu_seqlens_k, total_k, pad_id=-2)
    out = flash_attention_segmented(
        q[None], k[None], v[None], seg_q[None], seg_k[None],
        causal=causal, scale=scale)
    return out[0]
