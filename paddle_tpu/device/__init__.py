"""paddle.device namespace parity
(/root/reference/python/paddle/device/__init__.py): device selection /
introspection. Streams and events have no user-facing analog on TPU
(XLA owns scheduling); the Stream/Event API is accepted as no-ops so
ported code runs."""
from __future__ import annotations

from typing import List, Optional

from ..framework.core import get_device, set_device  # noqa: F401

__all__ = ["set_device", "get_device", "get_all_device_type",
           "get_available_device", "get_available_custom_device",
           "get_device_count", "is_compiled_with_cuda",
           "is_compiled_with_xpu", "is_compiled_with_custom_device",
           "synchronize", "Stream", "Event", "current_stream",
           "device_count", "cuda"]


def get_all_device_type() -> List[str]:
    import jax
    return sorted({d.platform for d in jax.devices()})


def get_available_device() -> List[str]:
    import jax
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device() -> List[str]:
    return [d for d in get_available_device()
            if not d.startswith(("cpu", "tpu", "gpu"))]


def get_device_count() -> int:
    import jax
    return jax.device_count()


device_count = get_device_count


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_custom_device(device_type: str) -> bool:
    return device_type in get_all_device_type()


def synchronize(device=None):
    """Block until queued work on every local device completes
    (reference paddle.device.synchronize). Per-device programs execute
    in dispatch order, so a trivial computation enqueued now on each
    device becomes ready only after everything already queued there."""
    import jax
    import jax.numpy as jnp
    # a jitted computation (not a bare transfer) lands on each device's
    # execution queue behind everything already enqueued there
    noop = jax.jit(lambda x: x + 0)
    for d in jax.local_devices():
        noop(jax.device_put(jnp.zeros(()), d)).block_until_ready()


class Stream:
    """Accepted for API compat; XLA schedules its own streams."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        pass

    def wait_stream(self, stream):
        pass

    def record_event(self, event=None):
        return event or Event()


class Event:
    def __init__(self, device=None, enable_timing=False, blocking=False):
        pass

    def record(self, stream=None):
        pass

    def query(self) -> bool:
        return True

    def synchronize(self):
        synchronize()


def current_stream(device=None) -> Stream:
    return Stream(device)


class _CudaNamespace:
    """paddle.device.cuda shim: reports zero CUDA devices."""

    @staticmethod
    def device_count() -> int:
        return 0

    @staticmethod
    def is_available() -> bool:
        return False


cuda = _CudaNamespace()
