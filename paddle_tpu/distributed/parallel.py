"""Parallel env + DataParallel (parity:
/root/reference/python/paddle/distributed/parallel.py — init_parallel_env:
943, DataParallel:202).

TPU-native: single-controller JAX. A "rank" in the reference's
process-per-GPU world maps to a device here; multi-host runs use
jax.distributed (coordinator = the TCPStore analog) and keep the same API.
DataParallel needs no gradient reducer (the reference's EagerReducer,
/root/reference/paddle/fluid/distributed/collective/reducer.h:88): with
params replicated and the batch sharded over the 'dp' axis, XLA inserts the
gradient all-reduce during the backward build — bucketing/fusion included.
"""
from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np

from ..framework.core import Tensor
from .mesh import ProcessMesh
from .placement import Replicate, Shard

__all__ = ["init_parallel_env", "get_rank", "get_world_size", "ParallelEnv",
           "DataParallel"]

_initialized = False


def init_parallel_env():
    """Bootstraps multi-host JAX if coordinator env vars are present
    (PADDLE_MASTER / MASTER_ADDR / JAX coordination vars); no-op otherwise.
    The reference's TCPStore rendezvous
    (/root/reference/paddle/phi/core/distributed/store/tcp_store.h:121) is
    jax.distributed's coordination service."""
    global _initialized
    if _initialized:
        return
    coord = os.environ.get("PADDLE_MASTER") or os.environ.get("MASTER_ADDR")
    nprocs = int(os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    pid = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
    if coord and nprocs > 1:
        port = os.environ.get("MASTER_PORT", "8476")
        addr = coord if ":" in coord else f"{coord}:{port}"
        jax.distributed.initialize(coordinator_address=addr,
                                   num_processes=nprocs, process_id=pid)
    _initialized = True


def get_rank(group=None) -> int:
    return jax.process_index()


def get_world_size(group=None) -> int:
    # device-granular world (see module docstring): total chips
    return jax.device_count()


class ParallelEnv:
    @property
    def rank(self):
        return get_rank()

    @property
    def world_size(self):
        return get_world_size()

    @property
    def local_rank(self):
        return 0

    @property
    def nranks(self):
        return get_world_size()

    @property
    def device_id(self):
        return 0


class DataParallel:
    """paddle.DataParallel parity. Wraps a Layer: parameters are replicated
    over a 1-D dp mesh, inputs get sharded on the batch dim. Both eager
    (computation-follows-sharding) and jitted paths then run data-parallel
    with XLA-inserted gradient all-reduce."""

    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        object.__setattr__(self, "_layers", layers)
        n = jax.device_count()
        mesh = ProcessMesh(np.arange(n), ["dp"])
        object.__setattr__(self, "_mesh", mesh)
        if n > 1:
            from .api import shard_tensor
            for _, p in layers.named_parameters():
                sharded = jax.device_put(
                    p._value, mesh.named_sharding(None))
                p._replace(sharded)
            for _, b in layers.named_buffers():
                if b is not None:
                    b._replace(jax.device_put(
                        b._value, mesh.named_sharding(None)))

    def __call__(self, *inputs, **kwargs):
        n = jax.device_count()
        if n > 1:
            sharded_inputs = []
            for x in inputs:
                if isinstance(x, Tensor) and x.shape and x.shape[0] % n == 0:
                    arr = jax.device_put(
                        x._value, self._mesh.named_sharding("dp"))
                    t = Tensor(arr, x.stop_gradient, x.name)
                    sharded_inputs.append(t)
                else:
                    sharded_inputs.append(x)
            inputs = tuple(sharded_inputs)
        return self._layers(*inputs, **kwargs)

    def __getattr__(self, name):
        return getattr(self._layers, name)

    def __setattr__(self, name, value):
        setattr(self._layers, name, value)

    # common passthroughs made explicit for clarity
    def forward(self, *a, **kw):
        return self.__call__(*a, **kw)

    def state_dict(self, *a, **kw):
        return self._layers.state_dict(*a, **kw)

    def set_state_dict(self, *a, **kw):
        return self._layers.set_state_dict(*a, **kw)

    def scale_loss(self, loss):
        return loss

    def apply_collective_grads(self):
        pass
