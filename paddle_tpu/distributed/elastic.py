"""Elastic membership + fault tolerance over the native KV store.

Reference: ElasticManager (/root/reference/python/paddle/distributed/
fleet/elastic/manager.py:126) — etcd leases + heartbeats (:254-296),
watching the node set, levels FAULT_TOLERANCE=1 / ELASTIC=2 (:42-44).
Here the store is the C++ TCP KV (no TTL primitives), so liveness is
timestamped heartbeats: a node is dead when its beat is older than
2*heartbeat_interval. On membership change the manager reports a new
world spec so the launcher can re-rendezvous (restart generation bump) —
on TPU pods that means re-forming the jax.distributed world and resuming
from the latest checkpoint.
"""
from __future__ import annotations

import json
import threading
import time
from enum import Enum
from typing import Callable, Dict, List, Optional

from ..core.native import TCPStore


class ElasticLevel(Enum):
    OFF = 0
    FAULT_TOLERANCE = 1   # fixed world size, restart on failure
    ELASTIC = 2           # world size may shrink/grow within [min, max]


class ElasticStatus(Enum):
    RUNNING = "running"
    RESTART = "restart"
    COMPLETED = "completed"
    ERROR = "error"


class ElasticManager:
    def __init__(self, store: TCPStore, job_id: str, rank: int,
                 min_nodes: int, max_nodes: int,
                 level: ElasticLevel = ElasticLevel.FAULT_TOLERANCE,
                 heartbeat_interval: float = 2.0):
        self.store = store
        self.job_id = job_id
        self.rank = rank
        self.min_nodes = min_nodes
        self.max_nodes = max(max_nodes, min_nodes)
        self.level = level
        self.interval = heartbeat_interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._on_change: Optional[Callable[[List[int]], None]] = None
        self._last_alive: List[int] = []

    def _k(self, *parts) -> str:
        return "/".join(("elastic", self.job_id) + parts)

    # -- membership --------------------------------------------------------
    def register(self):
        self.store.set(self._k(f"node{self.rank}"),
                       json.dumps({"t": time.time()}).encode())
        self.store.add(self._k("registered"), 1)

    def heartbeat(self):
        self.store.set(self._k(f"node{self.rank}"),
                       json.dumps({"t": time.time()}).encode())

    def alive_nodes(self) -> List[int]:
        """Ranks whose heartbeat is fresh."""
        alive = []
        horizon = 2.0 * self.interval
        for r in range(self.max_nodes):
            key = self._k(f"node{r}")
            try:
                if not self.store.check(key):
                    continue
                beat = json.loads(self.store.get(key, timeout=5))
                if time.time() - beat["t"] <= horizon:
                    alive.append(r)
            except Exception:
                continue
        return alive

    def healthy(self, alive: Optional[List[int]] = None) -> bool:
        alive = self.alive_nodes() if alive is None else alive
        return len(alive) >= self.min_nodes

    # -- watch loop --------------------------------------------------------
    def start(self, on_change: Optional[Callable[[List[int]], None]] = None):
        """Start heartbeating + watching in a daemon thread. on_change is
        called with the new alive set whenever membership changes."""
        self._on_change = on_change
        self.register()
        self._last_alive = self.alive_nodes()

        def loop():
            while not self._stop.wait(self.interval):
                self.heartbeat()
                alive = self.alive_nodes()
                if set(alive) != set(self._last_alive):
                    # fire the callback BEFORE updating _last_alive so a
                    # handler calling plan() still sees the transition
                    if self._on_change is not None:
                        self._on_change(alive)
                    self._last_alive = alive

        self._thread = threading.Thread(target=loop, daemon=True)
        self._thread.start()

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- decisions ---------------------------------------------------------
    def plan(self, alive: Optional[List[int]] = None) -> ElasticStatus:
        """What should the job do given current membership?"""
        alive = self.alive_nodes() if alive is None else alive
        n = len(alive)
        if n >= self.min_nodes and set(self._last_alive) == set(alive):
            return ElasticStatus.RUNNING
        if self.level == ElasticLevel.OFF:
            return ElasticStatus.ERROR if n < self.min_nodes \
                else ElasticStatus.RUNNING
        if n < self.min_nodes:
            # below quorum: fault-tolerance waits (RESTART when it
            # recovers); elastic likewise cannot shrink below min
            return ElasticStatus.ERROR
        return ElasticStatus.RESTART
