"""paddle.distributed long-tail parity (reference
python/paddle/distributed/__init__.py exports beyond the core that
paddle_tpu.distributed already implements).

REAL: enums (ReduceType/ParallelMode), object collectives (trivially
exact in single-controller mode — every process sees the global
objects), alltoall aliases, split (megatron-style layer splitter),
process-group state queries, checkpoint re-exports, shard_dataloader,
dtensor to_static/DistModel wrappers, distributed.io.
LOUD STUBS: parameter-server datasets/entries (COVERAGE.md descope).
"""
from __future__ import annotations

from typing import List, Optional

__all__ = [
    "ReduceType", "ParallelMode", "DistAttr", "DistModel",
    "all_gather_object", "broadcast_object_list", "scatter_object_list",
    "alltoall", "alltoall_single", "split", "destroy_process_group",
    "get_backend", "is_available", "is_initialized", "gloo_barrier",
    "gloo_init_parallel_env", "gloo_release", "load_state_dict",
    "save_state_dict", "shard_dataloader", "to_static", "io",
    "InMemoryDataset", "QueueDataset", "CountFilterEntry",
    "ProbabilityEntry", "ShowClickEntry",
]


class ReduceType:
    """Reference paddle.distributed.ReduceType (auto-parallel partial
    reductions)."""
    kRedSum = 0
    kRedMax = 1
    kRedMin = 2
    kRedProd = 3
    kRedAvg = 4
    kRedAny = 5
    kRedAll = 6


class ParallelMode:
    """Reference paddle.distributed.ParallelMode."""
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


def DistAttr(mesh=None, sharding_specs=None):
    """Reference dist_attr factory: here the (mesh, placements) pair IS
    the dist attr — returns it as a dict consumable by shard_tensor."""
    return {"process_mesh": mesh, "sharding_specs": sharding_specs}




class DistModel:
    """Reference auto-parallel DistModel (api.py:983): a to_static'd
    model + optimizer driven by the compiled sharded step. Thin wrapper
    over fleet.auto.Engine."""

    def __init__(self, layer, loader=None, loss=None, optimizer=None,
                 strategy=None, metrics=None):
        from .auto_parallel import Engine
        self._engine = Engine(model=layer, loss=loss,
                              optimizer=optimizer, strategy=strategy)
        self._mode = "train" if optimizer is not None else "predict"

    def train(self):
        self._mode = "train"

    def eval(self):
        self._mode = "eval"

    def predict(self):
        self._mode = "predict"

    def __call__(self, *args):
        if self._mode == "train":
            if len(args) != 2:
                raise ValueError(
                    "DistModel train call takes exactly (input, label); "
                    f"got {len(args)} argument(s)")
            self._engine.prepare("train")
            x, y = args
            return self._engine._train_step(x, y)
        self._engine.prepare("eval")
        return self._engine._forward(args)


def to_static(layer, loader=None, loss=None, optimizer=None,
              strategy=None):
    """Reference paddle.distributed.to_static → DistModel."""
    return DistModel(layer, loader=loader, loss=loss,
                     optimizer=optimizer, strategy=strategy)


# -- object collectives ------------------------------------------------------
# Single-controller SPMD: every process executes this SAME python, so
# "the object on rank r" is already globally visible — the semantics of
# the reference (pickle over the comm ring) reduce to identity/copies.

def all_gather_object(object_list: List, obj, group=None):
    import copy
    from . import get_world_size
    n = max(1, get_world_size())
    object_list.clear()
    object_list.extend(copy.deepcopy(obj) for _ in range(n))


def broadcast_object_list(object_list: List, src=0, group=None):
    return object_list


def scatter_object_list(out_object_list: List, in_object_list=None,
                        src=0, group=None):
    from . import get_rank
    if in_object_list is None:
        raise ValueError("scatter_object_list needs in_object_list on "
                         "the src rank (single-controller: pass it)")
    out_object_list.clear()
    out_object_list.append(in_object_list[get_rank()])


# -- aliases / state ---------------------------------------------------------

def alltoall(out_tensor_list, in_tensor_list, group=None, sync_op=True):
    from .collective import all_to_all
    return all_to_all(out_tensor_list, in_tensor_list, group=group,
                      sync_op=sync_op)


def alltoall_single(out_tensor, in_tensor, in_split_sizes=None,
                    out_split_sizes=None, group=None, sync_op=True):
    """Single-tensor all-to-all (reference alltoall_single): each rank's
    dim 0 is split evenly into nranks chunks; chunk d goes to rank d.
    Rank-stacked emulation: in_tensor is [nranks_src, nranks*k, ...];
    out_tensor receives [nranks_dst, nranks*k, ...] in place (chunk s of
    dst's row came from src s). Uneven splits are a loud descope — the
    even-split path would silently move the wrong slices (MoE token
    routing uses uneven splits in the reference)."""
    from ..framework.core import Tensor
    from .collective import _group, all_to_all
    g = _group(group)
    n = g.nranks
    arr = in_tensor._value
    if arr.ndim < 2 or arr.shape[0] != n or arr.shape[1] % n != 0:
        raise ValueError(
            f"alltoall_single expects rank-stacked [nranks={n}, "
            f"nranks*k, ...]; got shape {tuple(arr.shape)}")
    k = arr.shape[1] // n
    for name_, sizes in (("in_split_sizes", in_split_sizes),
                         ("out_split_sizes", out_split_sizes)):
        if sizes is None:
            continue
        if list(sizes) != [k] * n:
            raise NotImplementedError(
                f"alltoall_single with uneven {name_}={list(sizes)} is "
                "not supported (even chunk here is "
                f"{k}); pad to even splits or use ops.moe ragged "
                "dispatch (COVERAGE.md descope)")
    if (tuple(out_tensor._value.shape) != tuple(arr.shape)
            or out_tensor._value.dtype != arr.dtype):
        raise ValueError(
            f"alltoall_single out_tensor {tuple(out_tensor._value.shape)}"
            f"/{out_tensor._value.dtype} must match in_tensor "
            f"{tuple(arr.shape)}/{arr.dtype}")
    # [src, dst, k, ...] -> all_to_all -> [dst, src, k, ...]
    chunks = arr.reshape((n, n, k) + tuple(arr.shape[2:]))
    received: list = []
    task = all_to_all(received, Tensor(chunks), group=group,
                      sync_op=sync_op)
    out = received[0]._value.reshape((n, n * k) + tuple(arr.shape[2:]))
    out_tensor._replace(out)
    return task


def split(x, size, operation, axis=0, num_partitions=1, gather_out=True,
          weight_attr=None, bias_attr=None, name=None):
    """Reference paddle.distributed.split auto-parallelizes a layer op
    (embedding/linear) across ranks and applies it to x (reference
    python/paddle/distributed/collective.py split). The TPU-native form
    is the mpu layer set; this wrapper builds one, forwards
    weight_attr/bias_attr, validates num_partitions against the mesh's
    'mp' degree (GSPMD partitions by mesh axis, not an ad-hoc count),
    and returns layer(x) — or, as a documented extension, the layer
    itself when x is None."""
    from .fleet import (ColumnParallelLinear, RowParallelLinear,
                        VocabParallelEmbedding)
    from .fleet.mpu import _get_mesh
    mesh = _get_mesh()
    mp = mesh.get_dim_size("mp") if mesh is not None else 1
    if num_partitions not in (1, mp):
        raise ValueError(
            f"num_partitions={num_partitions} does not match the mesh "
            f"'mp' degree ({mp}); GSPMD partitions by mesh axis — "
            "resize the mesh instead of passing a partition count")
    if operation == "embedding":
        layer = VocabParallelEmbedding(size[0], size[1],
                                       weight_attr=weight_attr, name=name)
    elif operation == "linear":
        if axis not in (0, 1):
            raise ValueError(
                f"split(..., 'linear') axis must be 0 (row-parallel) or "
                f"1 (column-parallel); got {axis}")
        if axis == 1:
            layer = ColumnParallelLinear(
                size[0], size[1], weight_attr=weight_attr,
                bias_attr=bias_attr, gather_output=gather_out, name=name)
        else:
            if not gather_out:
                raise NotImplementedError(
                    "row-parallel split with gather_out=False (partial "
                    "sums left unreduced) cannot be expressed through "
                    "GSPMD's replicated-output constraint; use "
                    "RowParallelLinear with a manual shard_map if you "
                    "need the partials")
            layer = RowParallelLinear(size[0], size[1],
                                      weight_attr=weight_attr,
                                      bias_attr=bias_attr, name=name)
    else:
        raise ValueError(f"unsupported split operation {operation!r}")
    return layer if x is None else layer(x)


_pg_alive = True


def destroy_process_group(group=None):
    global _pg_alive
    _pg_alive = False


def get_backend(group=None) -> str:
    import jax
    return "xla:" + jax.default_backend()


def is_available() -> bool:
    return True


def is_initialized() -> bool:
    from . import parallel
    return getattr(parallel, "_initialized", False) and _pg_alive


def gloo_init_parallel_env(rank_id, rank_num, server_endpoint):
    from . import init_parallel_env
    return init_parallel_env()


def gloo_barrier():
    from .collective import barrier
    return barrier()


def gloo_release():
    destroy_process_group()


def load_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0):
    from .checkpoint import load_state_dict as _l
    return _l(state_dict, path)


def save_state_dict(state_dict, path, process_group=None,
                    coordinator_rank=0):
    from .checkpoint import save_state_dict as _s
    return _s(state_dict, path)


def shard_dataloader(dataloader, meshes=None, shard_dims=None,
                     input_keys=None, is_dataset_splitted=False):
    """Reference shard_dataloader wraps a loader so each rank reads its
    split. Single-controller: the DataLoader already yields global
    batches that shard_tensor placements split — return it unchanged
    (documented identity, not a silent stub: the semantics hold)."""
    return dataloader


class _IONamespace:
    """paddle.distributed.io (save/load for distributed programs)."""

    @staticmethod
    def save_persistables(executor, dirname, main_program=None,
                          filename=None):
        from ..static.compat import save
        return save(main_program, dirname + "/persistables")

    @staticmethod
    def load_persistables(executor, dirname, main_program=None,
                          filename=None):
        from ..static.compat import load
        return load(main_program, dirname + "/persistables")


io = _IONamespace()


# -- parameter-server era (descoped; COVERAGE.md) ----------------------------

def _ps_descope(name):
    raise NotImplementedError(
        f"{name} belongs to the parameter-server training stack, "
        "deliberately descoped on TPU (SURVEY §2.5 item 15, "
        "COVERAGE.md); use array-sharded embeddings (EP/MoE recipes) "
        "instead")


class InMemoryDataset:
    def __init__(self, *a, **k):
        _ps_descope("InMemoryDataset")


class QueueDataset:
    def __init__(self, *a, **k):
        _ps_descope("QueueDataset")


class CountFilterEntry:
    def __init__(self, *a, **k):
        _ps_descope("CountFilterEntry")


class ProbabilityEntry:
    def __init__(self, *a, **k):
        _ps_descope("ProbabilityEntry")


class ShowClickEntry:
    def __init__(self, *a, **k):
        _ps_descope("ShowClickEntry")
