"""ProcessMesh (parity:
/root/reference/python/paddle/distributed/auto_parallel/process_mesh.py:72,
C++ /root/reference/paddle/phi/core/distributed/auto_parallel/process_mesh.h:34).

A ProcessMesh is a named N-D grid of devices; it materializes as a
jax.sharding.Mesh whose axis names carry the parallelism meaning
(dp/fsdp/tp/pp/sep/ep). GSPMD inserts the collectives implied by
NamedSharding placements over these axes — the reference's 132 SPMD rules
(/root/reference/paddle/phi/infermeta/spmd_rules/rules.cc:38) collapse into
XLA's propagation pass.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import jax
import numpy as np

__all__ = ["ProcessMesh", "get_mesh", "set_mesh", "auto"]


class ProcessMesh:
    def __init__(self, mesh, dim_names: Optional[Sequence[str]] = None,
                 process_ids=None):
        arr = np.asarray(mesh, dtype=np.int64)
        if arr.ndim == 0:
            arr = arr.reshape(1)
        self._mesh_arr = arr
        if dim_names is None:
            dim_names = [f"d{i}" for i in range(arr.ndim)]
        if len(dim_names) != arr.ndim:
            raise ValueError("dim_names must match mesh ndim")
        self._dim_names = list(dim_names)
        self._jax_mesh = None

    # -- paddle API ----------------------------------------------------------
    @property
    def shape(self) -> List[int]:
        return list(self._mesh_arr.shape)

    @property
    def ndim(self) -> int:
        return self._mesh_arr.ndim

    @property
    def dim_names(self) -> List[str]:
        return list(self._dim_names)

    @property
    def mesh(self):
        return self._mesh_arr

    @property
    def process_ids(self) -> List[int]:
        return self._mesh_arr.reshape(-1).tolist()

    @property
    def size(self) -> int:
        return int(self._mesh_arr.size)

    def get_dim_size(self, dim_name: str) -> int:
        return self._mesh_arr.shape[self._dim_names.index(dim_name)]

    def get_mesh_with_dim(self, dim_name: str, index=None):
        """Sub-mesh slicing along a named dim (paddle parity)."""
        axis = self._dim_names.index(dim_name)
        moved = np.moveaxis(self._mesh_arr, axis, 0)
        names = [dim_name] + [n for n in self._dim_names if n != dim_name]
        if index is None:
            return ProcessMesh(moved, names)
        return ProcessMesh(moved[index], names[1:])

    def __eq__(self, other):
        return (isinstance(other, ProcessMesh)
                and np.array_equal(self._mesh_arr, other._mesh_arr)
                and self._dim_names == other._dim_names)

    def __hash__(self):
        return hash((self._mesh_arr.tobytes(), tuple(self._dim_names)))

    def __repr__(self):
        return (f"ProcessMesh(shape={self.shape}, dim_names="
                f"{self._dim_names})")

    # -- jax materialization -------------------------------------------------
    def to_jax_mesh(self) -> jax.sharding.Mesh:
        if self._jax_mesh is None:
            devices = np.asarray(jax.devices())
            ids = self._mesh_arr.reshape(-1)
            if ids.max() >= len(devices):
                raise RuntimeError(
                    f"mesh references device {ids.max()} but only "
                    f"{len(devices)} JAX devices exist")
            dev_grid = devices[ids].reshape(self._mesh_arr.shape)
            self._jax_mesh = jax.sharding.Mesh(dev_grid,
                                               tuple(self._dim_names))
        return self._jax_mesh

    def named_sharding(self, *spec) -> jax.sharding.NamedSharding:
        return jax.sharding.NamedSharding(
            self.to_jax_mesh(), jax.sharding.PartitionSpec(*spec))


def create_mesh(shape: Sequence[int], dim_names: Sequence[str]) -> ProcessMesh:
    n = int(np.prod(shape))
    return ProcessMesh(np.arange(n).reshape(tuple(shape)), dim_names)


_global_mesh: Optional[ProcessMesh] = None


def set_mesh(mesh: ProcessMesh):
    global _global_mesh
    _global_mesh = mesh


def get_mesh() -> Optional[ProcessMesh]:
    return _global_mesh


class _AutoNamespace:
    """paddle.distributed.auto namespace stub for API parity."""
    ProcessMesh = ProcessMesh


auto = _AutoNamespace()
