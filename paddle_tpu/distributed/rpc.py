"""paddle.distributed.rpc parity
(/root/reference/python/paddle/distributed/rpc/ — RpcAgent over brpc,
rpc.py: init_rpc/rpc_sync/rpc_async/shutdown). TPU-native transport: the
native TCP KV store carries pickled call/result envelopes (host-side
control plane only — tensor traffic belongs to the in-program XLA
collectives, same division as the reference).

Each worker runs a serving thread that polls its inbox key; rpc_sync /
rpc_async post to the callee's inbox and wait on a per-call result key.

Security model (same as the reference's brpc RpcAgent): envelopes are
pickled callables executed on the callee, so anyone who can reach the
master store port can run code on every worker. RPC is only safe on a
TRUSTED, ISOLATED cluster network. Single-host runs should set
``PT_KV_BIND_ADDR=127.0.0.1`` to pin the store to loopback; multi-host
deployments must firewall the master port to the pod network.
"""
from __future__ import annotations

import pickle
import threading
import time
import uuid
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional

from ..core.native import TCPStore

__all__ = ["init_rpc", "shutdown", "rpc_sync", "rpc_async",
           "get_worker_info", "get_all_worker_infos", "WorkerInfo"]


@dataclass
class WorkerInfo:
    name: str
    rank: int
    ip: str = "127.0.0.1"
    port: int = 0


class _Agent:
    def __init__(self, name: str, rank: int, world_size: int,
                 store: TCPStore):
        self.name = name
        self.rank = rank
        self.world_size = world_size
        self.store = store
        # The serving thread gets its own client connection: the native
        # client serializes one request per handle, so a caller blocked in
        # a long wait() would otherwise starve serving (deadlocking
        # self-calls and any call arriving while this rank waits).
        self._serve_store = TCPStore(store.host, store.port)
        self._stop = threading.Event()
        self._seq = 0
        self._thread = threading.Thread(target=self._serve, daemon=True)
        # register self; wait for peers
        store.set(f"rpc/worker{rank}", pickle.dumps(
            WorkerInfo(name, rank)))
        store.add("rpc/registered", 1)
        self._thread.start()

    # -- serving ------------------------------------------------------------
    def _serve(self):
        inbox_ctr = f"rpc/inbox{self.rank}/n"
        served = 0
        while not self._stop.is_set():
            try:
                pending = self._serve_store.add(inbox_ctr, 0)
            except Exception:
                return
            if pending <= served:
                time.sleep(0.005)
                continue
            for i in range(served, pending):
                # the envelope is a 2-tuple (call_id, payload_bytes) so a
                # payload that fails to unpickle (module only importable
                # on the caller) still yields an id to report back on
                blob = None
                for _attempt in range(3):
                    try:
                        blob = self._serve_store.get(
                            f"rpc/inbox{self.rank}/{i}", timeout=10)
                        break
                    except Exception:
                        continue
                if blob is None:
                    continue  # unreadable slot; caller hits its timeout
                call_id = None
                try:
                    call_id, body = pickle.loads(blob)
                    call = pickle.loads(body)
                    result = call["fn"](*call["args"], **call["kwargs"])
                    payload = pickle.dumps({"ok": True, "value": result})
                except Exception as e:  # noqa: BLE001 — ship to caller
                    payload = pickle.dumps({"ok": False, "error": repr(e)})
                if call_id is not None:
                    try:
                        self._serve_store.set(f"rpc/result/{call_id}",
                                              payload)
                    except Exception:
                        pass
            served = pending

    # -- calling ------------------------------------------------------------
    def call(self, to: str, fn: Callable, args: tuple, kwargs: dict,
             timeout: float):
        # worker registry is immutable after the init barrier: cache it
        if not hasattr(self, "_infos"):
            self._infos = {i.name: i for i in _fetch_worker_infos(self)}
        target = self._infos.get(to)
        if target is None:
            raise ValueError(
                f"unknown rpc worker {to!r}; registered: "
                f"{sorted(self._infos)}")
        call_id = f"{self.rank}-{uuid.uuid4().hex[:12]}"
        body = pickle.dumps({"fn": fn, "args": args, "kwargs": kwargs})
        blob = pickle.dumps((call_id, body))
        idx = self.store.add(f"rpc/inbox{target.rank}/n", 1) - 1
        slot = f"rpc/inbox{target.rank}/{idx}"
        try:
            self.store.set(slot, blob)
        except Exception:
            # The index is already reserved; tombstone it so the callee's
            # in-order scan doesn't stall ~30s on an empty slot. A None
            # body fails to unpickle remotely, bouncing an error to us.
            try:
                self.store.set(slot, pickle.dumps((call_id, None)))
            except Exception:
                pass
            raise
        return call_id

    def wait(self, call_id: str, timeout: float):
        blob = self.store.get(f"rpc/result/{call_id}", timeout=timeout)
        res = pickle.loads(blob)
        if not res["ok"]:
            raise RuntimeError(f"rpc call failed remotely: {res['error']}")
        return res["value"]

    def shutdown(self):
        self._stop.set()
        self._thread.join(timeout=5)
        # Only free the native handle once the serving thread is out of
        # it; a still-blocked daemon thread keeps the (leaked) handle
        # until process exit rather than risking a use-after-free.
        if not self._thread.is_alive():
            try:
                self._serve_store.close()
            except Exception:
                pass


_agent: Optional[_Agent] = None


def init_rpc(name: str, rank: Optional[int] = None,
             world_size: Optional[int] = None,
             master_endpoint: Optional[str] = None):
    """Reference init_rpc parity: master_endpoint "ip:port" hosts the
    store on rank 0."""
    global _agent
    import os
    rank = rank if rank is not None else int(
        os.environ.get("PADDLE_TRAINER_ID", "0"))
    world_size = world_size if world_size is not None else int(
        os.environ.get("PADDLE_TRAINERS_NUM", "1"))
    ep = master_endpoint or os.environ.get("PADDLE_MASTER",
                                           "127.0.0.1:8790")
    host, port = ep.rsplit(":", 1)
    store = TCPStore(host, int(port), is_master=(rank == 0),
                     world_size=world_size)
    _agent = _Agent(name, rank, world_size, store)
    # barrier until all workers registered
    deadline = time.time() + 60
    while _agent.store.add("rpc/registered", 0) < world_size:
        if time.time() > deadline:
            raise TimeoutError("init_rpc: peers missing")
        time.sleep(0.01)
    return _agent


def shutdown():
    global _agent
    if _agent is not None:
        try:
            _agent.store.add("rpc/done", 1)
            # drain until everyone is done so late callers don't hang.
            # The store-hosting rank tears the server down once it sees
            # the full count — a lost connection here on other ranks
            # MEANS everyone is done, not an error.
            deadline = time.time() + 30
            while _agent.store.add("rpc/done", 0) < _agent.world_size \
                    and time.time() < deadline:
                time.sleep(0.01)
        except RuntimeError:
            pass  # server already gone → all peers finished
        _agent.shutdown()
        _agent = None


class _Future:
    def __init__(self, agent: _Agent, call_id: str, timeout: float):
        self._agent = agent
        self._id = call_id
        self._timeout = timeout

    def wait(self):
        return self._agent.wait(self._id, self._timeout)


def _require_agent() -> _Agent:
    if _agent is None:
        raise RuntimeError("call init_rpc first")
    return _agent


def rpc_sync(to: str, fn: Callable, args: tuple = (), kwargs=None,
             timeout: float = 180.0):
    agent = _require_agent()
    cid = agent.call(to, fn, args, kwargs or {}, timeout)
    return agent.wait(cid, timeout)


def rpc_async(to: str, fn: Callable, args: tuple = (), kwargs=None,
              timeout: float = 180.0) -> _Future:
    agent = _require_agent()
    cid = agent.call(to, fn, args, kwargs or {}, timeout)
    return _Future(agent, cid, timeout)


def get_worker_info(name: Optional[str] = None) -> WorkerInfo:
    agent = _require_agent()
    if name is None:
        return WorkerInfo(agent.name, agent.rank)
    for info in get_all_worker_infos():
        if info.name == name:
            return info
    raise ValueError(f"unknown worker {name!r}")


def _fetch_worker_infos(agent: "_Agent") -> List[WorkerInfo]:
    """All registered workers; after the init barrier every rank must be
    present — a missing entry is a real error, not something to skip."""
    out = []
    for r in range(agent.world_size):
        out.append(pickle.loads(
            agent.store.get(f"rpc/worker{r}", timeout=30)))
    return out


def get_all_worker_infos() -> List[WorkerInfo]:
    agent = _require_agent()
    if not hasattr(agent, "_infos"):
        agent._infos = {i.name: i for i in _fetch_worker_infos(agent)}
    return list(agent._infos.values())
