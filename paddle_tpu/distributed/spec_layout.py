"""Canonical PartitionSpecs for tensor-parallel serving (the
`SpecLayout` pattern, SNIPPETS.md [2]) — ONE table that every call site
annotating a decoder weight or the paged KV pool must agree with.

ROADMAP item 1 (multi-chip TP decode on the 8-device mesh) shards the
decoder over a ``tp`` mesh axis. The failure mode this table exists to
prevent is *spec drift*: the same parameter annotated column-parallel at
one call site and row-parallel at another composes into silent
all-gathers (or wrong math under shard_map). The table is the single
source of truth, in BOTH directions:

- runtime: ``layout.sharding(mesh, name)`` / ``layout.apply(mesh,
  weights)`` place a PagedLlamaDecoder-style weight tree (the
  ``paged_decode._weights_from_model`` key vocabulary: wq/wk/wv/wo,
  wg/wu/wd, embed/head/norm, cache_k/cache_v) onto a mesh;
- static analysis: ``tools/flightcheck`` rule FC605 parses
  ``CANONICAL_SPECS`` out of this file (AST, no import) and flags any
  *literal* PartitionSpec in the tree that disagrees with the canonical
  layout for the same parameter name on the same axis vocabulary.

Layout choices (Megatron-style 1-allreduce-per-block decode):
- attention: wq/wk/wv column-parallel (heads split over tp), wo
  row-parallel — the block's only collective is the allreduce after wo;
- mlp: wg/wu column-parallel, wd row-parallel — allreduce after wd;
- embed/norm replicated (small), head column-parallel (per-shard logits
  concatenate over vocab);
- paged KV pool: [num_blocks, block_size, kv_heads, head_dim] sharded
  over the kv-head dim, so a tp shard appends exactly the heads it
  computed — no cross-chip traffic on the KV write path.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["SpecLayout", "CANONICAL_SPECS", "TP_AXIS"]

TP_AXIS = "tp"

# parameter name -> canonical PartitionSpec over the tp axis. The specs
# describe the TRAILING dims of the parameter (stacked trunks prepend
# bookkeeping dims; FC605 compares suffixes). Keep every value a P(...)
# LITERAL — flightcheck reads this dict syntactically.
CANONICAL_SPECS: Dict[str, P] = {
    # attention (column: out-features split; row: in-features split)
    "wq": P(None, "tp"),
    "wk": P(None, "tp"),
    "wv": P(None, "tp"),
    "wo": P("tp", None),
    # mlp
    "wg": P(None, "tp"),
    "wu": P(None, "tp"),
    "wd": P("tp", None),
    # embedding / output
    "embed": P(None, None),
    "norm": P(None),
    "head": P(None, "tp"),
    # paged KV pool: [num_blocks, block_size, kv_heads, head_dim]
    "cache_k": P(None, None, "tp", None),
    "cache_v": P(None, None, "tp", None),
}


@dataclass(frozen=True)
class SpecLayout:
    """Resolved canonical layout over a concrete tp axis name."""

    tp_axis: str = TP_AXIS

    def spec(self, name: str) -> P:
        base = CANONICAL_SPECS.get(name)
        if base is None:
            # per-layer dicts nest under "layers"; unknown small tensors
            # (norms, rope caches, scales) replicate
            return P()
        if self.tp_axis == TP_AXIS:
            return base
        return P(*[self.tp_axis if e == TP_AXIS else e for e in base])

    def sharding(self, mesh, name: str) -> NamedSharding:
        return NamedSharding(mesh, self.spec(name))

    def apply(self, mesh, weights):
        """device_put a paged-decoder weight tree by key name. Leaves
        under ``layers`` (a list of per-layer dicts) use their dict key;
        anything without a canonical entry replicates."""
        import jax

        def put(name, leaf):
            return jax.device_put(leaf, self.sharding(mesh, name))

        out = {}
        for k, v in weights.items():
            if k == "layers":
                out[k] = [{kk: put(kk, vv) for kk, vv in layer.items()}
                          for layer in v]
            else:
                out[k] = put(k, v)
        return out
