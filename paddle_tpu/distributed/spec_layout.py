"""Canonical PartitionSpecs for tensor-parallel serving (the
`SpecLayout` pattern, SNIPPETS.md [2]) — ONE table that every call site
annotating a decoder weight or the paged KV pool must agree with.

ROADMAP item 1 (multi-chip TP decode on the 8-device mesh) shards the
decoder over a ``tp`` mesh axis. The failure mode this table exists to
prevent is *spec drift*: the same parameter annotated column-parallel at
one call site and row-parallel at another composes into silent
all-gathers (or wrong math under shard_map). The table is the single
source of truth, in BOTH directions:

- runtime: ``layout.sharding(mesh, name)`` / ``layout.apply(mesh,
  weights)`` place a paged-decoder weight tree (the PagedLlamaDecoder
  ``_extract_weights`` key vocabulary — wq/wk/wv/wo, wg/wu/wd,
  embed/head/norm/ln1/ln2 — and the PagedGPTDecoder TP-split vocabulary
  — wq/wk/wv/bq/bk/bv, wo/bo, wi/bi, wf/bf, pos/ln*_w/ln*_b/lnf_* —
  plus the paged pool cache_k/cache_v) onto a mesh, quantized
  ``(w_q, scale)`` pairs included;
- static analysis: ``tools/flightcheck`` rule FC605 parses
  ``CANONICAL_SPECS`` out of this file (AST, no import) and flags any
  *literal* PartitionSpec in the tree that disagrees with the canonical
  layout for the same parameter name on the same axis vocabulary.

Layout choices (Megatron-style 1-allreduce-per-block decode):
- attention: wq/wk/wv column-parallel (heads split over tp), wo
  row-parallel — the block's only collective is the allreduce after wo;
- mlp: wg/wu (Llama) / wi (GPT) column-parallel, wd/wf row-parallel —
  allreduce after wd/wf;
- biases follow their weight's OUT dim: column-parallel biases shard
  (bq/bk/bv/bi), row-parallel output biases replicate and are added
  AFTER the allreduce (bo/bf) — adding them per shard before the psum
  would multiply them by the tp degree;
- embed/pos/norms replicated (small), head column-parallel (per-shard
  vocab logits all-gather once before sampling);
- paged KV pool: [num_blocks, kv_heads, block_size, head_dim]
  (ops.paged_attention.PagedKVCache layout — one physical page is a
  contiguous [kv_heads, block_size, head_dim] region) sharded over the
  kv-head dim, so a tp shard appends exactly the heads it computed —
  ZERO collectives on the KV-append path.

Fleet serving (ISSUE 11) adds the DATA axis: the dp x tp serving mesh
is a [dp, tp] device grid with axes ("data", "tp") where each row is
one replica's tp mesh. The canonical placement over the data axis is
PURE REPLICATION — no CANONICAL_SPECS entry ever names it: every
replica holds full weights and its own full KV pool, which is exactly
what keeps dp at ZERO step-path collectives (replicas never talk
during a step; the comm audit pins serving.ragged_dp2_tp2 identical to
serving.ragged_tp2_fp32). ``fleet_device_slices`` hands the Router
(inference/fleet.py) the disjoint per-replica device rows this table
implies.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["SpecLayout", "CANONICAL_SPECS", "TP_AXIS", "DATA_AXIS"]

TP_AXIS = "tp"
# the replica axis of the dp x tp serving mesh: weights and KV pools
# REPLICATE over it (each replica is an independent engine), so no
# canonical spec below may name it — spec() enforces that invariant
DATA_AXIS = "data"

# parameter name -> canonical PartitionSpec over the tp axis. The specs
# describe the TRAILING dims of the parameter (stacked trunks prepend
# bookkeeping dims; FC605 compares suffixes). Keep every value a P(...)
# LITERAL — flightcheck reads this dict syntactically.
CANONICAL_SPECS: Dict[str, P] = {
    # attention (column: out-features split; row: in-features split)
    "wq": P(None, "tp"),
    "wk": P(None, "tp"),
    "wv": P(None, "tp"),
    "wo": P("tp", None),
    # attention biases (GPT family): column biases shard with the out
    # dim; the row-parallel output bias replicates (added post-psum)
    "bq": P("tp"),
    "bk": P("tp"),
    "bv": P("tp"),
    "bo": P(None),
    # mlp (Llama gate/up/down)
    "wg": P(None, "tp"),
    "wu": P(None, "tp"),
    "wd": P("tp", None),
    # mlp (GPT fc_in/fc_out + biases)
    "wi": P(None, "tp"),
    "bi": P("tp"),
    "wf": P("tp", None),
    "bf": P(None),
    # embedding / output
    "embed": P(None, None),
    "pos": P(None, None),
    "norm": P(None),
    "ln1": P(None),
    "ln2": P(None),
    "ln1_w": P(None),
    "ln1_b": P(None),
    "ln2_w": P(None),
    "ln2_b": P(None),
    "lnf_w": P(None),
    "lnf_b": P(None),
    "head": P(None, "tp"),
    # paged KV pool: [num_blocks, kv_heads, block_size, head_dim]
    # (kv-head dim sharded — each shard appends the heads it computed)
    "cache_k": P(None, "tp", None, None),
    "cache_v": P(None, "tp", None, None),
    # quantized-pool sidecar scales (ISSUE 13): [num_blocks, kv_heads,
    # block_size] — the kv-head dim shards EXACTLY like the values'
    # (dim-aligned with their heads), so each tp shard quantizes and
    # dequantizes its own head slice with its own scales and the int8
    # pool adds ZERO collectives (pinned by comm-audit entry
    # serving.ragged_kv8_tp2 == serving.ragged_tp2_fp32)
    "cache_k_scale": P(None, "tp", None),
    "cache_v_scale": P(None, "tp", None),
    # LoRA adapter-page plane: [num_blocks, page_elems] REPLICATED —
    # each shard slices its own A-rows/B-columns from the full
    # factors in-program, which is what keeps the lora deltas at
    # zero extra collectives (see inference/lora.py)
    "lora_pool": P(None, None),
}


@dataclass(frozen=True)
class SpecLayout:
    """Resolved canonical layout over concrete tp/data axis names.

    The ``data_axis`` is the replica dimension of the dp x tp serving
    mesh (ISSUE 11): it never appears in a weight spec — replicas
    replicate — so its only resolved artifacts are the device GRID
    (``fleet_mesh``) and the disjoint per-replica rows
    (``fleet_device_slices``) the fleet Router places engines on."""

    tp_axis: str = TP_AXIS
    data_axis: str = DATA_AXIS

    def spec(self, name: str, strict: bool = False) -> P:
        base = CANONICAL_SPECS.get(name)
        if base is None:
            if strict:
                raise KeyError(
                    f"weight key {name!r} has no canonical PartitionSpec"
                    f" in CANONICAL_SPECS (paddle_tpu/distributed/"
                    f"spec_layout.py) — a silently-replicated unknown "
                    f"key is how spec drift starts; add it to the table"
                    f" (or place it explicitly)")
            # per-layer dicts nest under "layers"; unknown small tensors
            # (rope caches, scales) replicate — only in non-strict mode
            return P()
        if self.tp_axis == TP_AXIS:
            return base
        return P(*[self.tp_axis if e == TP_AXIS else e for e in base])

    def scale_spec(self, name: str) -> P:
        """Spec for the per-output-channel scale of a quantized
        ``(w_q, scale)`` pair: the scale follows the OUT dim, so it
        shards iff the weight is column-parallel (out dim sharded)."""
        s = self.spec(name)
        if len(s) >= 2 and s[-1] == self.tp_axis:
            return P(self.tp_axis)
        return P()

    def sharding(self, mesh, name: str) -> NamedSharding:
        return NamedSharding(mesh, self.spec(name))

    def _map(self, weights, leaf_fn, strict: bool):
        out = {}
        for k, v in weights.items():
            if k == "layers":
                out[k] = [{kk: leaf_fn(kk, vv, strict)
                           for kk, vv in layer.items()} for layer in v]
            else:
                out[k] = leaf_fn(k, v, strict)
        return out

    def apply(self, mesh, weights, strict: bool = False):
        """device_put a paged-decoder weight tree by key name. Leaves
        under ``layers`` (a list of per-layer dicts) use their dict key;
        quantized ``(w_q, scale)`` tuples place the packed array by the
        weight's spec and the scale by ``scale_spec``. With
        ``strict=True`` a key missing from CANONICAL_SPECS raises
        instead of silently replicating."""
        import jax

        def put(name, leaf, strict_):
            ns = NamedSharding(mesh, self.spec(name, strict=strict_))
            if isinstance(leaf, tuple):
                wq, sc = leaf
                return (jax.device_put(wq, ns),
                        jax.device_put(sc, NamedSharding(
                            mesh, self.scale_spec(name))))
            return jax.device_put(leaf, ns)

        return self._map(weights, put, strict)

    def spec_tree(self, weights, strict: bool = False):
        """A PartitionSpec pytree matching ``weights`` leaf-for-leaf —
        the ``in_specs`` entry a fully-manual shard_map needs for the
        weight operand (quantized tuples get (weight_spec,
        scale_spec))."""

        def spec_of(name, leaf, strict_):
            if isinstance(leaf, tuple):
                return (self.spec(name, strict=strict_),
                        self.scale_spec(name))
            return self.spec(name, strict=strict_)

        return self._map(weights, spec_of, strict)

    # -- dp x tp fleet placement (ISSUE 11) -------------------------------
    def _fleet_grid(self, dp: int, tp: int,
                    devices: Optional[Sequence] = None):
        import jax
        import numpy as np
        dp, tp = int(dp), int(tp)
        if dp < 1 or tp < 1:
            raise ValueError(f"dp and tp must be >= 1, got dp={dp} "
                             f"tp={tp}")
        devs = list(devices) if devices is not None else jax.devices()
        if len(devs) < dp * tp:
            raise ValueError(
                f"dp={dp} x tp={tp} needs {dp * tp} devices, found "
                f"{len(devs)}")
        return np.asarray(devs[:dp * tp], dtype=object).reshape(dp, tp)

    def fleet_mesh(self, dp: int, tp: int,
                   devices: Optional[Sequence] = None):
        """The canonical [dp, tp] serving mesh: axes (data_axis,
        tp_axis) over the first dp*tp devices (or an explicit list).
        Row r IS replica r's tp mesh — the 2D mesh exists so placement
        (and the FC6xx analyses) can reason about both axes from one
        table; each replica's engine runs fully-manual shard_map over
        its OWN one-axis row, never over the data axis."""
        from jax.sharding import Mesh
        return Mesh(self._fleet_grid(dp, tp, devices),
                    (self.data_axis, self.tp_axis))

    def fleet_device_slices(self, dp: int, tp: int,
                            devices: Optional[Sequence] = None
                            ) -> List[list]:
        """The disjoint per-replica device rows of the dp x tp grid —
        what the fleet Router passes to each ServingEngine(devices=...)
        so R tp-sharded replicas never share a chip."""
        grid = self._fleet_grid(dp, tp, devices)
        return [list(grid[r]) for r in range(grid.shape[0])]
