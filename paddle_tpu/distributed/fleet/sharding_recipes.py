"""Hybrid sharding recipes (parity: the reference's meta_parallel wrappers
— /root/reference/python/paddle/distributed/fleet/model.py:141-160 routing
to TensorParallel/ShardingParallel/PipelineParallel, and the group_sharded
stages /root/reference/python/paddle/distributed/fleet/meta_parallel/sharding/).

TPU-native: 'wrapping' a model for dp/sharding is a parameter placement
choice:
- DP            → params replicated over 'dp' (grad psum GSPMD-inserted)
- sharding st.1 → optimizer state sharded over 'sharding' (via
                  shard_optimizer matching param placements)
- sharding st.2 → + grads reduce-scattered (falls out of param placement
                  under jit: grads inherit param sharding)
- sharding st.3 → params themselves Shard(0) over 'sharding' (FSDP);
                  all-gather on use is GSPMD-inserted
"""
from __future__ import annotations

import jax
import numpy as np

from ...framework.core import Parameter
from ..mesh import ProcessMesh
from ..placement import Replicate, Shard
from ..api import placements_to_spec

__all__ = ["apply_hybrid_shardings", "shard_params_fsdp"]


def _shardable(p, ax_size: int, min_size: int = 1024) -> bool:
    """Shared stage-1/2/3 eligibility: big enough to be worth sharding
    and dim 0 divisible by the sharding-axis size."""
    return (p.size >= min_size and p.shape
            and p.shape[0] % ax_size == 0)


def _place(p: Parameter, mesh: ProcessMesh, placements):
    sharding = jax.sharding.NamedSharding(
        mesh.to_jax_mesh(), placements_to_spec(mesh, placements))
    p._replace(jax.device_put(p._value, sharding))
    p.process_mesh = mesh
    p.placements = placements


def shard_params_fsdp(model, mesh: ProcessMesh, axis: str = "sharding",
                      min_size: int = 1024):
    """Stage-3/FSDP: shard each large param's dim 0 over `axis`; small
    params stay replicated (same policy as the reference's stage-3
    segment_size threshold)."""
    ax_idx = mesh.dim_names.index(axis)
    ax_size = mesh.shape[ax_idx]
    for _, p in model.named_parameters():
        if getattr(p, "placements", None) is not None:
            # already annotated (e.g. TP layer) — extend, don't override
            continue
        placements = [Replicate()] * mesh.ndim
        if _shardable(p, ax_size, min_size):
            placements[ax_idx] = Shard(0)
        _place(p, mesh, placements)
    return model


def apply_hybrid_shardings(model, hcg, strategy=None):
    """Annotate un-annotated params according to the hybrid degrees."""
    mesh = hcg.mesh
    degrees = hcg.topology()
    stage = 1
    if strategy is not None and getattr(strategy, "sharding_configs", None):
        stage = strategy.sharding_configs.get("stage", 1)
    if degrees.get("sharding", 1) > 1 and stage >= 3:
        shard_params_fsdp(model, mesh, "sharding")
    else:
        for _, p in model.named_parameters():
            if getattr(p, "placements", None) is not None:
                continue
            _place(p, mesh, [Replicate()] * mesh.ndim)
        if degrees.get("sharding", 1) > 1 and stage >= 1:
            # ZeRO stage 1/2: params stay replicated but OPTIMIZER STATE
            # shards over the 'sharding' axis (the reference's
            # DygraphShardingOptimizer / GroupShardedOptimizerStage2
            # memory win). shard_optimizer reads _opt_state_placements;
            # under the whole-step jit GSPMD then reduce-scatters grads
            # into the sharded update and all-gathers the param delta —
            # the stage-2 comm pattern, chosen by the partitioner.
            ax = mesh.dim_names.index("sharding")
            ax_size = mesh.shape[ax]
            for _, p in model.named_parameters():
                if _shardable(p, ax_size):
                    sp = list(p.placements or
                              [Replicate()] * mesh.ndim)
                    if all(isinstance(x, Replicate) for x in sp):
                        sp[ax] = Shard(0)
                        p._opt_state_placements = sp
    for _, b in model.named_buffers():
        if b is None:
            continue
        sharding = jax.sharding.NamedSharding(
            mesh.to_jax_mesh(), jax.sharding.PartitionSpec())
        b._replace(jax.device_put(b._value, sharding))
    return model
