"""paddle_tpu.distributed.fleet — hybrid-parallel entry (parity:
/root/reference/python/paddle/distributed/fleet/fleet.py:167 fleet.init,
base/distributed_strategy.py:1808 hybrid_configs).

TPU-native: fleet.init builds a HybridCommunicateGroup = a named device
mesh; distributed_model / distributed_optimizer are sharding-recipe
appliers, not wrapper runtimes.
"""
from __future__ import annotations

from typing import Optional

from .topology import CommunicateTopology, HybridCommunicateGroup
from .strategy import DistributedStrategy
from . import mpu  # noqa: F401
from .. import auto_parallel as auto  # noqa: F401  (fleet.auto.Engine parity)
from .mpu import (  # noqa: F401
    ColumnParallelLinear, RowParallelLinear, VocabParallelEmbedding,
    ParallelCrossEntropy,
)
from .recompute import recompute, recompute_sequential  # noqa: F401
from .pipeline import (  # noqa: F401
    LayerDesc, PipelineLayer, PipelineParallel, pipeline_apply,
)
from .pp_schedule import (  # noqa: F401
    PipelineSchedule, build_pipeline_schedule, pipeline_forward_backward,
    make_pipeline_loss_fn,
)
from .sequence_parallel_utils import (  # noqa: F401
    ScatterOp, GatherOp, ColumnSequenceParallelLinear,
    RowSequenceParallelLinear,
)

__all__ = ["init", "fleet", "DistributedStrategy", "HybridCommunicateGroup",
           "get_hybrid_communicate_group", "distributed_model",
           "distributed_optimizer", "recompute", "ColumnParallelLinear",
           "RowParallelLinear", "VocabParallelEmbedding",
           "ParallelCrossEntropy", "LayerDesc", "PipelineLayer",
           "PipelineParallel",
           "pipeline_apply", "ScatterOp", "GatherOp",
           "ColumnSequenceParallelLinear", "RowSequenceParallelLinear",
           "PipelineSchedule", "build_pipeline_schedule",
           "pipeline_forward_backward", "make_pipeline_loss_fn",
           "pipeline_schedule_from_strategy"]

_hcg: Optional[HybridCommunicateGroup] = None
_strategy: Optional[DistributedStrategy] = None


def init(role_maker=None, is_collective: bool = False,
         strategy: Optional[DistributedStrategy] = None, log_level="INFO"):
    """fleet.init parity: reads strategy.hybrid_configs and builds the mesh."""
    global _hcg, _strategy
    from .. import parallel
    parallel.init_parallel_env()
    _strategy = strategy or DistributedStrategy()
    hc = _strategy.hybrid_configs
    _hcg = HybridCommunicateGroup(
        dp_degree=hc.get("dp_degree", 1),
        mp_degree=hc.get("mp_degree", 1),
        pp_degree=hc.get("pp_degree", 1),
        sharding_degree=hc.get("sharding_degree", 1),
        sep_degree=hc.get("sep_degree", 1),
    )
    return _hcg


def get_hybrid_communicate_group() -> Optional[HybridCommunicateGroup]:
    return _hcg


def distributed_model(model):
    """Apply the sharding recipe implied by the strategy (parity:
    /root/reference/python/paddle/distributed/fleet/model.py:32). On TPU
    this annotates parameter shardings; TP layers already carry theirs.
    A PipelineLayer with pp_degree > 1 returns the PipelineParallel
    train_batch driver (reference fleet/model.py:160)."""
    if _hcg is None:
        return model
    if isinstance(model, PipelineLayer) and \
            _hcg.get_pipe_parallel_world_size() > 1:
        return PipelineParallel(model, _hcg, _strategy)
    from .sharding_recipes import apply_hybrid_shardings
    return apply_hybrid_shardings(model, _hcg, _strategy)


def distributed_optimizer(optimizer, strategy=None):
    from ..api import shard_optimizer
    return shard_optimizer(optimizer)


def pipeline_schedule_from_strategy(strategy: DistributedStrategy,
                                    n_micro: int = None):
    """Build the PipelineSchedule that strategy.pipeline_configs selects
    (schedule_mode is validated — an unknown mode fails loudly rather than
    silently meaning GPipe). n_micro defaults to accumulate_steps."""
    hc = strategy.hybrid_configs
    pc = strategy.pipeline_configs
    if n_micro is None:
        n_micro = int(pc.get("accumulate_steps", 1))
    return build_pipeline_schedule(
        n_stages=int(hc.get("pp_degree", 1)), n_micro=n_micro,
        vpp=int(pc.get("vpp_degree", 1)),
        mode=pc.get("schedule_mode", "1F1B"))


class _FleetNamespace:
    init = staticmethod(init)
    distributed_model = staticmethod(distributed_model)
    distributed_optimizer = staticmethod(distributed_optimizer)
    get_hybrid_communicate_group = staticmethod(get_hybrid_communicate_group)

    @property
    def worker_num(self):
        import jax
        return jax.process_count()

    @property
    def worker_index(self):
        import jax
        return jax.process_index()


fleet = _FleetNamespace()
