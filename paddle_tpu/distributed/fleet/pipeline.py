"""Pipeline parallelism as an in-program SPMD schedule.

Replaces the reference's actor/schedule machinery — PipelineParallel 1F1B
(/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:440), P2P tensor protocol (pp_utils/
p2p_communication.py), and the C++ FleetExecutor interceptor runtime
(/root/reference/paddle/fluid/distributed/fleet_executor/) — with a single
jitted collective program: every pp rank runs the same code, activations
move between neighbor stages via ppermute (ICI neighbor links), and the
backward schedule falls out of autodiff through the loop (reverse
ppermute), so no send/recv protocol, no interceptors, no message bus.

Design (homogeneous stages, the transformer case):
- stage parameters are stacked on a leading [n_stages, ...] axis sharded
  over 'pp' — each device holds exactly its stage's slice;
- the microbatch loop runs n_micro + n_stages - 1 ticks; stage 0 feeds a
  fresh microbatch each tick, the last stage emits a finished microbatch
  each tick after the fill phase (GPipe schedule; per-tick work is one
  microbatch per stage, so steady-state utilization matches 1F1B — the
  1F1B advantage on GPUs is weight-memory timing, which XLA's liveness
  analysis handles for us).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["LayerDesc", "PipelineLayer", "PipelineParallel",
           "pipeline_apply", "pipeline_apply_interleaved"]


class LayerDesc:
    """Declarative layer description for pipeline segmentation (parity:
    /root/reference/python/paddle/distributed/fleet/meta_parallel/
    parallel_layers/pp_layers.py:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class PipelineLayer:
    """Container that segments a LayerDesc list into pp stages (parity:
    pp_layers.py:237 PipelineLayer). Builds all layers (single-controller:
    every process holds the program; per-stage placement happens via the
    stacked-parameter sharding in pipeline_apply)."""

    def __init__(self, layers: List[LayerDesc], num_stages: int,
                 loss_fn: Optional[Callable] = None, topology=None,
                 seg_method: str = "uniform"):
        self.descs = layers
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        n = len(layers)
        per = n // num_stages
        assert per * num_stages == n, \
            f"{n} layers not divisible into {num_stages} stages"
        self.stage_layers = [
            [d.build_layer() for d in layers[i * per:(i + 1) * per]]
            for i in range(num_stages)
        ]

    def parameters(self):
        ps = []
        for stage in self.stage_layers:
            for l in stage:
                ps.extend(l.parameters())
        return ps


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh, axis: str = "pp", extra_spec=None):
    """Run a GPipe schedule over the `axis` mesh dimension.

    stage_fn(params_slice, x) -> y   (same signature for every stage)
    stacked_params: pytree whose leaves have leading dim n_stages (sharded
      over `axis` outside or resharded here)
    x_microbatches: [n_micro, ...] microbatched input of stage 0
    Returns [n_micro, ...] outputs of the last stage (valid on every rank
    — they're psum-broadcast so the loss is computable anywhere).
    """
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    n_stages = jmesh.shape[axis]

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    x_spec = P()  # microbatches replicated into the loop; stage0 consumes

    def body(params, xs, stage_ids):
        # params: leaves [1, ...] (this stage's slice) → squeeze
        p_local = jax.tree_util.tree_map(lambda a: a[0], params)
        # sharded-arange stage id: axis_index inside a partially-manual
        # shard_map lowers to PartitionId, which the SPMD partitioner
        # rejects on hybrid (auto+manual) meshes on jax<=0.4.x
        stage = stage_ids[0]
        n_micro = xs.shape[0]
        n_ticks = n_micro + n_stages - 1
        perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]

        y0_shape = jax.eval_shape(lambda p, x: stage_fn(p, x), p_local,
                                  xs[0])

        def tick(t, carry):
            prev_out, outputs = carry
            # activation arriving from the previous stage
            incoming = jax.lax.ppermute(prev_out, axis, perm_fwd)
            my_in = jnp.where(
                stage == 0,
                xs[jnp.minimum(t, n_micro - 1)].astype(incoming.dtype),
                incoming)
            out = stage_fn(p_local, my_in)
            # last stage stores finished microbatch m = t - (n_stages - 1)
            m = t - (n_stages - 1)
            outputs = jax.lax.cond(
                m >= 0,
                lambda o: o.at[jnp.maximum(m, 0)].set(
                    jnp.where(stage == n_stages - 1, out,
                              o[jnp.maximum(m, 0)])),
                lambda o: o,
                outputs)
            return out, outputs

        init_out = jnp.zeros(y0_shape.shape, y0_shape.dtype)
        outputs0 = jnp.zeros((n_micro,) + tuple(y0_shape.shape),
                             y0_shape.dtype)
        _, outputs = jax.lax.fori_loop(0, n_ticks, tick,
                                       (init_out, outputs0))
        # broadcast finished outputs from the last stage to all pp ranks
        mask = (stage == n_stages - 1).astype(outputs.dtype)
        outputs = jax.lax.psum(outputs * mask, axis)
        return outputs

    f = shard_map(body, mesh=jmesh,
                  in_specs=(param_specs, x_spec, P(axis)), out_specs=P(),
                  check_vma=False)
    return f(stacked_params, x_microbatches,
             jnp.arange(n_stages, dtype=jnp.int32))


def pipeline_apply_interleaved(stage_fn: Callable, stacked_params,
                               x_microbatches, mesh, vpp_degree: int,
                               axis: str = "pp"):
    """Interleaved (virtual-pipeline / VPP) chunk placement — reference:
    PipelineParallelWithInterleave (meta_parallel/pipeline_parallel.py:906)
    and the VPP pass (passes/pipeline_scheduler_pass.py:465).

    The model is V = vpp_degree * n_stages chunks; physical stage s hosts
    virtual chunks {j * n_stages + s : j < vpp}. Consecutive virtual
    stages sit on consecutive physical stages, so every hop is the same
    neighbor ppermute as the plain schedule (wrapping n-1 → 0 advances a
    microbatch to its next chunk group).

    SCHEDULE NOTE: this runs lock-step — every stage computes all of its
    vpp chunk slots each tick. It provides the interleaved PLACEMENT
    (state dicts, chunk-wise sharding) for forward-only use; for
    TRAINING with the real one-chunk-per-tick circular interleaved 1F1B
    schedule (reduced bubble, bounded activation memory) use
    pp_schedule.build_pipeline_schedule + pipeline_forward_backward.

    stage_fn(params_slice, x) -> y  — one CHUNK's computation.
    stacked_params: pytree, leaves [vpp, n_stages, ...] (axis 1 sharded
      over `axis`).
    x_microbatches: [n_micro, ...].
    Returns [n_micro, ...] final-chunk outputs (psum-broadcast).
    """
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    n_stages = jmesh.shape[axis]
    V = vpp_degree * n_stages
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != vpp_degree or leaf.shape[1] != n_stages:
            raise ValueError(
                f"stacked_params leaves must be [vpp={vpp_degree}, "
                f"n_stages={n_stages}, ...]; got {leaf.shape} — a "
                f"mismatched leading dim would silently clamp chunk "
                f"indices")

    param_specs = jax.tree_util.tree_map(
        lambda _: P(None, axis), stacked_params)

    def body(params, xs, stage_ids):
        # params leaves: [vpp, 1, ...] → this stage's vpp chunk slices
        p_local = jax.tree_util.tree_map(lambda a: a[:, 0], params)
        # sharded-arange stage id (see pipeline_apply: axis_index inside
        # shard_map trips the SPMD partitioner on hybrid meshes)
        stage = stage_ids[0]
        n_micro = xs.shape[0]
        n_ticks = n_micro + V - 1
        ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        p_first = jax.tree_util.tree_map(lambda a: a[0], p_local)
        y_shape = jax.eval_shape(lambda p, x: stage_fn(p, x),
                                 p_first, xs[0])
        zero = jnp.zeros(y_shape.shape, y_shape.dtype)

        def tick(t, carry):
            acts, outputs = carry          # acts: [vpp, ...]
            outs = jax.vmap(stage_fn)(p_local, acts)
            arrived = jax.lax.ppermute(outs, axis, ring)
            # stage 0 re-routes on the wrap: slot j's arrival came from
            # virtual stage j*n + (n-1); its successor lives in slot j+1.
            # slot 0 consumes a fresh microbatch; the last slot's arrival
            # is a FINISHED microbatch (left the final virtual stage).
            fresh = xs[jnp.clip(t + 1, 0, n_micro - 1)].astype(
                arrived.dtype)
            shifted = jnp.concatenate(
                [fresh[None], arrived[:-1]], axis=0)
            acts_new = jnp.where(stage == 0, shifted, arrived)
            m = t - (V - 1)                 # finished microbatch id
            done = jnp.where(stage == 0, arrived[vpp_degree - 1], zero)
            outputs = jax.lax.cond(
                m >= 0,
                lambda o: o.at[jnp.maximum(m, 0)].set(
                    jnp.where(stage == 0, done, o[jnp.maximum(m, 0)])),
                lambda o: o, outputs)
            return acts_new, outputs

        acts0 = jnp.broadcast_to(zero, (vpp_degree,) + zero.shape)
        # seed slot 0 of stage 0 with microbatch 0 for tick 0
        acts0 = jnp.where(stage == 0,
                          acts0.at[0].set(xs[0].astype(zero.dtype)),
                          acts0)
        outputs0 = jnp.zeros((n_micro,) + zero.shape, zero.dtype)
        _, outputs = jax.lax.fori_loop(0, n_ticks, tick,
                                       (acts0, outputs0))
        # finished outputs live on stage 0 → broadcast to all pp ranks
        mask = (stage == 0).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    f = shard_map(body, mesh=jmesh,
                  in_specs=(param_specs, P(), P(axis)), out_specs=P(),
                  check_vma=False)
    return f(stacked_params, x_microbatches,
             jnp.arange(n_stages, dtype=jnp.int32))


class PipelineParallel:
    """train_batch-style driver over the table-driven schedules (parity:
    PipelineParallel.train_batch, /root/reference/python/paddle/
    distributed/fleet/meta_parallel/pipeline_parallel.py:657, with the
    1F1B schedule at :440). fleet.distributed_model returns this wrapper
    for a PipelineLayer when pp_degree > 1 (reference fleet/model.py:160).

    Requires HOMOGENEOUS stages (identical per-stage parameter
    structure — the transformer case): per-stage parameters are stacked
    on a leading [n_stages] axis sharded over 'pp', and one pp_schedule
    program runs the whole fwd+bwd. The optimizer step is the caller's
    own eager optimizer over the per-stage Tensors (grads are written
    back unstacked), so every paddle optimizer / lr scheduler / clip
    composes unchanged.
    """

    def __init__(self, layers: PipelineLayer, hcg, strategy=None):
        import jax
        self._layers = layers
        self._hcg = hcg
        from .pp_schedule import _resolve_mesh
        self._mesh = _resolve_mesh(hcg.mesh)
        self._pp = self._mesh.shape["pp"]
        if len(layers.stage_layers) != self._pp:
            raise ValueError(
                f"PipelineLayer has {len(layers.stage_layers)} stages but "
                f"mesh pp degree is {self._pp}")
        pc = (strategy.pipeline_configs if strategy is not None
              else {"schedule_mode": "1F1B", "accumulate_steps": 1})
        self._mode = pc.get("schedule_mode", "1F1B")
        self._n_micro = int(pc.get("accumulate_steps", 1))
        # backward mode: strategy.recompute forces remat; otherwise pick
        # automatically — store activations (reference default,
        # pipeline_parallel.py:440 stores, no remat) when the residual
        # buffers fit the budget, remat when they don't
        self._remat_mode = ("remat" if (strategy is not None
                                        and getattr(strategy, "recompute",
                                                    False))
                            else "auto")
        self._scheds = {}
        self._compiled = {}
        self._remat_choice = {}
        # observability: times from the LAST store-vs-remat measurement
        # (None until one runs; cached later steps do not re-measure)
        self.last_mode_times = None

        # homogeneity check + per-stage param lists
        self._stage_params = []
        struct0 = None
        for si, stage in enumerate(layers.stage_layers):
            ps = []
            for l in stage:
                ps.extend(p for _, p in l.named_parameters())
                if any(b is not None for _, b in l.named_buffers()):
                    raise ValueError(
                        "PipelineParallel stages with buffers (BatchNorm "
                        "running stats etc.) are not supported — buffer "
                        "updates cannot thread through the pipelined "
                        "schedule; use buffer-free stage layers")
            struct = [(tuple(p.shape), str(p._value.dtype))
                      for p in ps]
            if struct0 is None:
                struct0 = struct
            elif struct != struct0:
                raise ValueError(
                    "PipelineParallel needs homogeneous stages (same "
                    f"param shapes per stage); stage 0 has {struct0}, "
                    f"stage {si} has {struct}")
            self._stage_params.append(ps)
        self._template_stage = layers.stage_layers[0]

    # -- functional stage ----------------------------------------------------
    def _stage_fn(self, chunk_params, x):
        """Run the (template) stage layers with swapped-in arrays.
        chunk_params: list of arrays matching stage-0's param order."""
        from ...jit import functional_call
        idx = 0
        h = x
        for l in self._template_stage:
            n = len(list(l.named_parameters()))
            arrs = chunk_params[idx:idx + n]
            idx += n
            h, _ = functional_call(l, arrs, [], (h,))
        return h

    def _stacked(self):
        import jax.numpy as jnp
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        leaves = []
        n_params = len(self._stage_params[0])
        for i in range(n_params):
            stacked = jnp.stack([self._stage_params[s][i]._value
                                 for s in range(self._pp)])[None]
            # [1(vpp), pp, ...] — pp axis sharded
            spec = [None, "pp"] + [None] * (stacked.ndim - 2)
            leaves.append(jax.device_put(
                stacked, NamedSharding(self._mesh, P(*spec))))
        return leaves

    def _sched(self, n_micro):
        key = (self._pp, n_micro, self._mode)
        if key not in self._scheds:
            from .pp_schedule import build_pipeline_schedule
            self._scheds[key] = build_pipeline_schedule(
                self._pp, n_micro, 1, self._mode)
        return self._scheds[key]

    def _pick_remat(self, stage_fn, stacked, sched, x_aval,
                    runner=None, run_args=None) -> bool:
        """auto mode. Two gates, cached per (n_micro, x shape/dtype):
        1. memory: store-activations is only a candidate when the
           vjp-residual buffers fit FLAGS_pp_store_budget_mb (default
           2048 MB per device) — else remat is forced.
        2. speed: when both fit and a `runner` is provided (train_batch
           passes the compiled-engine factory), both modes are TIMED on
           the real batch and the faster wall time wins (r3 measured
           store 24% slower than remat on an attention stage — the
           winner is shape-dependent, so it is measured, not assumed).
           One-time cost on the first train_batch: a second engine
           compile plus ~4 extra step executions per mode (dispatch-
           count differencing needs warm + 1 + 2 calls). Disable with
           FLAGS_pp_auto_measure=0 (then store wins ties, matching the
           reference default: pipeline_parallel.py:440 stores, it
           never remats).
        Explicit strategy.recompute always remats."""
        if self._remat_mode == "remat":
            return True
        import os
        budget = float(os.environ.get("FLAGS_pp_store_budget_mb",
                                      "2048")) * 1e6
        key = (sched.n_micro, x_aval.shape, str(x_aval.dtype), budget)
        cached = self._remat_choice.get(key)
        if cached is not None:
            return cached
        import jax
        import numpy as np
        from .pp_schedule import probe_residuals
        chunk_avals = [jax.ShapeDtypeStruct(leaf[0, 0].shape,
                                            leaf[0, 0].dtype)
                       for leaf in stacked]
        try:
            # same probe the store-mode engine allocates buffers from —
            # the budget estimate and the actual allocation agree
            probe = probe_residuals(stage_fn, chunk_avals, x_aval)
            need = sum(int(np.prod(a.shape)) * a.dtype.itemsize
                       for a in probe["buf_avals"]) * sched.res_buf_size
            choice = need > budget
        except Exception:
            choice = True  # unprobeable stage: safe default
        if (not choice and runner is not None
                and os.environ.get("FLAGS_pp_auto_measure", "1") != "0"):
            try:
                t_store = self._time_mode(runner, run_args, remat=False)
                t_remat = self._time_mode(runner, run_args, remat=True)
                choice = t_remat < t_store
                self.last_mode_times = {"remat_s": t_remat,
                                        "store_s": t_store}
            except Exception:
                pass  # keep the memory-gate choice (store)
        self._remat_choice[key] = choice
        return choice

    @staticmethod
    def _time_mode(runner, run_args, remat):
        """Per-step wall time of one engine mode (dispatch-count
        differencing so a remote-dispatch round trip cancels out;
        repeats=1 keeps the one-time pick cheap)."""
        from ...utils.timing import timed_dispatch_diff
        return timed_dispatch_diff(runner(remat), run_args,
                                   calls=(1, 2), repeats=1)

    # -- public API ----------------------------------------------------------
    def train_batch(self, data, optimizer, lr_scheduler=None, scaler=None):
        """data: (inputs, labels) Tensors; the batch splits into
        accumulate_steps microbatches on dim 0. Returns the mean loss
        Tensor. Runs fwd+bwd through the schedule, writes grads onto the
        per-stage param Tensors, then steps the caller's optimizer (via
        scaler.step when a GradScaler is passed, preserving its inf-skip
        and scale-update semantics)."""
        import jax.numpy as jnp
        from ...framework.core import Tensor
        from .pp_schedule import pipeline_forward_backward

        x, y = data
        xa = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        ya = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        m = self._n_micro
        if xa.shape[0] % m:
            raise ValueError(
                f"batch {xa.shape[0]} not divisible into "
                f"accumulate_steps={m} microbatches")
        xs = xa.reshape((m, xa.shape[0] // m) + xa.shape[1:])
        ys = ya.reshape((m, ya.shape[0] // m) + ya.shape[1:])

        user_loss = self._layers.loss_fn

        def engine_loss(lp, out, target):
            if user_loss is None:
                return jnp.mean(out.astype(jnp.float32))
            l = user_loss(Tensor(out), Tensor(target))
            return l._value if isinstance(l, Tensor) else l

        def stage_fn(chunk, xv):
            return self._stage_fn(list(chunk), xv)

        stacked = self._stacked()
        sched = self._sched(m)
        dummy_lp = jnp.zeros((1,), jnp.float32)
        import jax as _jax
        x_aval = _jax.ShapeDtypeStruct(xs.shape[1:], xs.dtype)

        # the engine must run under jit: shard_map with auto (non-'pp')
        # axes only composes inside a traced program
        def get_fb(remat_):
            fb_ = self._compiled.get(("train", m, remat_))
            if fb_ is None:
                def _fb(stacked_, lp_, xs_, ys_, r=remat_):
                    return pipeline_forward_backward(
                        stage_fn, engine_loss, stacked_, lp_, xs_, ys_,
                        self._mesh, sched, axis="pp", remat=r)
                fb_ = self._compiled[("train", m, remat_)] = _jax.jit(_fb)
            return fb_

        remat = self._pick_remat(stage_fn, stacked, sched, x_aval,
                                 runner=get_fb,
                                 run_args=(stacked, dummy_lp, xs, ys))
        self.last_remat = remat   # observability (tests/bench)
        loss, gstacked, _, _ = get_fb(remat)(stacked, dummy_lp, xs, ys)

        # unstack grads back onto the stage param Tensors
        for i, g in enumerate(gstacked):
            for s in range(self._pp):
                p = self._stage_params[s][i]
                ga = g[0, s]
                p.grad = Tensor(ga.astype(p._value.dtype))
        if scaler is not None:
            scaler.step(optimizer)
            scaler.update()
        else:
            optimizer.step()
        optimizer.clear_grad()
        if lr_scheduler is not None:
            lr_scheduler.step()
        return Tensor(loss)

    def eval_batch(self, data, compute_loss=True):
        """Forward-only microbatched eval; returns mean loss (or last
        stage outputs when compute_loss=False, in which case labels may
        be omitted)."""
        import jax.numpy as jnp
        from ...framework.core import Tensor
        if isinstance(data, (tuple, list)) and len(data) == 2:
            x, y = data
        else:
            x = data[0] if isinstance(data, (tuple, list)) else data
            y = None
        if compute_loss and y is None and self._layers.loss_fn is not None:
            raise ValueError("eval_batch(compute_loss=True) needs "
                             "(inputs, labels)")
        xa = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        ya = None if y is None else (
            y._value if isinstance(y, Tensor) else jnp.asarray(y))
        m = self._n_micro
        if xa.shape[0] % m:
            raise ValueError(
                f"batch {xa.shape[0]} not divisible into "
                f"accumulate_steps={m} microbatches")
        xs = xa.reshape((m, xa.shape[0] // m) + xa.shape[1:])
        stacked = self._stacked()
        # forward via pipeline_apply on composed stage params (jitted:
        # shard_map over a hybrid mesh only composes inside a trace)
        fw = self._compiled.get(("eval", m))
        if fw is None:
            def _fw(stacked_, xs_):
                squeezed = jax.tree_util.tree_map(lambda a: a[0],
                                                  stacked_)
                return pipeline_apply(
                    lambda p, v: self._stage_fn(list(p), v),
                    squeezed, xs_, self._mesh, axis="pp")
            fw = self._compiled[("eval", m)] = jax.jit(_fw)
        out = fw(stacked, xs)
        out_full = out.reshape((-1,) + out.shape[2:])
        if not compute_loss or self._layers.loss_fn is None:
            return Tensor(out_full)
        loss = self._layers.loss_fn(Tensor(out_full), Tensor(ya))
        return loss

    def parameters(self):
        return self._layers.parameters()
