"""Pipeline parallelism as an in-program SPMD schedule.

Replaces the reference's actor/schedule machinery — PipelineParallel 1F1B
(/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:440), P2P tensor protocol (pp_utils/
p2p_communication.py), and the C++ FleetExecutor interceptor runtime
(/root/reference/paddle/fluid/distributed/fleet_executor/) — with a single
jitted collective program: every pp rank runs the same code, activations
move between neighbor stages via ppermute (ICI neighbor links), and the
backward schedule falls out of autodiff through the loop (reverse
ppermute), so no send/recv protocol, no interceptors, no message bus.

Design (homogeneous stages, the transformer case):
- stage parameters are stacked on a leading [n_stages, ...] axis sharded
  over 'pp' — each device holds exactly its stage's slice;
- the microbatch loop runs n_micro + n_stages - 1 ticks; stage 0 feeds a
  fresh microbatch each tick, the last stage emits a finished microbatch
  each tick after the fill phase (GPipe schedule; per-tick work is one
  microbatch per stage, so steady-state utilization matches 1F1B — the
  1F1B advantage on GPUs is weight-memory timing, which XLA's liveness
  analysis handles for us).
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

__all__ = ["LayerDesc", "PipelineLayer", "pipeline_apply",
           "pipeline_apply_interleaved"]


class LayerDesc:
    """Declarative layer description for pipeline segmentation (parity:
    /root/reference/python/paddle/distributed/fleet/meta_parallel/
    parallel_layers/pp_layers.py:56)."""

    def __init__(self, layer_cls, *args, **kwargs):
        self.layer_cls = layer_cls
        self.args = args
        self.kwargs = kwargs

    def build_layer(self):
        return self.layer_cls(*self.args, **self.kwargs)


class PipelineLayer:
    """Container that segments a LayerDesc list into pp stages (parity:
    pp_layers.py:237 PipelineLayer). Builds all layers (single-controller:
    every process holds the program; per-stage placement happens via the
    stacked-parameter sharding in pipeline_apply)."""

    def __init__(self, layers: List[LayerDesc], num_stages: int,
                 loss_fn: Optional[Callable] = None, topology=None,
                 seg_method: str = "uniform"):
        self.descs = layers
        self.num_stages = num_stages
        self.loss_fn = loss_fn
        n = len(layers)
        per = n // num_stages
        assert per * num_stages == n, \
            f"{n} layers not divisible into {num_stages} stages"
        self.stage_layers = [
            [d.build_layer() for d in layers[i * per:(i + 1) * per]]
            for i in range(num_stages)
        ]

    def parameters(self):
        ps = []
        for stage in self.stage_layers:
            for l in stage:
                ps.extend(l.parameters())
        return ps


def pipeline_apply(stage_fn: Callable, stacked_params, x_microbatches,
                   mesh, axis: str = "pp", extra_spec=None):
    """Run a GPipe schedule over the `axis` mesh dimension.

    stage_fn(params_slice, x) -> y   (same signature for every stage)
    stacked_params: pytree whose leaves have leading dim n_stages (sharded
      over `axis` outside or resharded here)
    x_microbatches: [n_micro, ...] microbatched input of stage 0
    Returns [n_micro, ...] outputs of the last stage (valid on every rank
    — they're psum-broadcast so the loss is computable anywhere).
    """
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    n_stages = jmesh.shape[axis]

    param_specs = jax.tree_util.tree_map(
        lambda _: P(axis), stacked_params)
    x_spec = P()  # microbatches replicated into the loop; stage0 consumes

    def body(params, xs):
        # params: leaves [1, ...] (this stage's slice) → squeeze
        p_local = jax.tree_util.tree_map(lambda a: a[0], params)
        stage = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        n_ticks = n_micro + n_stages - 1
        perm_fwd = [(i, i + 1) for i in range(n_stages - 1)]

        y0_shape = jax.eval_shape(lambda p, x: stage_fn(p, x), p_local,
                                  xs[0])

        def tick(t, carry):
            prev_out, outputs = carry
            # activation arriving from the previous stage
            incoming = jax.lax.ppermute(prev_out, axis, perm_fwd)
            my_in = jnp.where(
                stage == 0,
                xs[jnp.minimum(t, n_micro - 1)].astype(incoming.dtype),
                incoming)
            out = stage_fn(p_local, my_in)
            # last stage stores finished microbatch m = t - (n_stages - 1)
            m = t - (n_stages - 1)
            outputs = jax.lax.cond(
                m >= 0,
                lambda o: o.at[jnp.maximum(m, 0)].set(
                    jnp.where(stage == n_stages - 1, out,
                              o[jnp.maximum(m, 0)])),
                lambda o: o,
                outputs)
            return out, outputs

        init_out = jnp.zeros(y0_shape.shape, y0_shape.dtype)
        outputs0 = jnp.zeros((n_micro,) + tuple(y0_shape.shape),
                             y0_shape.dtype)
        _, outputs = jax.lax.fori_loop(0, n_ticks, tick,
                                       (init_out, outputs0))
        # broadcast finished outputs from the last stage to all pp ranks
        mask = (stage == n_stages - 1).astype(outputs.dtype)
        outputs = jax.lax.psum(outputs * mask, axis)
        return outputs

    f = shard_map(body, mesh=jmesh,
                  in_specs=(param_specs, x_spec), out_specs=P(),
                  check_vma=False)
    return f(stacked_params, x_microbatches)


def pipeline_apply_interleaved(stage_fn: Callable, stacked_params,
                               x_microbatches, mesh, vpp_degree: int,
                               axis: str = "pp"):
    """Interleaved (virtual-pipeline / VPP) chunk placement — reference:
    PipelineParallelWithInterleave (meta_parallel/pipeline_parallel.py:906)
    and the VPP pass (passes/pipeline_scheduler_pass.py:465).

    The model is V = vpp_degree * n_stages chunks; physical stage s hosts
    virtual chunks {j * n_stages + s : j < vpp}. Consecutive virtual
    stages sit on consecutive physical stages, so every hop is the same
    neighbor ppermute as the plain schedule (wrapping n-1 → 0 advances a
    microbatch to its next chunk group).

    SCHEDULE NOTE: this runs lock-step — every stage computes all of its
    vpp chunk slots each tick. It provides the interleaved PLACEMENT
    (state dicts, chunk-wise sharding) for forward-only use; for
    TRAINING with the real one-chunk-per-tick circular interleaved 1F1B
    schedule (reduced bubble, bounded activation memory) use
    pp_schedule.build_pipeline_schedule + pipeline_forward_backward.

    stage_fn(params_slice, x) -> y  — one CHUNK's computation.
    stacked_params: pytree, leaves [vpp, n_stages, ...] (axis 1 sharded
      over `axis`).
    x_microbatches: [n_micro, ...].
    Returns [n_micro, ...] final-chunk outputs (psum-broadcast).
    """
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    n_stages = jmesh.shape[axis]
    V = vpp_degree * n_stages
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[0] != vpp_degree or leaf.shape[1] != n_stages:
            raise ValueError(
                f"stacked_params leaves must be [vpp={vpp_degree}, "
                f"n_stages={n_stages}, ...]; got {leaf.shape} — a "
                f"mismatched leading dim would silently clamp chunk "
                f"indices")

    param_specs = jax.tree_util.tree_map(
        lambda _: P(None, axis), stacked_params)

    def body(params, xs):
        # params leaves: [vpp, 1, ...] → this stage's vpp chunk slices
        p_local = jax.tree_util.tree_map(lambda a: a[:, 0], params)
        stage = jax.lax.axis_index(axis)
        n_micro = xs.shape[0]
        n_ticks = n_micro + V - 1
        ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]

        p_first = jax.tree_util.tree_map(lambda a: a[0], p_local)
        y_shape = jax.eval_shape(lambda p, x: stage_fn(p, x),
                                 p_first, xs[0])
        zero = jnp.zeros(y_shape.shape, y_shape.dtype)

        def tick(t, carry):
            acts, outputs = carry          # acts: [vpp, ...]
            outs = jax.vmap(stage_fn)(p_local, acts)
            arrived = jax.lax.ppermute(outs, axis, ring)
            # stage 0 re-routes on the wrap: slot j's arrival came from
            # virtual stage j*n + (n-1); its successor lives in slot j+1.
            # slot 0 consumes a fresh microbatch; the last slot's arrival
            # is a FINISHED microbatch (left the final virtual stage).
            fresh = xs[jnp.clip(t + 1, 0, n_micro - 1)].astype(
                arrived.dtype)
            shifted = jnp.concatenate(
                [fresh[None], arrived[:-1]], axis=0)
            acts_new = jnp.where(stage == 0, shifted, arrived)
            m = t - (V - 1)                 # finished microbatch id
            done = jnp.where(stage == 0, arrived[vpp_degree - 1], zero)
            outputs = jax.lax.cond(
                m >= 0,
                lambda o: o.at[jnp.maximum(m, 0)].set(
                    jnp.where(stage == 0, done, o[jnp.maximum(m, 0)])),
                lambda o: o, outputs)
            return acts_new, outputs

        acts0 = jnp.broadcast_to(zero, (vpp_degree,) + zero.shape)
        # seed slot 0 of stage 0 with microbatch 0 for tick 0
        acts0 = jnp.where(stage == 0,
                          acts0.at[0].set(xs[0].astype(zero.dtype)),
                          acts0)
        outputs0 = jnp.zeros((n_micro,) + zero.shape, zero.dtype)
        _, outputs = jax.lax.fori_loop(0, n_ticks, tick,
                                       (acts0, outputs0))
        # finished outputs live on stage 0 → broadcast to all pp ranks
        mask = (stage == 0).astype(outputs.dtype)
        return jax.lax.psum(outputs * mask, axis)

    f = shard_map(body, mesh=jmesh,
                  in_specs=(param_specs, P()), out_specs=P(),
                  check_vma=False)
    return f(stacked_params, x_microbatches)
