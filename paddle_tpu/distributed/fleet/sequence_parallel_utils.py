"""Megatron-style sequence parallelism (parity:
/root/reference/python/paddle/distributed/fleet/utils/
sequence_parallel_utils.py:85-340 — ScatterOp/GatherOp/AllGatherOp/
ReduceScatterOp PyLayers + Column/RowSequenceParallelLinear).

TPU-native: the scatter/gather PyLayers become sharding transitions on the
sequence dim; the all-gather before the column matmul and the
reduce-scatter after the row matmul are GSPMD-inserted by constraining
activations to [seq→mp-sharded] outside the pair and unsharded inside.
"""
from __future__ import annotations

import jax

from ...framework.core import Tensor, apply
from ...nn import functional as F
from ...nn import initializer as I
from ...nn.layer.layers import Layer
from .mpu import _annotate_param, _constrain, _get_mesh

__all__ = ["ScatterOp", "GatherOp", "ColumnSequenceParallelLinear",
           "RowSequenceParallelLinear", "mark_as_sequence_parallel_parameter"]


def _seq_spec(ndim, axis="mp", seq_dim=1):
    spec = [None] * ndim
    spec[seq_dim] = axis
    return spec


def ScatterOp(x, seq_dim=1):
    """Split the sequence dim across mp ranks (reshard, not a PyLayer)."""
    mesh = _get_mesh()
    if mesh is None or mesh.get_dim_size("mp") <= 1:
        return x
    return _constrain(x, mesh, _seq_spec(x.ndim, "mp", seq_dim))


def GatherOp(x, seq_dim=1):
    """Re-replicate the sequence dim (all-gather under GSPMD)."""
    mesh = _get_mesh()
    if mesh is None or mesh.get_dim_size("mp") <= 1:
        return x
    return _constrain(x, mesh, [None] * x.ndim)


AllGatherOp = GatherOp
ReduceScatterOp = ScatterOp


def mark_as_sequence_parallel_parameter(param):
    # Parameter has a __dict__ (no __slots__ of its own); plain Tensors
    # with strict slots can't carry the mark — that's a usage error
    try:
        param.sequence_parallel = True
    except AttributeError:
        raise TypeError(
            "mark_as_sequence_parallel_parameter expects a Parameter")
    return param


class ColumnSequenceParallelLinear(Layer):
    """Input arrives sequence-sharded; GSPMD all-gathers it for the
    column-parallel matmul; output stays feature-sharded."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=False, name=None):
        super().__init__()
        self.mesh = _get_mesh()
        self.gather_output = gather_output
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias else None
        if self.mesh is not None and self.mesh.get_dim_size("mp") > 1:
            _annotate_param(self.weight, self.mesh, 1, "mp")
            if self.bias is not None:
                _annotate_param(self.bias, self.mesh, 0, "mp")

    def forward(self, x):
        out = F.linear(x, self.weight, self.bias)
        if self.mesh is not None and self.mesh.get_dim_size("mp") > 1:
            spec = [None] * (out.ndim - 1) + ([None] if self.gather_output
                                              else ["mp"])
            out = _constrain(out, self.mesh, spec)
        return out


class RowSequenceParallelLinear(Layer):
    """Input is feature-sharded; output is reduce-scattered onto the
    sequence dim (one fused collective under GSPMD instead of
    all-reduce + scatter)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=True, name=None):
        super().__init__()
        self.mesh = _get_mesh()
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter((out_features,), is_bias=True) \
            if has_bias else None
        if self.mesh is not None and self.mesh.get_dim_size("mp") > 1:
            _annotate_param(self.weight, self.mesh, 0, "mp")

    def forward(self, x):
        if self.mesh is None or self.mesh.get_dim_size("mp") <= 1:
            return F.linear(x, self.weight, self.bias)
        out = F.linear(x, self.weight, None)
        # reduce-scatter onto the sequence dim
        out = _constrain(out, self.mesh, _seq_spec(out.ndim, "mp", 1))
        if self.bias is not None:
            out = out + self.bias
        return out
