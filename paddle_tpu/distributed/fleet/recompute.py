"""Activation recompute (parity:
/root/reference/python/paddle/distributed/fleet/recompute/recompute.py:108).

TPU-native: jax.checkpoint IS the recompute engine — the reference's
RecomputeFunction PyLayer (save inputs, re-run forward in backward, RNG
state juggling via mp RNG tracker) collapses into one rematerialization
annotation that XLA schedules optimally. RNG correctness under remat is
handled by jax.checkpoint's deterministic key threading (our dropout draws
from fold_in counters, which replay identically).
"""
from __future__ import annotations

from typing import Any

import jax

from ...framework.core import Tensor, apply, no_grad
from ...jit import _SwapGuard, _unwrap_tree

__all__ = ["recompute", "recompute_sequential"]


class _SubFn:
    """Generic recompute() adapter over a named sub-block of a layer:
    _SubFn(layer, "method", (modules...)) rematerializes
    layer.method(x), exposing the modules' parameters for the swap.
    Model families share this instead of growing bespoke adapters."""

    def __init__(self, layer, method, modules):
        self.layer = layer
        self.method = method
        self.modules = modules

    def parameters(self):
        ps = []
        for m in self.modules:
            ps.extend(m.parameters())
        return ps

    def __call__(self, x):
        return getattr(self.layer, self.method)(x)


def recompute(function, *args, use_reentrant: bool = True, **kwargs):
    """Run function(*args) with activation rematerialization in backward."""
    preserve = kwargs.pop("preserve_rng_state", True)
    layer_params = []
    if hasattr(function, "parameters"):
        layer_params = [p for p in function.parameters()]
    tensor_args = [a for a in args if isinstance(a, Tensor)]
    tensor_pos = [i for i, a in enumerate(args) if isinstance(a, Tensor)]
    n_p = len(layer_params)

    treedef_holder = {}

    def pure(*arrs):
        p_arrs = arrs[:n_p]
        i_arrs = arrs[n_p:]
        full_args = list(args)
        for pos, a in zip(tensor_pos, i_arrs):
            full_args[pos] = Tensor(a)
        with _SwapGuard(layer_params, list(p_arrs)):
            with no_grad():
                out = function(*full_args, **kwargs)
        flat, treedef = jax.tree_util.tree_flatten(_unwrap_tree(out))
        treedef_holder["treedef"] = treedef
        return tuple(flat) if len(flat) > 1 else flat[0]

    ckpt = jax.checkpoint(pure)
    result = apply("recompute", ckpt, *layer_params, *tensor_args)
    flat = list(result) if isinstance(result, tuple) else [result]
    return jax.tree_util.tree_unflatten(treedef_holder["treedef"], flat)


def recompute_sequential(ctx, functions, *args, **kwargs):
    """paddle recompute_sequential parity: chunked recompute over a
    Sequential container."""
    segments = ctx.get("segments", 1) if isinstance(ctx, dict) else 1
    layers = list(functions)
    n = len(layers)
    per = max(1, n // segments)
    out = args
    i = 0
    while i < n:
        chunk = layers[i:i + per]

        def run_chunk(*xs, _chunk=tuple(chunk)):
            y = xs if len(xs) > 1 else xs[0]
            for l in _chunk:
                y = l(y) if not isinstance(y, tuple) else l(*y)
            return y

        class _ChunkFn:
            def __init__(self, chunk):
                self.chunk = chunk

            def parameters(self):
                ps = []
                for l in self.chunk:
                    ps.extend(l.parameters())
                return ps

            def __call__(self, *xs):
                y = xs if len(xs) > 1 else xs[0]
                for l in self.chunk:
                    y = l(y) if not isinstance(y, tuple) else l(*y)
                return y

        out = recompute(_ChunkFn(chunk), *(out if isinstance(out, tuple)
                                           else (out,)), **kwargs)
        out = out if isinstance(out, tuple) else (out,)
        i += per
    return out[0] if len(out) == 1 else out
