"""DistributedStrategy (parity:
/root/reference/python/paddle/distributed/fleet/base/distributed_strategy.py
:1808 hybrid_configs — the protobuf-backed config becomes a plain typed
dict with the same keys).

Every knob is either consumed by a code path, or rejected/warned at set
time — silent no-op configs are a bug class this file exists to prevent
(see tests/test_distributed.py strategy-consumption test).
"""
from __future__ import annotations

import warnings
from typing import Any, Dict

__all__ = ["DistributedStrategy"]

_DEFAULT_HYBRID = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
}

_DEFAULT_AMP = {
    "init_loss_scaling": 32768.0,
    "custom_white_list": [],
    "custom_black_list": [],
    "level": "O1",
    "dtype": "bfloat16",
    "use_pure_bf16": False,
}

_DEFAULT_SHARDING = {
    "sharding_degree": 1,
    "stage": 1,
    "offload": False,
}

_DEFAULT_RECOMPUTE = {
    "enable": False,
    "checkpoints": [],
}

_DEFAULT_PIPELINE = {
    "accumulate_steps": 1,
    "micro_batch_size": 1,
    # selects the pp_schedule table: "1F1B", "FThenB"/"GPipe", or (with
    # vpp_degree > 1) circular interleaved 1F1B. Validated by
    # fleet.pipeline_schedule_from_strategy — unknown modes raise.
    "schedule_mode": "1F1B",
    "vpp_degree": 1,
}

_DEFAULT_GRADIENT_MERGE = {
    "k_steps": 1,
    "avg": True,
}


class DistributedStrategy:
    """Where each knob is consumed (the 'consumed or rejected' registry —
    audited by tests):

    - hybrid_configs     → fleet.init (HybridCommunicateGroup mesh axes)
    - amp / amp_configs  → sharding_recipes.apply_hybrid_shardings
    - sharding(+configs) → sharding_recipes (ZeRO stage placements)
    - recompute(+configs)→ sharding_recipes (jax.checkpoint policy)
    - pipeline(+configs) → fleet.pipeline / pp_schedule tables
    - gradient_merge(+configs) → auto.Engine → jit.TrainStep k-step
      accumulation (f32 accumulators, optimizer applied every k)
    - find_unused_parameters, fuse_grad_size_in_MB → meaningless under
      whole-program jit (grads are always computed in-program and fused
      by XLA); setting a non-default value WARNS instead of silently
      doing nothing.
    """

    # config-dict attr → allowed keys (assignment merges into defaults;
    # unknown keys are rejected loudly)
    _CONFIG_KEYS = {
        "hybrid_configs": set(_DEFAULT_HYBRID),
        "amp_configs": set(_DEFAULT_AMP),
        "sharding_configs": set(_DEFAULT_SHARDING),
        "recompute_configs": set(_DEFAULT_RECOMPUTE),
        "pipeline_configs": set(_DEFAULT_PIPELINE),
        "gradient_merge_configs": set(_DEFAULT_GRADIENT_MERGE),
    }
    # knobs that cannot do anything under whole-program jit: warn, don't
    # silently accept (value = the inert default)
    _NOOP_KNOBS = {
        "find_unused_parameters": False,
        "fuse_grad_size_in_MB": 32,
    }
    # knobs that EXIST in the reference DistributedStrategy but are
    # consciously inert here (descoped/irrelevant on TPU — see
    # COVERAGE.md): accepted with a warning so reference-ported code
    # runs, while typos still raise. Distinct from _NOOP_KNOBS only in
    # not being pre-initialized attributes.
    _REFERENCE_INERT_KNOBS = frozenset({
        "a_sync", "a_sync_configs",               # parameter-server mode
        "without_graph_optimization",             # XLA always optimizes
        "heter_ccl_mode", "is_fl_ps_mode",        # heterogeneous PS
        "localsgd", "localsgd_configs",           # see COVERAGE.md
        "adaptive_localsgd", "adaptive_localsgd_configs",
        "dgc", "dgc_configs",                     # grad compression
        "lars", "lars_configs", "lamb", "lamb_configs",
        "fp16_allreduce", "sync_nccl_allreduce",  # NCCL-specific
        "nccl_comm_num", "use_hierarchical_allreduce",
        "sync_batch_norm", "cudnn_exhaustive_search",
        "cudnn_batchnorm_spatial_persistent", "conv_workspace_size_limit",
        "auto", "semi_auto", "auto_search", "qat", "qat_configs",
    })

    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = dict(_DEFAULT_HYBRID)
        self.amp = False
        self.amp_configs: Dict[str, Any] = dict(_DEFAULT_AMP)
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = dict(_DEFAULT_SHARDING)
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = dict(_DEFAULT_RECOMPUTE)
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = dict(_DEFAULT_PIPELINE)
        self.gradient_merge = False
        self.gradient_merge_configs = dict(_DEFAULT_GRADIENT_MERGE)
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32
        object.__setattr__(self, "_sealed", True)

    def __setattr__(self, name, value):
        if getattr(self, "_sealed", False) and name not in self.__dict__:
            if name in self._REFERENCE_INERT_KNOBS:
                warnings.warn(
                    f"DistributedStrategy.{name} exists in the reference "
                    "API but is inert on TPU (descoped or subsumed by "
                    "XLA — see COVERAGE.md); the value is stored and "
                    "ignored.", stacklevel=2)
                object.__setattr__(self, name, value)
                return
            raise AttributeError(
                f"DistributedStrategy has no knob {name!r} — unknown "
                "names are rejected so a typo can't become a silent "
                f"no-op. Known knobs: "
                f"{sorted(k for k in self.__dict__ if not k.startswith('_'))}")
        if name in self._CONFIG_KEYS and isinstance(value, dict):
            known = self._CONFIG_KEYS[name]
            unknown = set(value) - known
            if unknown:
                raise ValueError(
                    f"unknown {name} key(s) {sorted(unknown)}; "
                    f"known: {sorted(known)}")
            if hasattr(self, name):
                merged = dict(getattr(self, name))
                merged.update(value)
                value = merged
        if name in self._NOOP_KNOBS and getattr(self, "_sealed", False) \
                and value != self._NOOP_KNOBS[name]:
            warnings.warn(
                f"DistributedStrategy.{name} has no effect on TPU: "
                "gradients are computed in-program under jit and fused "
                "by XLA, so there is no reducer to configure.",
                stacklevel=2)
        object.__setattr__(self, name, value)

    def gradient_merge_k(self):
        """(k_steps, avg) if gradient merge is enabled, else (1, True).
        The consumer seam for auto.Engine / TrainStep."""
        if not self.gradient_merge:
            return 1, True
        cfg = self.gradient_merge_configs
        return int(cfg.get("k_steps", 1)), bool(cfg.get("avg", True))

    def __repr__(self):
        return (f"DistributedStrategy(hybrid={self.hybrid_configs}, "
                f"amp={self.amp}, sharding={self.sharding}, "
                f"recompute={self.recompute}, pipeline={self.pipeline}, "
                f"gradient_merge={self.gradient_merge})")
