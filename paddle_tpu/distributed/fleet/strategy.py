"""DistributedStrategy (parity:
/root/reference/python/paddle/distributed/fleet/base/distributed_strategy.py
:1808 hybrid_configs — the protobuf-backed config becomes a plain typed
dict with the same keys)."""
from __future__ import annotations

from typing import Any, Dict

__all__ = ["DistributedStrategy"]

_DEFAULT_HYBRID = {
    "dp_degree": 1,
    "mp_degree": 1,
    "pp_degree": 1,
    "sharding_degree": 1,
    "sep_degree": 1,
    "order": ["dp", "pp", "sharding", "sep", "mp"],
}

_DEFAULT_AMP = {
    "init_loss_scaling": 32768.0,
    "custom_white_list": [],
    "custom_black_list": [],
    "level": "O1",
    "dtype": "bfloat16",
    "use_pure_bf16": False,
}

_DEFAULT_SHARDING = {
    "sharding_degree": 1,
    "stage": 1,
    "offload": False,
}

_DEFAULT_RECOMPUTE = {
    "enable": False,
    "checkpoints": [],
}

_DEFAULT_PIPELINE = {
    "accumulate_steps": 1,
    "micro_batch_size": 1,
    # selects the pp_schedule table: "1F1B", "FThenB"/"GPipe", or (with
    # vpp_degree > 1) circular interleaved 1F1B. Validated by
    # fleet.pipeline_schedule_from_strategy — unknown modes raise.
    "schedule_mode": "1F1B",
    "vpp_degree": 1,
}


class DistributedStrategy:
    def __init__(self):
        self.hybrid_configs: Dict[str, Any] = dict(_DEFAULT_HYBRID)
        self.amp = False
        self.amp_configs: Dict[str, Any] = dict(_DEFAULT_AMP)
        self.sharding = False
        self.sharding_configs: Dict[str, Any] = dict(_DEFAULT_SHARDING)
        self.recompute = False
        self.recompute_configs: Dict[str, Any] = dict(_DEFAULT_RECOMPUTE)
        self.pipeline = False
        self.pipeline_configs: Dict[str, Any] = dict(_DEFAULT_PIPELINE)
        self.gradient_merge = False
        self.gradient_merge_configs = {"k_steps": 1, "avg": True}
        self.find_unused_parameters = False
        self.fuse_grad_size_in_MB = 32

    def __setattr__(self, name, value):
        if name == "hybrid_configs" and isinstance(value, dict) and \
                hasattr(self, "hybrid_configs"):
            merged = dict(self.hybrid_configs)
            merged.update(value)
            object.__setattr__(self, name, merged)
        else:
            object.__setattr__(self, name, value)

    def __repr__(self):
        return (f"DistributedStrategy(hybrid={self.hybrid_configs}, "
                f"amp={self.amp}, sharding={self.sharding}, "
                f"recompute={self.recompute}, pipeline={self.pipeline})")
