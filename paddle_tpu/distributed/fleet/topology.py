"""Hybrid topology (parity:
/root/reference/python/paddle/distributed/fleet/base/topology.py —
CommunicateTopology:61, HybridCommunicateGroup:174).

TPU-native: the rank grid IS a jax mesh with named axes. The reference
carves NCCL subgroups out of a flattened rank list; here each parallelism
axis is a mesh axis, and "groups" are the axis names that GSPMD collectives
ride. Axis order (outer→inner) follows the scaling-book recipe: put the
highest-traffic axis (tp) innermost so its collectives ride the
fastest ICI links.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np

from ..mesh import ProcessMesh

__all__ = ["CommunicateTopology", "HybridCommunicateGroup"]

# outer → inner (dp slowest-varying, tp fastest / innermost)
_AXIS_ORDER = ["dp", "pp", "sharding", "sep", "mp"]


class CommunicateTopology:
    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = hybrid_group_names or ["data", "pipe", "model"]
        self._dims = dims or [1, 1, 1]
        self.coordinate = None

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    def world_size(self):
        return int(np.prod(self._dims))


class HybridCommunicateGroup:
    """Builds the device mesh from hybrid degrees {dp, mp(tp), pp,
    sharding, sep} and exposes paddle's group-query API plus the jax mesh
    for the compiled path."""

    def __init__(self, topology=None, *, dp_degree=1, mp_degree=1,
                 pp_degree=1, sharding_degree=1, sep_degree=1):
        n = jax.device_count()
        degrees = {"dp": dp_degree, "pp": pp_degree,
                   "sharding": sharding_degree, "sep": sep_degree,
                   "mp": mp_degree}
        specified = int(np.prod([v for v in degrees.values()]))
        if specified != n:
            # auto-fill dp like the reference does
            rest = n // max(1, (specified // max(dp_degree, 1)))
            if dp_degree * 0 == 0 and specified != n:
                other = int(np.prod([degrees[a] for a in _AXIS_ORDER
                                     if a != "dp"]))
                if n % other == 0:
                    degrees["dp"] = n // other
                else:
                    raise ValueError(
                        f"hybrid degrees {degrees} don't divide device "
                        f"count {n}")
        self._degrees = degrees
        shape = tuple(degrees[a] for a in _AXIS_ORDER)
        self._mesh = ProcessMesh(
            np.arange(n).reshape(shape), _AXIS_ORDER)
        self.global_rank = 0  # single-controller

    # -- mesh access (compiled path) ----------------------------------------
    @property
    def mesh(self) -> ProcessMesh:
        return self._mesh

    def jax_mesh(self):
        return self._mesh.to_jax_mesh()

    # -- paddle query API ----------------------------------------------------
    def get_parallel_mode(self):
        if self._degrees["pp"] > 1:
            return "pipeline"
        if self._degrees["sharding"] > 1:
            return "sharding_parallel"
        if self._degrees["mp"] > 1:
            return "tensor_parallel"
        return "data_parallel"

    def _degree(self, axis):
        return self._degrees[axis]

    def get_data_parallel_world_size(self):
        return self._degrees["dp"]

    def get_model_parallel_world_size(self):
        return self._degrees["mp"]

    def get_pipe_parallel_world_size(self):
        return self._degrees["pp"]

    def get_sharding_parallel_world_size(self):
        return self._degrees["sharding"]

    def get_sep_parallel_world_size(self):
        return self._degrees["sep"]

    def get_data_parallel_rank(self):
        return 0

    def get_model_parallel_rank(self):
        return 0

    def get_stage_id(self):
        return 0

    def get_sharding_parallel_rank(self):
        return 0

    # group objects = axis names for the compiled path
    def get_data_parallel_group(self):
        return "dp"

    def get_model_parallel_group(self):
        return "mp"

    def get_pipe_parallel_group(self):
        return "pp"

    def get_sharding_parallel_group(self):
        return "sharding"

    def get_sep_parallel_group(self):
        return "sep"

    def get_check_parallel_group(self, *a):
        return "mp"

    def topology(self):
        return self._degrees
