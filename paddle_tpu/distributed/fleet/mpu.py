"""Tensor-parallel (Megatron-style) layers (parity:
/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_layers.py —
VocabParallelEmbedding:47, ColumnParallelLinear:333, RowParallelLinear:540,
ParallelCrossEntropy:741).

TPU-native: no _c_identity/_mp_allreduce PyLayers
(/root/reference/python/paddle/distributed/fleet/layers/mpu/mp_ops.py) —
layers carry NamedSharding annotations on weights and sharding constraints
on activations; GSPMD inserts the identity/all-reduce/all-gather pair in
forward/backward exactly as the reference's manual PyLayers do, but fused
and overlapped by the XLA scheduler.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ...framework.core import Parameter, Tensor, apply
from ...nn import initializer as I
from ...nn.layer.layers import Layer
from ..mesh import ProcessMesh
from ..placement import Replicate, Shard

__all__ = ["ColumnParallelLinear", "RowParallelLinear",
           "VocabParallelEmbedding", "ParallelCrossEntropy"]


def _get_mesh() -> Optional[ProcessMesh]:
    from . import get_hybrid_communicate_group
    hcg = get_hybrid_communicate_group()
    return hcg.mesh if hcg is not None else None


def _annotate_param(p: Parameter, mesh: ProcessMesh, tensor_dim: Optional[int],
                    axis: str):
    """Shard param dim `tensor_dim` over mesh axis `axis` (replicate when
    tensor_dim is None); stores placements + places the array."""
    placements = []
    for name in mesh.dim_names:
        if name == axis and tensor_dim is not None:
            placements.append(Shard(tensor_dim))
        else:
            placements.append(Replicate())
    from ..api import placements_to_spec
    sharding = jax.sharding.NamedSharding(
        mesh.to_jax_mesh(), placements_to_spec(mesh, placements))
    p._replace(jax.device_put(p._value, sharding))
    p.process_mesh = mesh
    p.placements = placements
    return p


def _constrain(t: Tensor, mesh: ProcessMesh, spec) -> Tensor:
    """with_sharding_constraint that works on tracers and concrete arrays."""
    sharding = jax.sharding.NamedSharding(mesh.to_jax_mesh(),
                                          jax.sharding.PartitionSpec(*spec))
    def f(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sharding)
        return jax.device_put(a, sharding)
    return apply("sharding_constraint", f, t)


def _maybe_bias(layer: Layer, out_features: int, has_bias, bias_attr):
    """nn.Linear's bias convention (nn/layer/common.py Linear):
    bias_attr=False suppresses the bias, anything else flows to
    create_parameter as the attr."""
    if not has_bias or bias_attr is False:
        return None
    return layer.create_parameter((out_features,), attr=bias_attr,
                                  is_bias=True)


class ColumnParallelLinear(Layer):
    """Y = X @ W with W column-sharded over the 'mp' axis. Output stays
    sharded on the feature dim unless gather_output=True."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, gather_output=True, fuse_matmul_bias=False,
                 mp_group=None, name=None, bias_attr=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.gather_output = gather_output
        self.mesh = _get_mesh()
        self.axis = "mp"
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = _maybe_bias(self, out_features, has_bias, bias_attr)
        if self.mesh is not None and self.mesh.get_dim_size(self.axis) > 1:
            _annotate_param(self.weight, self.mesh, 1, self.axis)
            if self.bias is not None:
                _annotate_param(self.bias, self.mesh, 0, self.axis)

    def forward(self, x):
        from ...nn import functional as F
        out = F.linear(x, self.weight, self.bias)
        if self.mesh is not None and self.mesh.get_dim_size(self.axis) > 1:
            nd = out.ndim
            if self.gather_output:
                spec = [None] * nd
            else:
                spec = [None] * (nd - 1) + [self.axis]
            out = _constrain(out, self.mesh, spec)
        return out


class RowParallelLinear(Layer):
    """Y = X @ W with W row-sharded (contracting dim). The partial-sum
    all-reduce is GSPMD-inserted when the output is constrained
    replicated."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 has_bias=True, input_is_parallel=False,
                 fuse_matmul_bias=False, mp_group=None, name=None,
                 bias_attr=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.input_is_parallel = input_is_parallel
        self.mesh = _get_mesh()
        self.axis = "mp"
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = _maybe_bias(self, out_features, has_bias, bias_attr)
        if self.mesh is not None and self.mesh.get_dim_size(self.axis) > 1:
            _annotate_param(self.weight, self.mesh, 0, self.axis)
            # bias replicated

    def forward(self, x):
        from ...nn import functional as F
        if self.mesh is not None and self.mesh.get_dim_size(self.axis) > 1:
            if self.input_is_parallel:
                nd = x.ndim
                x = _constrain(x, self.mesh, [None] * (nd - 1) + [self.axis])
            out = F.linear(x, self.weight, None)
            out = _constrain(out, self.mesh, [None] * out.ndim)
            if self.bias is not None:
                out = out + self.bias
            return out
        return F.linear(x, self.weight, self.bias)


class VocabParallelEmbedding(Layer):
    """Embedding with the vocab dim sharded over 'mp'."""

    def __init__(self, num_embeddings, embedding_dim, weight_attr=None,
                 mp_group=None, name=None):
        super().__init__()
        self.num_embeddings = num_embeddings
        self.embedding_dim = embedding_dim
        self.mesh = _get_mesh()
        self.axis = "mp"
        self.weight = self.create_parameter(
            (num_embeddings, embedding_dim), attr=weight_attr,
            default_initializer=I.Normal(0.0, 0.02))
        if self.mesh is not None and self.mesh.get_dim_size(self.axis) > 1:
            _annotate_param(self.weight, self.mesh, 0, self.axis)

    def forward(self, x):
        from ...nn import functional as F
        out = F.embedding(x, self.weight)
        if self.mesh is not None and self.mesh.get_dim_size(self.axis) > 1:
            out = _constrain(out, self.mesh, [None] * out.ndim)
        return out


class ParallelCrossEntropy(Layer):
    """Cross entropy over class-dim-sharded logits. The log-sum-exp
    reductions become cross-'mp' psums under GSPMD — no manual comm
    (reference does explicit max/sum allreduce pairs)."""

    def __init__(self, mp_group=None, name=None, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index
        self.mesh = _get_mesh()

    def forward(self, input, label):
        from ...nn import functional as F
        loss = F.cross_entropy(input, label, reduction="none",
                               ignore_index=self.ignore_index)
        return loss
