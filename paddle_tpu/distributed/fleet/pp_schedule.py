"""True pipeline-parallel schedules (1F1B / interleaved-1F1B / FThenB) as
table-driven SPMD programs.

Reference parity: PipelineParallel.forward_backward_pipeline (1F1B,
/root/reference/python/paddle/distributed/fleet/meta_parallel/
pipeline_parallel.py:440), PipelineParallelWithInterleave (:906), FThenB
(:1489), and the schedule passes (passes/pipeline_scheduler_pass.py:48).

TPU-native design — no actor runtime, no p2p protocol:
- The *entire* schedule is static (n_micro, n_stages, vpp are compile-time
  constants), so we compute it host-side: for every (tick, stage) the
  tables say which chunk to forward, which to backward, which buffer slot
  each activation/gradient lives in. The device program is one
  `lax.scan` over the tick tables inside a `shard_map` that is manual
  over the 'pp' mesh axis only (tp/dp/fsdp compose as GSPMD auto axes).
- Forward activations hop stage s -> s+1 (ring ppermute, wrapping
  (p-1) -> 0 advances a microbatch to its next virtual-chunk round);
  gradients hop the reverse ring.
- Backward rematerializes the chunk forward from its saved *input* (the
  1F1B memory story: the act buffer holds at most O(n_stages [* vpp])
  in-flight microbatch inputs, never O(n_micro) — compare FThenB where
  it provably holds O(n_micro * vpp); see `PipelineSchedule.act_buf_size`).
- The last virtual chunk computes the loss and its gradient seed in the
  forward slot, so the backward wave starts the same tick (true 1F1B
  pairing, not fwd-all-then-bwd-all).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["PipelineSchedule", "build_pipeline_schedule",
           "pipeline_forward_backward", "make_pipeline_loss_fn"]


_MODES = {
    "fthenb": "fthenb", "gpipe": "fthenb", "f-then-b": "fthenb",
    "1f1b": "1f1b", "vpp": "1f1b", "interleave": "1f1b",
    "interleaved": "1f1b", "1f1b-interleave": "1f1b",
    # zero-bubble (ZB-H1 family): the backward splits into an
    # input-grad slot B (critical path) and a weight-grad slot W that
    # the scheduler defers into would-be bubble ticks. The reference
    # has no such schedule (pipeline_scheduler_pass.py:48 stops at
    # 1F1B/VPP); store-activations mode only — under jax.vjp, a
    # dx-only call DCEs the dw matmuls and vice versa, so B and W cost
    # ~1 forward each with shared residuals.
    "zb": "zb", "zb1": "zb", "zero-bubble": "zb", "zbh1": "zb",
}


@dataclass
class PipelineSchedule:
    """Static tick tables for one (n_stages, n_micro, vpp, mode) config.

    All tables are int32/bool ndarrays of shape [n_ticks, n_stages]."""
    n_stages: int
    n_micro: int
    vpp: int
    mode: str
    n_ticks: int
    act_buf_size: int
    grad_buf_size: int
    tables: Dict[str, np.ndarray] = field(repr=False)
    # store-activations mode: vjp-residual slots (write fwd-tick, read
    # bwd-tick). Defaulted for schedules built before this field existed.
    res_buf_size: int = 1

    # Tick cost model (single-chunk-forward units). The engine cond-
    # skips invalid slots (pipeline_forward_backward wraps the fwd and
    # bwd compute in lax.cond on the per-stage validity bits), so a tick
    # costs what its *busiest stage* actually runs: fwd = 1; bwd = 2
    # from stored residuals, 3 under remat (remat-fwd 1 + bwd 2). The
    # lock-step barrier is the per-tick ppermute pair, hence max over
    # stages. bench.py `pp` measures the real on-chip number.
    CHUNK_COST_PER_TICK = 4.0          # full fwd+bwd tick, remat (back-compat)

    def chunk_cost_per_tick(self, remat: bool = True) -> float:
        return 4.0 if remat else 3.0

    def tick_costs(self, remat: bool = True) -> np.ndarray:
        """Per-tick wall cost [n_ticks]: max over stages of the work the
        cond-skipping engine actually executes that tick."""
        if self.mode == "zb":
            # store-mode units: fwd 1, input-grad B 1, weight-grad W 1
            per_stage = (self.tables["fwd_valid"].astype(np.float64)
                         + self.tables["bwd_valid"].astype(np.float64)
                         + self.tables["w_valid"].astype(np.float64))
        else:
            b = 3.0 if remat else 2.0
            per_stage = (self.tables["fwd_valid"].astype(np.float64)
                         + b * self.tables["bwd_valid"].astype(np.float64))
        return per_stage.max(axis=1)

    @property
    def work_units(self) -> float:
        """Total wall cost in single-chunk-forward units for the whole
        step (cond-skipping engine, remat mode)."""
        return float(self.tick_costs(remat=True).sum())

    def efficiency(self, remat: bool = True) -> float:
        """ideal / achieved wall ratio — 1.0 means no bubble. Ideal
        per-stage work is n_micro*vpp fwd + n_micro*vpp bwd."""
        if self.mode == "zb":
            ideal = self.n_micro * self.vpp * 3.0
        else:
            b = 3.0 if remat else 2.0
            ideal = self.n_micro * self.vpp * (1.0 + b)
        return ideal / float(self.tick_costs(remat).sum())

    def bubble_overhead(self, remat: bool = True) -> float:
        return 1.0 - self.efficiency(remat)

    def __hash__(self):  # identity — schedules are built once per step fn
        return id(self)


def build_pipeline_schedule(n_stages: int, n_micro: int, vpp: int = 1,
                            mode: str = "1F1B",
                            inflight_cap=None) -> PipelineSchedule:
    """Greedy dependency-respecting list scheduler.

    Work items: fwd(m, q) and bwd(m, q) for microbatch m and virtual stage
    q in [0, vpp*n_stages); virtual stage q lives on physical stage q % p
    (chunk j = q // p), so consecutive virtual stages are ring neighbors.
    Per tick each stage runs at most one fwd and one bwd item. A message
    (activation or gradient) sent at tick t is consumable from tick t+1.

    inflight_cap: per-stage in-flight microbatch limit (int, per-stage
    list, or None = auto). The lock-step tick runs one fwd AND one bwd
    slot, so a stage only reaches full throughput when enough
    microbatches are in flight to cover the fwd+bwd ring round-trip —
    2*(p-s)-1 at stage s. That is the v=1 auto default (it reaches the
    classic async-1F1B bubble (p-1)/(m+p-1) exactly, at ~2x the
    reference's p-deep in-flight window — cheap here because remat mode
    only holds chunk *inputs* in flight). Pass the Megatron depth
    (p - s) to reproduce the reference's tighter memory story at the
    cost of ~2x bubble. v>1 auto uses the Megatron interleave depth,
    which already reaches the classic bound."""
    p, m, v = int(n_stages), int(n_micro), int(vpp)
    mkey = _MODES.get(mode.lower())
    if mkey is None:
        raise ValueError(
            f"unknown pipeline schedule_mode {mode!r}; expected one of "
            f"{sorted(set(_MODES))}")
    if v > 1 and m % p != 0:
        raise ValueError(
            f"interleaved schedule needs n_micro ({m}) divisible by "
            f"n_stages ({p})")
    V = v * p
    stage_of = lambda q: q % p

    # --- greedy tick simulation -----------------------------------------
    fwd_tick: Dict[Tuple[int, int], int] = {}
    bwd_tick: Dict[Tuple[int, int], int] = {}
    stage_items = [[(mb, q) for q in range(V) if stage_of(q) == s
                    for mb in range(m)] for s in range(p)]
    # 1F1B in-flight cap on *injection* (q==0).
    if inflight_cap is not None:
        caps = [int(inflight_cap)] * p if np.isscalar(inflight_cap) \
            else [int(c) for c in inflight_cap]
        if len(caps) != p or min(caps) < 1:
            raise ValueError(
                f"inflight_cap must be a positive int or a length-{p} "
                f"per-stage list; got {inflight_cap!r}")
    elif v > 1:
        caps = [2 * (p - s - 1) + (v - 1) * p + 1 for s in range(p)]
    else:
        caps = [2 * (p - s) - 1 for s in range(p)]

    fwd_sched = [[] for _ in range(p)]   # per tick: list over stages
    bwd_sched = [[] for _ in range(p)]
    per_tick = []                        # [(fwd_sel, bwd_sel, w_sel)]
    w_tick: Dict[Tuple[int, int], int] = {}
    n_items = m * V
    t = 0
    limit = 6 * n_items + 8 * V + 64
    while len(bwd_tick) < n_items or \
            (mkey == "zb" and len(w_tick) < n_items):
        if t > limit:
            raise RuntimeError(
                f"pipeline scheduler failed to converge (p={p}, m={m}, "
                f"v={v}, mode={mkey}); scheduled {len(bwd_tick)}/{n_items}")
        fwd_sel: Dict[int, Tuple[int, int]] = {}
        for s in range(p):
            inflight = sum(1 for it in stage_items[s]
                           if it in fwd_tick and it not in bwd_tick)
            cands = []
            for it in stage_items[s]:
                if it in fwd_tick:
                    continue
                mb, q = it
                if q == 0:
                    if mkey == "1f1b" and inflight >= caps[s]:
                        continue
                elif fwd_tick.get((mb, q - 1), t) > t - 1:
                    continue
                cands.append(it)
            if cands:
                # deepest virtual stage first (drain), then oldest microbatch
                it = max(cands, key=lambda it: (it[1], -it[0]))
                fwd_sel[s] = it
                fwd_tick[it] = t
        all_fwd_done = len(fwd_tick) == n_items
        bwd_sel: Dict[int, Tuple[int, int]] = {}
        for s in range(p):
            cands = []
            for it in stage_items[s]:
                if it not in fwd_tick or it in bwd_tick:
                    continue
                mb, q = it
                if mkey == "fthenb" and not all_fwd_done:
                    continue
                if q == V - 1:
                    if fwd_tick[it] > t:       # seed ready same tick as fwd
                        continue
                elif bwd_tick.get((mb, q + 1), t) > t - 1:
                    continue
                cands.append(it)
            if cands:
                # oldest microbatch first, then deepest chunk
                it = min(cands, key=lambda it: (it[0], -it[1]))
                bwd_sel[s] = it
                bwd_tick[it] = t
        # zero-bubble W pass. Policy (swept over cap shapes on the
        # lock-step max-cost model): stage s runs W inline with fwd+B
        # (a 3-unit tick is bubble-free — ideal work IS 3 units/micro)
        # but keeps up to `s` W items deferred, exactly filling its
        # cooldown while the B-chain of the last microbatches drains
        # through shallower stages. p4/m16: bubble 0.158 (1F1B-store)
        # -> 0.111; the residual is the forced lock-step B drain (the
        # async-model ZB-H1 floor (p-1)/3m is not reachable here).
        w_sel: Dict[int, Tuple[int, int]] = {}
        if mkey == "zb":
            drained = (len(fwd_tick) == n_items
                       and len(bwd_tick) == n_items)
            for s in range(p):
                busy = (s in fwd_sel) + (s in bwd_sel)
                backlog = sum(1 for it in stage_items[s]
                              if bwd_tick.get(it, t + 1) <= t
                              and it not in w_tick)
                if busy >= 2 and not drained and backlog <= s:
                    continue
                cands = [it for it in stage_items[s]
                         if bwd_tick.get(it, t + 1) <= t
                         and it not in w_tick]
                if cands:
                    it = min(cands, key=lambda it: (it[0], -it[1]))
                    w_sel[s] = it
                    w_tick[it] = t
        per_tick.append((fwd_sel, bwd_sel, w_sel))
        t += 1
    n_ticks = t

    # --- static buffer-slot allocation ----------------------------------
    # act slot per (mb, q>=1): lives [arrival = fwd_tick[(mb,q-1)]+1,
    # bwd_tick[(mb,q)]]; grad slot per (mb, q): lives [seed/arrival tick,
    # bwd_tick[(mb,q)]]. Allocation is per stage (buffers are per-device).
    def _alloc(intervals):
        """intervals: {item: (stage, t_write, t_read)} -> (slots, size).

        A slot busy through t_read frees for writes at t_read + 1 (reads
        happen in the same tick's compute phase, after arrival writes)."""
        slots, size = {}, 0
        for s in range(p):
            evs = sorted((iv[1], iv[2], it) for it, iv in intervals.items()
                         if iv[0] == s)
            busy: list = []   # (t_read, slot)
            free: list = []
            next_slot = 0
            for t_w, t_r, it in evs:
                still = []
                for t_busy_until, b_slot in busy:
                    if t_busy_until >= t_w:
                        still.append((t_busy_until, b_slot))
                    else:
                        free.append(b_slot)
                busy = still
                if free:
                    slot = min(free)
                    free.remove(slot)
                else:
                    slot = next_slot
                    next_slot += 1
                busy.append((t_r, slot))
                slots[it] = slot
                size = max(size, slot + 1)
        return slots, size

    act_iv = {}
    for (mb, q), ft in fwd_tick.items():
        if q >= 1:
            act_iv[(mb, q)] = (stage_of(q), fwd_tick[(mb, q - 1)] + 1,
                               bwd_tick[(mb, q)])
    grad_iv = {}
    for (mb, q), bt in bwd_tick.items():
        t_w = fwd_tick[(mb, V - 1)] if q == V - 1 \
            else bwd_tick[(mb, q + 1)] + 1
        # zb: the incoming gradient is read again by the deferred
        # weight-grad slot, extending the slot's lifetime
        t_r = max(bt, w_tick.get((mb, q), bt))
        grad_iv[(mb, q)] = (stage_of(q), t_w, t_r)
    act_slot, act_size = _alloc(act_iv)
    grad_slot, grad_size = _alloc(grad_iv)
    # residual slots (store-activations mode): written at the fwd tick,
    # read at the bwd tick (and the W tick under zb) — every (mb, q)
    # including q == 0 (whose act input comes from xs, no act slot)
    res_iv = {(mb, q): (stage_of(q), ft,
                        max(bwd_tick[(mb, q)],
                            w_tick.get((mb, q), bwd_tick[(mb, q)])))
              for (mb, q), ft in fwd_tick.items()}
    res_slot, res_size = _alloc(res_iv)

    # --- emit tables -----------------------------------------------------
    def zi():
        return np.zeros((n_ticks, p), np.int32)

    def zb():
        return np.zeros((n_ticks, p), bool)

    T = {k: zi() for k in
         ("fwd_chunk", "fwd_mb", "fwd_in_slot", "fwd_seed_slot",
          "rx_slot", "grx_slot", "bwd_chunk", "bwd_mb", "bwd_in_slot",
          "bwd_gslot", "res_slot", "bwd_res_slot")}
    T.update({k: zb() for k in
              ("fwd_valid", "fwd_is_first", "fwd_is_last", "rx_valid",
               "grx_valid", "bwd_valid", "bwd_is_first")})
    if mkey == "zb":
        T.update({k: zi() for k in ("w_chunk", "w_mb", "w_res_slot",
                                    "w_gslot")})
        T["w_valid"] = zb()
    for tick, (fwd_sel, bwd_sel, w_sel) in enumerate(per_tick):
        for s, (mb, q) in w_sel.items():
            T["w_valid"][tick, s] = True
            T["w_chunk"][tick, s] = q // p
            T["w_mb"][tick, s] = mb
            T["w_res_slot"][tick, s] = res_slot[(mb, q)]
            T["w_gslot"][tick, s] = grad_slot[(mb, q)]
        for s, (mb, q) in fwd_sel.items():
            T["fwd_valid"][tick, s] = True
            T["fwd_chunk"][tick, s] = q // p
            T["fwd_mb"][tick, s] = mb
            T["fwd_is_first"][tick, s] = q == 0
            T["fwd_is_last"][tick, s] = q == V - 1
            if q >= 1:
                T["fwd_in_slot"][tick, s] = act_slot[(mb, q)]
            T["res_slot"][tick, s] = res_slot[(mb, q)]
            if q == V - 1:
                T["fwd_seed_slot"][tick, s] = grad_slot[(mb, q)]
            # receiver-side arrival of this fwd's output (next virtual stage)
            if q + 1 <= V - 1:
                rs, rt = stage_of(q + 1), tick + 1
                T["rx_valid"][rt, rs] = True
                T["rx_slot"][rt, rs] = act_slot[(mb, q + 1)]
        for s, (mb, q) in bwd_sel.items():
            T["bwd_valid"][tick, s] = True
            T["bwd_chunk"][tick, s] = q // p
            T["bwd_mb"][tick, s] = mb
            T["bwd_is_first"][tick, s] = q == 0
            if q >= 1:
                T["bwd_in_slot"][tick, s] = act_slot[(mb, q)]
            T["bwd_res_slot"][tick, s] = res_slot[(mb, q)]
            T["bwd_gslot"][tick, s] = grad_slot[(mb, q)]
            if q >= 1:  # this bwd's dx arrives at the upstream stage
                rs, rt = stage_of(q - 1), tick + 1
                T["grx_valid"][rt, rs] = True
                T["grx_slot"][rt, rs] = grad_slot[(mb, q - 1)]

    # sanity: every fwd/bwd read happens at/after its write
    for (mb, q), ft in fwd_tick.items():
        if q >= 1:
            assert fwd_tick[(mb, q - 1)] + 1 <= ft, (mb, q)
        assert bwd_tick[(mb, q)] >= ft, (mb, q)

    return PipelineSchedule(
        n_stages=p, n_micro=m, vpp=v, mode=mkey, n_ticks=n_ticks,
        act_buf_size=max(1, act_size), grad_buf_size=max(1, grad_size),
        res_buf_size=max(1, res_size), tables=T)


def _resolve_mesh(mesh):
    return mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh


def partial_manual_ok() -> bool:
    """Whether this jax can run a shard_map that is manual over a SUBSET
    of mesh axes and contains collectives. On jax 0.4.x the SPMD
    partitioner hard-CHECKs (spmd_partitioner.cc:512
    `target.IsManualSubgroup() == sharding().IsManualSubgroup()`) when a
    ppermute/all_gather sits in a partially-manual region of a mesh with
    auto axes — a fatal process abort, not a catchable error, so this is
    version-gated rather than probed. When False, the pipeline engines
    run the shard_map FULLY manual over every mesh axis: all in/out
    specs only name the pp axis, so non-pp shards are replicated at the
    boundary and the numerics are identical (auto-axis GSPMD composition
    inside the body is what's lost, not correctness)."""
    import jax as _jax
    try:
        major, minor = _jax.__version_info__[:2]
    except Exception:  # pragma: no cover
        return True
    return (major, minor) >= (0, 5)


def probe_residuals(stage_fn: Callable, chunk_avals, x_aval) -> Dict[str, Any]:
    """Abstractly trace one chunk's jax.vjp and report its residual
    layout: {"treedef", "param_pos" (per-leaf index into the chunk's
    param leaves, -1 = activation-derived), "buf_avals" (avals of the
    leaves that must ride buffers in store-activations mode)}.

    Single source of truth for both the store-mode engine and the
    memory-budget auto-pick — the two must agree on what gets buffered.
    Residual leaves that ARE param leaves (same tracer in this trace —
    jaxpr construction is deterministic, so positions are stable) are
    re-picked from live params at the backward tick instead of being
    buffered.
    """
    import jax

    out: Dict[str, Any] = {}

    def _probe(pj, x):
        res, vjp = jax.vjp(stage_fn, pj, x)
        leaves, td = jax.tree_util.tree_flatten(vjp)
        pleaves = jax.tree_util.tree_leaves(pj)
        pmap = {id(pl): k for k, pl in enumerate(pleaves)}
        out["treedef"] = td
        out["param_pos"] = [pmap.get(id(l), -1) for l in leaves]
        out["buf_avals"] = [
            jax.ShapeDtypeStruct(l.shape, l.dtype)
            for l, pos in zip(leaves, out["param_pos"]) if pos < 0]
        return res

    jax.eval_shape(_probe, chunk_avals, x_aval)
    return out


def pipeline_forward_backward(stage_fn: Callable, loss_fn: Callable,
                              stacked_params, loss_params,
                              x_microbatches, y_microbatches,
                              mesh, sched: PipelineSchedule,
                              axis: str = "pp", remat: bool = True):
    """Run one pipelined train micro-step: forward + backward fused.

    stage_fn(chunk_params, x) -> y      one chunk's computation; uniform
                                        activation shape across chunks.
    loss_fn(loss_params, y, target) -> scalar mean loss per microbatch.
    stacked_params: pytree, leaves [vpp, n_stages, ...] (dim 1 sharded
        over `axis`; dim 0 is the chunk round).
    x_microbatches / y_microbatches: [n_micro, ...].

    remat=True (the 1F1B memory story): backward re-runs the chunk
    forward from its saved input — O(act_buf_size) inputs held, +1 fwd
    of compute per tick. remat=False (store-activations, the reference
    default — pipeline_parallel.py:440 stores, it doesn't remat): the
    forward slot runs jax.vjp and its residuals ride buffers to the
    backward tick; param-only residual leaves are substituted from the
    live params at backward instead of being buffered, so params are
    never duplicated per slot.

    Returns (loss, grads_stacked, grads_loss_params, dxs) where loss is
    the mean over microbatches, grads are summed cotangents (d mean-loss),
    and dxs [n_micro, ...] is the gradient w.r.t. x_microbatches (for an
    embedding stage living outside the pipeline).
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    jmesh = _resolve_mesh(mesh)
    p, v, m = sched.n_stages, sched.vpp, sched.n_micro
    is_zb = sched.mode == "zb"
    if is_zb and remat:
        raise ValueError(
            "zero-bubble schedules require store-activations mode "
            "(remat=False): the B and W slots share stored vjp "
            "residuals; remat would re-run each chunk forward twice")
    if jmesh.shape[axis] != p:
        raise ValueError(f"mesh axis {axis!r} has size {jmesh.shape[axis]}, "
                         f"schedule built for {p} stages")
    if x_microbatches.shape[0] != m:
        raise ValueError(f"got {x_microbatches.shape[0]} microbatches, "
                         f"schedule built for {m}")
    for leaf in jax.tree_util.tree_leaves(stacked_params):
        if leaf.shape[:2] != (v, p):
            raise ValueError(
                f"stacked_params leaves must be [vpp={v}, n_stages={p}, "
                f"...]; got {leaf.shape}")

    tables = {k: jnp.asarray(a) for k, a in sched.tables.items()}
    inv_m = 1.0 / float(m)

    param_specs = jax.tree_util.tree_map(lambda _: P(None, axis),
                                         stacked_params)
    ring_fwd = [(i, (i + 1) % p) for i in range(p)]
    ring_bwd = [(i, (i - 1) % p) for i in range(p)]

    def body(params, lparams, xs, ys, stage_ids):
        p_local = jax.tree_util.tree_map(lambda a: a[:, 0], params)
        # stage id arrives as a P(axis)-sharded arange instead of
        # jax.lax.axis_index: on jax<=0.4.x axis_index inside a
        # partially-manual shard_map lowers to a PartitionId HLO that
        # the SPMD partitioner rejects whenever the mesh has auto axes
        stage = stage_ids[0]

        chunk0 = jax.tree_util.tree_map(lambda a: a[0], p_local)
        a_shape = jax.eval_shape(stage_fn, chunk0, xs[0])
        if a_shape.shape != xs.shape[1:] or a_shape.dtype != xs.dtype:
            raise ValueError(
                f"pipeline chunks must preserve activation shape/dtype; "
                f"chunk maps {xs.shape[1:]}/{xs.dtype} -> "
                f"{a_shape.shape}/{a_shape.dtype}")
        act_z = jnp.zeros(a_shape.shape, a_shape.dtype)

        def pick_chunk(tree, j):
            return jax.tree_util.tree_map(
                lambda a: jax.lax.dynamic_index_in_dim(a, j, 0, False), tree)

        # --- store-activations support: the shared residual-layout
        # probe (see probe_residuals) tells which vjp residual leaves
        # ride buffers vs get re-picked from live params at backward.
        res_probe: Dict[str, Any] = {}
        if not remat:
            res_probe = probe_residuals(stage_fn, chunk0, act_z)

        def _store_res(res_buf, vjp, slot):
            leaves = jax.tree_util.tree_leaves(vjp)
            buffered = [l for l, pos in zip(leaves,
                                            res_probe["param_pos"])
                        if pos < 0]
            return tuple(
                rb.at[slot].set(lf.astype(rb.dtype))
                for rb, lf in zip(res_buf, buffered))

        def _load_vjp(res_buf, slot, pj):
            pleaves = jax.tree_util.tree_leaves(pj)
            it = iter(res_buf)
            leaves = [pleaves[pos] if pos >= 0 else next(it)[slot]
                      for pos in res_probe["param_pos"]]
            return jax.tree_util.tree_unflatten(res_probe["treedef"],
                                                leaves)

        def loss_and_seeds(out, y):
            (lv, (g_lp, g_out)) = jax.value_and_grad(
                lambda lp, o: loss_fn(lp, o, y), argnums=(0, 1))(lparams, out)
            return lv, g_out, g_lp

        zero_lp = jax.tree_util.tree_map(jnp.zeros_like, lparams)

        def tick(carry, row):
            (fwd_msg, bwd_msg, act_buf, grad_buf, res_buf, gacc, lp_acc,
             loss_sum, dxs) = carry
            r = {k: a[stage] for k, a in row.items()}

            # -- message arrivals (written before compute reads) --
            incoming = jax.lax.ppermute(fwd_msg, axis, ring_fwd)
            g_incoming = jax.lax.ppermute(bwd_msg, axis, ring_bwd)
            act_buf = act_buf.at[r["rx_slot"]].set(
                jnp.where(r["rx_valid"], incoming, act_buf[r["rx_slot"]]))
            grad_buf = grad_buf.at[r["grx_slot"]].set(
                jnp.where(r["grx_valid"], g_incoming,
                          grad_buf[r["grx_slot"]]))

            # -- forward slot (cond-skipped: a stage with no fwd work
            # this tick pays nothing — warmup/cooldown ticks no longer
            # burn a full masked chunk-forward) --
            x_in = jnp.where(r["fwd_is_first"], xs[r["fwd_mb"]],
                             act_buf[r["fwd_in_slot"]])
            pj_f = pick_chunk(p_local, r["fwd_chunk"])

            def fwd_do(x_in, res_buf):
                if remat:
                    return stage_fn(pj_f, x_in), res_buf
                out, vjp_f = jax.vjp(stage_fn, pj_f, x_in)
                return out, _store_res(res_buf, vjp_f, r["res_slot"])

            out, res_buf = jax.lax.cond(
                r["fwd_valid"], fwd_do,
                lambda x_in, res_buf: (act_z, res_buf), x_in, res_buf)
            lv, g_seed, g_lp = jax.lax.cond(
                r["fwd_is_last"],
                lambda o: loss_and_seeds(o, ys[r["fwd_mb"]]),
                lambda o: (jnp.zeros((), jnp.float32),
                           jnp.zeros_like(o), zero_lp),
                out)
            last_valid = jnp.logical_and(r["fwd_valid"], r["fwd_is_last"])
            grad_buf = grad_buf.at[r["fwd_seed_slot"]].set(
                jnp.where(last_valid, g_seed.astype(grad_buf.dtype),
                          grad_buf[r["fwd_seed_slot"]]))
            loss_sum = loss_sum + jnp.where(last_valid,
                                            lv.astype(jnp.float32), 0.0)
            lp_acc = jax.tree_util.tree_map(
                lambda a, g: a + jnp.where(last_valid, g, 0.0).astype(a.dtype),
                lp_acc, g_lp)

            # -- backward slot (cond-skipped like the forward) --
            pj = pick_chunk(p_local, r["bwd_chunk"])
            g_in = grad_buf[r["bwd_gslot"]]

            if is_zb:
                # zero-bubble: the backward slot computes ONLY the
                # input gradient (the critical-path B item); XLA DCEs
                # the unused dw matmuls out of the vjp call. The
                # weight gradient runs in the separate W slot below,
                # re-deriving the vjp from the same stored residuals.
                def bwdx_do(g_in, res_buf):
                    vjp = _load_vjp(res_buf, r["bwd_res_slot"], pj)
                    _, dx = vjp(g_in)
                    return dx

                dx = jax.lax.cond(
                    r["bwd_valid"], bwdx_do,
                    lambda g_in, res_buf: act_z, g_in, res_buf)
                pj_w = pick_chunk(p_local, r["w_chunk"])

                def w_do(res_buf, grad_buf):
                    vjp_w = _load_vjp(res_buf, r["w_res_slot"], pj_w)
                    dpw, _ = vjp_w(grad_buf[r["w_gslot"]])  # dx DCE'd
                    return dpw

                dp_w = jax.lax.cond(
                    r["w_valid"], w_do,
                    lambda res_buf, grad_buf: jax.tree_util.tree_map(
                        jnp.zeros_like, pj_w), res_buf, grad_buf)
                gacc = jax.tree_util.tree_map(
                    lambda acc, g: acc.at[r["w_chunk"]].add(
                        g.astype(acc.dtype)), gacc, dp_w)
            else:
                def bwd_do(g_in, act_buf, res_buf):
                    if remat:
                        # remat from the saved chunk input
                        xb = jnp.where(r["bwd_is_first"],
                                       xs[r["bwd_mb"]],
                                       act_buf[r["bwd_in_slot"]])
                        _, vjp = jax.vjp(stage_fn, pj, xb)
                    else:
                        # stored residuals (param leaves re-picked live)
                        vjp = _load_vjp(res_buf, r["bwd_res_slot"], pj)
                    return vjp(g_in)

                dp, dx = jax.lax.cond(
                    r["bwd_valid"], bwd_do,
                    lambda g_in, act_buf, res_buf: (
                        jax.tree_util.tree_map(jnp.zeros_like, pj),
                        act_z),
                    g_in, act_buf, res_buf)
                gacc = jax.tree_util.tree_map(
                    lambda acc, g: acc.at[r["bwd_chunk"]].add(
                        g.astype(acc.dtype)), gacc, dp)
            first_valid = jnp.logical_and(r["bwd_valid"], r["bwd_is_first"])
            dxs = dxs.at[r["bwd_mb"]].set(
                jnp.where(first_valid, dx.astype(dxs.dtype),
                          dxs[r["bwd_mb"]]))

            return (out, dx, act_buf, grad_buf, res_buf, gacc, lp_acc,
                    loss_sum, dxs), None

        res_buf0 = ()
        if not remat:
            res_buf0 = tuple(
                jnp.zeros((sched.res_buf_size,) + av.shape, av.dtype)
                for av in res_probe["buf_avals"])
        carry0 = (
            act_z, act_z,
            jnp.zeros((sched.act_buf_size,) + act_z.shape, act_z.dtype),
            jnp.zeros((sched.grad_buf_size,) + act_z.shape, act_z.dtype),
            res_buf0,
            jax.tree_util.tree_map(jnp.zeros_like, p_local),
            zero_lp,
            jnp.zeros((), jnp.float32),
            jnp.zeros((m,) + act_z.shape, act_z.dtype),
        )
        carry, _ = jax.lax.scan(tick, carry0, tables)
        (_, _, _, _, _, gacc, lp_acc, loss_sum, dxs) = carry

        # loss / loss-param grads / dxs live on one stage — broadcast.
        loss = jax.lax.psum(loss_sum, axis) * inv_m
        lp_grads = jax.tree_util.tree_map(
            lambda a: jax.lax.psum(a, axis) * inv_m, lp_acc)
        dxs = jax.lax.psum(dxs, axis) * inv_m
        # stacked grads stay stage-local: reinsert the sharded stage dim.
        gacc = jax.tree_util.tree_map(lambda a: (a * inv_m)[:, None], gacc)
        return loss, gacc, lp_grads, dxs

    # partial-manual (auto axes compose via GSPMD) where the toolchain
    # supports it; fully-manual otherwise — see partial_manual_ok
    manual_kw = {"axis_names": {axis}} if partial_manual_ok() else {}
    f = jax.shard_map(
        body, mesh=jmesh,
        in_specs=(param_specs, P(), P(), P(), P(axis)),
        out_specs=(P(), param_specs, P(), P()),
        check_vma=False, **manual_kw)
    return f(stacked_params, loss_params, x_microbatches, y_microbatches,
             jnp.arange(p, dtype=jnp.int32))


def make_pipeline_loss_fn(stage_fn: Callable, loss_fn: Callable, mesh,
                          sched: PipelineSchedule, axis: str = "pp",
                          remat: bool = True):
    """Wrap the fused engine as a scalar-loss function differentiable by
    outer jax.grad: f(stacked_params, loss_params, xs, ys) -> loss.

    The engine already computes the exact gradients in its single fused
    pass; the custom_vjp just replays them scaled by the cotangent. This
    lets an embedding (or any pre-pipeline stage) live outside the
    pipeline and receive d loss/d xs through normal autodiff.
    """
    import jax
    import jax.numpy as jnp

    @jax.custom_vjp
    def pipeline_loss(stacked_params, loss_params, xs, ys):
        loss, _, _, _ = pipeline_forward_backward(
            stage_fn, loss_fn, stacked_params, loss_params, xs, ys,
            mesh, sched, axis, remat=remat)
        return loss

    def fwd(stacked_params, loss_params, xs, ys):
        loss, gs, glp, dxs = pipeline_forward_backward(
            stage_fn, loss_fn, stacked_params, loss_params, xs, ys,
            mesh, sched, axis, remat=remat)
        return loss, (gs, glp, dxs, ys)

    def bwd(res, gbar):
        gs, glp, dxs, ys = res
        scale = lambda t: jax.tree_util.tree_map(
            lambda a: (a.astype(jnp.float32) * gbar).astype(a.dtype), t)
        y_ct = jax.tree_util.tree_map(
            lambda y: np.zeros(y.shape, jax.dtypes.float0)
            if not jnp.issubdtype(y.dtype, jnp.inexact)
            else jnp.zeros_like(y), ys)
        return scale(gs), scale(glp), scale(dxs), y_ct

    pipeline_loss.defvjp(fwd, bwd)
    return pipeline_loss
