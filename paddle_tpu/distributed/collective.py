"""Eager collective API (parity:
/root/reference/python/paddle/distributed/communication/ — all_reduce,
all_gather, all_to_all, broadcast, reduce_scatter, send/recv, Group).

TPU-native semantics: in the single-controller model there is no per-rank
process; a "rank" is a device on a 1-D group mesh. A collective operates on
a rank-stacked tensor (leading dim = group size, sharded across the group
axis) and runs the real XLA collective via shard_map — so tests exercise
the same psum/all_gather/ppermute lowering that GSPMD emits inside jitted
programs. In multi-process (multi-host) deployments the jitted path is the
supported one; this eager facade is for debugging and test parity
(SURVEY §5.8).
"""
from __future__ import annotations

from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..framework.core import Tensor

__all__ = ["ReduceOp", "Group", "new_group", "get_group", "all_reduce",
           "all_gather", "all_to_all", "broadcast", "reduce",
           "reduce_scatter", "scatter", "gather", "barrier", "send", "recv",
           "isend", "irecv", "wait", "destroy_process_group",
           "int8_all_reduce", "int8_all_reduce_body"]


class ReduceOp:
    SUM = "sum"
    MAX = "max"
    MIN = "min"
    PROD = "prod"
    AVG = "avg"


class Group:
    """A set of 'ranks' = devices on a 1-D mesh axis named 'rank'."""

    _next_id = 0

    def __init__(self, ranks: Optional[List[int]] = None):
        devices = jax.devices()
        if ranks is None:
            ranks = list(range(len(devices)))
        self.ranks = ranks
        self.nranks = len(ranks)
        self.devs = np.asarray([devices[r] for r in ranks])
        self.mesh = Mesh(self.devs, ("rank",))
        Group._next_id += 1
        self.id = Group._next_id

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank)

    def process_group(self):
        return self


_default_group: Optional[Group] = None


def _group(group) -> Group:
    global _default_group
    if group is not None:
        return group
    if _default_group is None:
        _default_group = Group()
    return _default_group


def new_group(ranks=None, backend=None, timeout=None) -> Group:
    return Group(ranks)


def get_group(gid=None) -> Group:
    return _group(None)


def destroy_process_group(group=None):
    global _default_group
    _default_group = None


def _stacked(x: Tensor, g: Group):
    """Interpret x as rank-stacked [nranks, ...]; shard dim 0 over ranks."""
    arr = x._value
    if arr.shape[0] != g.nranks:
        raise ValueError(
            f"eager collective expects rank-stacked input [nranks={g.nranks}"
            f", ...]; got shape {arr.shape}")
    return jax.device_put(arr, NamedSharding(g.mesh, P("rank")))


# jitted collective programs memoized per (body identity, group ranks,
# out spec): `shard_map(...)` returns a FRESH callable every call, so an
# unmemoized `jax.jit(f)` retraced every eager collective — each
# all_reduce paid a trace+lower. Keyed on the body's cache key (the
# builders below return stable keys), not the closure object.
_PROGRAM_CACHE = {}


def _run(g: Group, fn, arr, out_spec=P("rank"), cache_key=None):
    from .watchdog import get_default_watchdog, watch_section
    key = None
    jf = None
    if cache_key is not None:
        key = (cache_key, tuple(g.ranks), str(out_spec))
        jf = _PROGRAM_CACHE.get(key)
    if jf is None:
        f = shard_map(fn, mesh=g.mesh, in_specs=(P("rank"),),
                      out_specs=out_spec, check_vma=False)
        jf = jax.jit(f)
        if key is not None:
            _PROGRAM_CACHE[key] = jf
    if get_default_watchdog() is None:   # default: keep async dispatch
        return jf(arr)
    # watchdog active: block inside the watched section so a device-side
    # hang is attributed to THIS collective (CommTaskManager parity:
    # comm_task_manager.h:37) — jax dispatch alone returns immediately.
    with watch_section(getattr(fn, "__name__", "collective")):
        out = jf(arr)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
    return out


_REDUCERS = {
    ReduceOp.SUM: lambda x, ax: jax.lax.psum(x, ax),
    ReduceOp.MAX: lambda x, ax: jax.lax.pmax(x, ax),
    ReduceOp.MIN: lambda x, ax: jax.lax.pmin(x, ax),
    ReduceOp.AVG: lambda x, ax: jax.lax.pmean(x, ax),
    ReduceOp.PROD: lambda x, ax: jnp.exp(jax.lax.psum(jnp.log(x), ax)),
}


# -- per-collective shard bodies --------------------------------------------
# Module-level builders (not inline lambdas) so (a) the public APIs and
# the comm auditor (tools/flightcheck/comm_audit.py) trace the SAME
# production bodies, and (b) each body carries a stable cache key for
# the program memo above.

def all_reduce_body(op):
    def body(x):
        return _REDUCERS[op](x, "rank")
    return body


def all_gather_body():
    def body(x):
        return jax.lax.all_gather(x, "rank", axis=0, tiled=True)
    return body


def broadcast_body(src_local):
    def body(x):
        # select src rank's slice for everyone (pbroadcast via psum of
        # a mask)
        idx = jax.lax.axis_index("rank")
        contrib = jnp.where(idx == src_local, x, jnp.zeros_like(x))
        return jax.lax.psum(contrib, "rank")
    return body


def reduce_body(op, dst_local):
    def body(x):
        total = _REDUCERS[op](x, "rank")
        idx = jax.lax.axis_index("rank")
        return jnp.where(idx == dst_local, total, x)
    return body


def reduce_scatter_body(op=ReduceOp.SUM):
    if op != ReduceOp.SUM:
        # the XLA primitive is sum-only; the old code silently summed
        # for every op — fail loudly instead of returning wrong math
        raise NotImplementedError(
            f"reduce_scatter supports ReduceOp.SUM only (psum_scatter "
            f"is a sum); got {op!r}")

    def body(x):
        return jax.lax.psum_scatter(x, "rank", scatter_dimension=1,
                                    tiled=False)
    return body


def all_to_all_body():
    def body(x):
        return jax.lax.all_to_all(x, "rank", split_axis=1,
                                  concat_axis=0, tiled=False)
    return body


def barrier_body():
    def body(x):
        return jax.lax.psum(x, "rank")
    return body


def ppermute_body(perm):
    def body(x):
        return jax.lax.ppermute(x, "rank", perm)
    return body


def int8_all_reduce(x, axis_name: str, n_shards: int):
    """EQuARX-style quantized allreduce (PAPERS.md) for FULLY-MANUAL
    shard_map bodies — the decode-collective compression behind the
    serving engine's ``tp_comm="int8"`` flag.

    Both phases of the ring allreduce move int8 instead of fp32:
    1. per-(row, chunk) symmetric scales (absmax/127 over each row's
       chunk — EQuARX's block-wise granularity: one global scale lets
       a single outlier feature crush every other row's resolution and
       greedy argmaxes start flipping), quantize, REDUCE-SCATTER the
       int8 chunks + their scales (all_to_all of the n_shards-way
       split along the last dim), dequantize each received chunk with
       its SENDER's scales and accumulate locally in fp32;
    2. re-quantize the reduced chunk (fresh per-row scales) and
       ALL-GATHER the int8 chunks + scales back to every shard.
    Payload per phase drops ~4x vs fp32 (int8 + one f32 scale per row
    per chunk); the error is bounded by two absmax-symmetric roundings
    at row granularity. The last dim must divide by n_shards (the
    serving layout guarantees it for hidden and intermediate sizes —
    checked at decoder construction); anything else falls back to a
    plain fp32 psum rather than padding.

    Only REDUCTIONS are quantized: the serving logits collective is an
    all_gather of disjoint vocab shards and stays exact.
    """
    d = x.shape[-1]
    if n_shards <= 1:
        return x
    if d % n_shards:
        return jax.lax.psum(x, axis_name)
    lead = x.shape[:-1]
    rows = 1
    for s in lead:
        rows *= s
    dc = d // n_shards
    xf = x.astype(jnp.float32).reshape(rows, n_shards, dc)
    xf = xf.transpose(1, 0, 2)                       # [n, rows, dc]
    scale = jnp.abs(xf).max(axis=2) / 127.0          # [n, rows]
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / scale[:, :, None]),
                 -127, 127).astype(jnp.int8)
    # reduce-scatter in int8: shard j receives every shard's chunk j
    # (and the matching row scales — a scale must travel with the
    # chunk it quantized, so it rides the same all_to_all pattern)
    recv = jax.lax.all_to_all(q, axis_name, split_axis=0,
                              concat_axis=0, tiled=False)
    rscale = jax.lax.all_to_all(scale, axis_name, split_axis=0,
                                concat_axis=0, tiled=False)  # [n, rows]
    acc = jnp.einsum("nr,nrd->rd", rscale,
                     recv.astype(jnp.float32))       # local dequant-sum
    # all-gather phase, int8 again (fresh per-row scales)
    s2 = jnp.abs(acc).max(axis=1) / 127.0            # [rows]
    s2 = jnp.where(s2 == 0, 1.0, s2)
    q2 = jnp.clip(jnp.round(acc / s2[:, None]),
                  -127, 127).astype(jnp.int8)
    g = jax.lax.all_gather(q2, axis_name)            # [n, rows, dc]
    s2s = jax.lax.all_gather(s2, axis_name)          # [n, rows]
    out = g.astype(jnp.float32) * s2s[:, :, None]
    out = out.transpose(1, 0, 2).reshape(*lead, d)
    return out.astype(x.dtype)


def int8_all_reduce_body(n_shards: int):
    """Module-level body builder (comm-audit idiom, see above): the
    auditor traces the SAME collective composition the serving decoders
    embed per block under ``tp_comm="int8"``."""
    def body(x):
        return int8_all_reduce(x, "rank", n_shards)
    return body


class _Task:
    """Stream-ordered task handle parity (ProcessGroup::Task). JAX arrays
    are async by construction; wait() blocks."""

    def __init__(self, arrs):
        self._arrs = arrs if isinstance(arrs, (list, tuple)) else [arrs]

    def wait(self):
        for a in self._arrs:
            if hasattr(a, "block_until_ready"):
                a.block_until_ready()

    def synchronize(self):
        self.wait()

    def is_completed(self):
        return True


def wait(tensor, group=None, use_calc_stream=True):
    tensor._value.block_until_ready()


def all_reduce(tensor: Tensor, op=ReduceOp.SUM, group=None,
               sync_op=True) -> _Task:
    g = _group(group)
    arr = _stacked(tensor, g)
    out = _run(g, all_reduce_body(op), arr,
               cache_key=("all_reduce", op))
    tensor._replace(out)
    return _Task(out)


def all_gather(tensor_list: List, tensor: Tensor, group=None,
               sync_op=True) -> _Task:
    """tensor: rank-stacked [nranks, ...]; result: each rank sees all —
    tensor_list receives the nranks slices (identical on every rank)."""
    g = _group(group)
    arr = _stacked(tensor, g)
    # per-shard [1,...] → all_gather(tiled) [nranks,...], replicated output
    out = _run(g, all_gather_body(), arr, out_spec=P(),
               cache_key=("all_gather",))
    gathered = jax.device_get(out)
    tensor_list.clear()
    for i in range(g.nranks):
        tensor_list.append(Tensor(jnp.asarray(gathered[i])))
    return _Task(out)


def all_to_all(out_tensor_list: List, in_tensor_list, group=None,
               sync_op=True) -> _Task:
    g = _group(group)
    if isinstance(in_tensor_list, Tensor):
        arr = _stacked(in_tensor_list, g)
    else:
        stacked = jnp.stack([t._value for t in in_tensor_list])
        # [nranks_dst, ...] per rank; emulate with a [src, dst, ...] matrix
        arr = stacked
    if isinstance(in_tensor_list, (list, tuple)):
        # full emulation: every rank r holds in_tensor_list (same on all) —
        # in single-controller mode the caller provides the per-rank matrix
        # as [src=me][dst]; transpose
        raise NotImplementedError(
            "eager all_to_all takes a rank-stacked Tensor "
            "[nranks_src, nranks_dst, ...] in single-controller mode")
    # arr: [src, dst, ...] sharded on src → output [dst, src, ...]
    out = _run(g, all_to_all_body(), arr, cache_key=("all_to_all",))
    out_tensor_list.clear()
    out_tensor_list.append(Tensor(out))
    return _Task(out)


def broadcast(tensor: Tensor, src=0, group=None, sync_op=True) -> _Task:
    g = _group(group)
    arr = _stacked(tensor, g)
    src_local = g.get_group_rank(src) if src in g.ranks else src
    out = _run(g, broadcast_body(src_local), arr,
               cache_key=("broadcast", src_local))
    tensor._replace(out)
    return _Task(out)


def reduce(tensor: Tensor, dst=0, op=ReduceOp.SUM, group=None,
           sync_op=True) -> _Task:
    g = _group(group)
    arr = _stacked(tensor, g)
    dst_local = g.get_group_rank(dst) if dst in g.ranks else dst
    out = _run(g, reduce_body(op, dst_local), arr,
               cache_key=("reduce", op, dst_local))
    tensor._replace(out)
    return _Task(out)


def reduce_scatter(tensor: Tensor, tensor_list, op=ReduceOp.SUM, group=None,
                   sync_op=True) -> _Task:
    """in: rank-stacked [nranks, nranks*chunk, ...]; out per rank: its
    reduced chunk. Result written to `tensor` as [nranks, chunk, ...]."""
    g = _group(group)
    if isinstance(tensor_list, Tensor):
        arr = _stacked(tensor_list, g)
    else:
        arr = _stacked(tensor_list[0], g) if len(tensor_list) == 1 else \
            jnp.stack([t._value for t in tensor_list])

    out = _run(g, reduce_scatter_body(op), arr,
               cache_key=("reduce_scatter", op))
    tensor._replace(out)
    return _Task(out)


def scatter(tensor: Tensor, tensor_list=None, src=0, group=None,
            sync_op=True) -> _Task:
    g = _group(group)
    stacked = jnp.stack([t._value for t in tensor_list]) \
        if tensor_list else tensor._value
    # every rank gets slice r
    tensor._replace(jax.device_put(
        stacked, NamedSharding(g.mesh, P("rank"))))
    return _Task(tensor._value)


def gather(tensor: Tensor, gather_list=None, dst=0, group=None,
           sync_op=True) -> _Task:
    g = _group(group)
    arr = _stacked(tensor, g)
    gathered = jax.device_get(arr)
    if gather_list is not None:
        gather_list.clear()
        for i in range(g.nranks):
            gather_list.append(Tensor(jnp.asarray(gathered[i])))
    return _Task(arr)


def barrier(group=None):
    g = _group(group)
    x = jnp.zeros((g.nranks,), jnp.int32)
    arr = jax.device_put(x, NamedSharding(g.mesh, P("rank")))
    out = _run(g, barrier_body(), arr, cache_key=("barrier",))
    out.block_until_ready()


def send(tensor: Tensor, dst=0, group=None, sync_op=True):
    raise NotImplementedError(
        "eager p2p send/recv: use ppermute inside jitted programs "
        "(paddle_tpu.distributed.fleet pipeline), or the rank-stacked "
        "batch_isend_irecv debug facade — single-controller eager "
        "point-to-point has no peer to talk to")


def recv(tensor: Tensor, src=0, group=None, sync_op=True):
    raise NotImplementedError(
        "eager p2p send/recv: use ppermute inside jitted programs, or "
        "the rank-stacked batch_isend_irecv debug facade")


isend = send
irecv = recv


class P2POp:
    """One batched point-to-point op (reference
    python/paddle/distributed/communication/batch_isend_irecv.py P2POp).

    Debug-parity semantics in single-controller mode: `tensor` is the
    RANK-STACKED view [nranks, ...] (like every eager collective here) —
    row r is rank r's local buffer. op is distributed.isend or
    distributed.irecv; peer is the PER-RANK peer mapping, a list
    (peer[r] = rank r's peer) or a callable rank -> peer. A plain int
    (the reference's per-rank local form) cannot express a rank-stacked
    route for nranks > 1 and is rejected at execution."""

    def __init__(self, op, tensor: Tensor, peer, group=None):
        if op not in (isend, irecv, send, recv):
            raise ValueError("P2POp op must be distributed.isend/irecv")
        self.op = "send" if op in (isend, send) else "recv"
        self.tensor = tensor
        self.peer = peer
        self.group = group

    def _peer_of(self, rank: int, nranks: int) -> int:
        if callable(self.peer):
            return int(self.peer(rank)) % nranks
        if isinstance(self.peer, (list, tuple)):
            return int(self.peer[rank]) % nranks
        return int(self.peer) % nranks


def batch_isend_irecv(p2p_op_list) -> List[_Task]:
    """Execute a batch of matched sends/recvs (reference
    communication/batch_isend_irecv.py) as a REAL lax.ppermute under
    shard_map — the same lowering the compiled pipeline p2p uses, so
    this debug facade exercises the production collective.

    Sends pair with recvs in list order; the send's peer mapping is the
    route and must be a permutation of the ranks (a batch where two
    ranks send to the same destination has no single-step answer — the
    reference would deadlock there too). Each recv op's peer is checked
    against the route's inverse, so a mis-ordered batch fails loudly
    instead of writing into the wrong buffer."""
    g = _group(p2p_op_list[0].group if p2p_op_list else None)
    n = g.nranks
    for op_ in p2p_op_list:
        og = _group(op_.group)
        if og.ranks != g.ranks:
            raise ValueError(
                f"batch_isend_irecv ops span different groups "
                f"({og.ranks} vs {g.ranks}); one batch = one group")
    sends = [op for op in p2p_op_list if op.op == "send"]
    recvs = [op for op in p2p_op_list if op.op == "recv"]
    if len(sends) != len(recvs):
        raise ValueError(
            f"batch_isend_irecv needs matched send/recv counts, got "
            f"{len(sends)} sends / {len(recvs)} recvs")
    tasks = []
    for s_op, r_op in zip(sends, recvs):
        src_arr = s_op.tensor._value
        if src_arr.shape[0] != n:
            raise ValueError(
                f"P2POp tensors must be rank-stacked [{n}, ...]; got "
                f"{list(src_arr.shape)}")
        if tuple(r_op.tensor._value.shape) != tuple(src_arr.shape):
            raise ValueError(
                f"recv buffer shape {list(r_op.tensor._value.shape)} "
                f"!= send shape {list(src_arr.shape)}")
        for op_ in (s_op, r_op):
            if n > 1 and not (callable(op_.peer)
                              or isinstance(op_.peer, (list, tuple))):
                raise ValueError(
                    "P2POp peer must be a list or callable (per-rank "
                    "mapping) in the rank-stacked facade — a plain int "
                    f"({op_.peer!r}) is the same peer for every rank, "
                    "which is never a valid route for nranks > 1; use "
                    "peer=lambda r: ... or a list")
        dest = [s_op._peer_of(r, n) for r in range(n)]
        if sorted(dest) != list(range(n)):
            raise ValueError(
                f"send route {dest} is not a permutation of the ranks — "
                "two sends target the same destination; split the batch")
        inv = {d: s for s, d in enumerate(dest)}
        for d in range(n):
            declared = r_op._peer_of(d, n)
            if declared != inv[d]:
                raise ValueError(
                    f"recv op expects rank {d} to receive from "
                    f"{declared}, but the paired send routes "
                    f"{inv[d]} -> {d}; send/recv ops are paired in "
                    "list order — reorder the batch or fix the peers")
        perm = [(s, d) for s, d in enumerate(dest)]
        arr = _stacked(s_op.tensor, g)
        out = _run(g, ppermute_body(perm), arr,
                   cache_key=("ppermute", tuple(perm)))
        r_op.tensor._replace(out)
        tasks.append(_Task(out))
    return tasks
