"""paddle.distributed.spawn parity
(/root/reference/python/paddle/distributed/spawn.py:450): run ``func`` in
``nprocs`` freshly spawned processes with rank env injected, propagate the
first failure, join all.

On TPU the common case is nprocs=1 per host (single-controller JAX); the
multi-process form exists for CPU-backend tests and host-parallel
utilities — matching the reference's subprocess test strategy
(SURVEY.md §4).
"""
from __future__ import annotations

import multiprocessing as mp
import os
import traceback
from typing import Optional, Tuple


def _entry(func, rank: int, nprocs: int, args, q, env):
    os.environ.update(env)
    os.environ["PADDLE_TRAINER_ID"] = str(rank)
    os.environ["PADDLE_TRAINERS_NUM"] = str(nprocs)
    os.environ["PADDLE_LOCAL_RANK"] = str(rank)
    try:
        func(*args)
        q.put((rank, None))
    except BaseException:
        q.put((rank, traceback.format_exc()))
        raise SystemExit(1)


class SpawnContext:
    def __init__(self, procs, q):
        self.processes = procs
        self._q = q

    def join(self, timeout: Optional[float] = None) -> bool:
        """Wait for all; raise on the first reported failure. Also detects
        children that die without reporting (segfault/OOM-kill), which
        would otherwise hang q.get() forever."""
        import queue as _queue
        import time as _time
        deadline = None if timeout is None else _time.time() + timeout
        remaining = len(self.processes)
        reported_ranks: set = set()
        while remaining:
            try:
                rank, err = self._q.get(timeout=0.2)
            except _queue.Empty:
                # a dead nonzero-exit child that never reported = silent
                # death (reports carry the rank; processes[rank] is it)
                silent = [r for r, p in enumerate(self.processes)
                          if not p.is_alive() and p.exitcode not in (0, None)
                          and r not in reported_ranks]
                if silent:
                    # a just-written report may be in flight: one grace get
                    try:
                        rank, err = self._q.get(timeout=1.0)
                    except _queue.Empty:
                        for p in self.processes:
                            if p.is_alive():
                                p.terminate()
                        codes = [self.processes[r].exitcode for r in silent]
                        raise RuntimeError(
                            f"spawned rank(s) {silent} died without "
                            f"reporting (exit codes {codes}) — likely "
                            f"killed (OOM/segfault)")
                elif deadline is not None and _time.time() > deadline:
                    raise TimeoutError("spawn join timed out")
                else:
                    continue
            remaining -= 1
            reported_ranks.add(rank)
            if err is not None:
                for p in self.processes:
                    if p.is_alive():
                        p.terminate()
                raise RuntimeError(
                    f"spawned process rank {rank} failed:\n{err}")
        for p in self.processes:
            p.join()
        return True


def spawn(func, args: Tuple = (), nprocs: int = 1, join: bool = True,
          daemon: bool = False, **options):
    """Launch func(*args) in nprocs processes. options: env (dict of extra
    env vars), start_method ('spawn'|'fork'|'forkserver')."""
    env = dict(options.get("env") or {})
    method = options.get("start_method", "spawn")
    ctx = mp.get_context(method)
    q = ctx.Queue()
    procs = []
    for rank in range(nprocs):
        p = ctx.Process(target=_entry,
                        args=(func, rank, nprocs, args, q, env),
                        daemon=daemon)
        p.start()
        procs.append(p)
    sctx = SpawnContext(procs, q)
    if join:
        sctx.join()
        return None
    return sctx
