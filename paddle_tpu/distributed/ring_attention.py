"""Ring attention: blockwise causal attention with the sequence sharded
over a mesh axis, KV blocks rotated around the ring via ppermute.

Fills the reference's long-context gap (SURVEY.md §5.7: Paddle has only
Megatron-SP and an early segment-parallel mode — no ring attention). This
is the TPU-native design: the ring rides ICI neighbor links, compute on
the current KV block overlaps the DMA of the next one (XLA schedules the
ppermute async), and the online-softmax merge makes the math exact.

Used inside shard_map / jitted programs; also exposed as an eager Tensor
op through paddle_tpu.nn.functional.ring_attention.
"""
from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30

__all__ = ["ring_attention_local", "ring_attention"]


def _block_attend(q, k, v, scale, mask):
    """One (q_chunk × kv_chunk) blockwise attention partial.

    q [b, sq, h, d]; k/v [b, sk, h, d]; mask broadcastable [sq, sk] bool or
    None. Returns partial (acc [b,h,sq,d] f32, m [b,h,sq], l [b,h,sq])."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    acc = jnp.einsum("bhqk,bkhd->bhqd", p, v.astype(jnp.float32))
    return acc, m, l


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None):
    """Per-shard ring attention body (call inside shard_map).

    q/k/v: the LOCAL sequence chunk [b, s_local, h, d]; the global sequence
    is the concatenation over `axis_name` in axis-index order.
    Returns the local output chunk [b, s_local, h, d].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    kv_heads = k.shape[2]
    if kv_heads != h:
        k = jnp.repeat(k, h // kv_heads, axis=2)
        v = jnp.repeat(v, h // kv_heads, axis=2)

    perm = [(i, (i + 1) % n) for i in range(n)]
    causal_mask = (jnp.arange(s)[:, None] >= jnp.arange(s)[None, :]) \
        if causal else None

    def step(t, carry):
        acc, m, l, k_cur, v_cur = carry
        src = (my - t) % n  # which chunk of the global sequence we hold now

        if causal:
            # chunk relation selects ONE mask: src < my → all-visible;
            # src == my → causal inside; src > my → fully masked
            mask = jnp.where(src < my, jnp.ones_like(causal_mask),
                             jnp.where(src == my, causal_mask,
                                       jnp.zeros_like(causal_mask)))
            a_blk, m_blk, l_blk = _block_attend(q, k_cur, v_cur, scale, mask)
        else:
            a_blk, m_blk, l_blk = _block_attend(q, k_cur, v_cur, scale, None)

        m_new = jnp.maximum(m, m_blk)
        # guard both corrections against exp(-inf - -inf)
        c_old = jnp.exp(jnp.maximum(m - m_new, -1e30))
        c_blk = jnp.exp(jnp.maximum(m_blk - m_new, -1e30))
        acc = acc * c_old[..., None] + a_blk * c_blk[..., None]
        l = l * c_old + l_blk * c_blk
        m = m_new

        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return acc, m, l, k_nxt, v_nxt

    acc0 = jnp.zeros((b, h, s, d), jnp.float32)
    m0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, s), jnp.float32)
    acc, m, l, _, _ = jax.lax.fori_loop(0, n, step, (acc0, m0, l0, k, v))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return jnp.einsum("bhqd->bqhd", out).astype(q.dtype)


def ring_attention(q, k, v, mesh, axis: str = "sep", causal: bool = True,
                   scale: Optional[float] = None):
    """Whole-array entry: q/k/v [b, S_global, h, d] (sharded or not) →
    output with the sequence dim sharded over `axis`."""
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    spec = P(None, axis, None, None)
    f = shard_map(
        partial(ring_attention_local, axis_name=axis, causal=causal,
                scale=scale),
        mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    return f(q, k, v)
