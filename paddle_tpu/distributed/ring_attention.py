"""Ring attention: blockwise causal attention with the sequence sharded
over a mesh axis, KV blocks rotated around the ring via ppermute.

Fills the reference's long-context gap (SURVEY.md §5.7: Paddle has only
Megatron-SP and an early segment-parallel mode — no ring attention). This
is the TPU-native design: the ring rides ICI neighbor links, compute on
the current KV block overlaps the DMA of the next one (XLA schedules the
ppermute async), and the online-softmax merge makes the math exact.

The inner block is the Pallas flash kernel (ops/pallas/flash_attention):
each ring step computes (out_blk, lse_blk) with blocked online softmax —
no [s_q, s_kv] score materialization — and merges via
logaddexp(lse, lse_blk). The backward is a second ring pass: q/out/do/lse
stay resident while (k, v, dk, dv) circulate; each step runs the flash
backward kernels against the MERGED lse, so dk/dv accumulate exactly and
arrive home after n hops. GQA needs no head expansion on the Pallas path
(kv-head index mapping + grouped dk/dv accumulation live in the kernel).

A jnp blockwise fallback (still per-shard-block, f32) serves CPU tests
and shapes the kernel doesn't tile.

Used inside shard_map / jitted programs; also exposed as an eager Tensor
op through paddle_tpu.nn.functional.ring_attention.
"""
from __future__ import annotations

import functools
import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import shard_map
from jax.sharding import PartitionSpec as P

_NEG_INF = -1e30

__all__ = ["ring_attention_local", "ring_attention", "zigzag_indices",
           "inverse_zigzag_indices"]


# ---------------------------------------------------------------------------
# zigzag sequence placement (causal load balancing)
#
# Contiguous placement wastes ~half the causal compute: rank r holds
# chunk r, and every ring step where the visiting KV chunk is later than
# r is fully masked (ring_attention computed it then zeroed it — VERDICT
# r2 weak#2). Zigzag placement splits the sequence into 2n blocks and
# gives rank r the PAIR (block r, block 2n-1-r): at every ring step
# exactly half of the 2x2 (q-half x kv-half) block pairs are visible —
#   kv from an earlier rank: full q attends its early-kv half;
#   kv from a later rank:   the late q half attends both kv halves;
#   own kv (t=0):           both diagonals + late-q x early-kv.
# so causal work is balanced across ranks and no block is computed just
# to be masked. (Same trick as llama3-style zigzag / striped attention.)
# ---------------------------------------------------------------------------

def zigzag_indices(seq_len: int, n: int):
    """Global seq index order such that a contiguous n-way shard of the
    reordered sequence gives rank r the zigzag pair (block r, 2n-1-r)."""
    import numpy as np
    if seq_len % (2 * n):
        raise ValueError(f"zigzag needs seq_len ({seq_len}) divisible "
                         f"by 2*n ({2 * n})")
    blk = seq_len // (2 * n)
    order = []
    for r in range(n):
        order.extend(range(r * blk, (r + 1) * blk))
        order.extend(range((2 * n - 1 - r) * blk, (2 * n - r) * blk))
    return np.asarray(order, np.int32)


def inverse_zigzag_indices(seq_len: int, n: int):
    import numpy as np
    order = zigzag_indices(seq_len, n)
    inv = np.empty_like(order)
    inv[order] = np.arange(seq_len, dtype=np.int32)
    return inv


# ---------------------------------------------------------------------------
# per-block fwd/bwd implementations (pallas | jnp), shared signature:
#   blk_fwd(q, k, v, causal, scale)            -> out [b,s,h,d], lse [b,h,s]
#   blk_bwd(q, k, v, out, lse, do, causal, scale) -> dq, dk, dv  (f32)
# ---------------------------------------------------------------------------

def _jnp_blk_fwd(q, k, v, causal, scale):
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    if hk != h:
        k = jnp.repeat(k, h // hk, axis=2)
        v = jnp.repeat(v, h // hk, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    m = jnp.max(s, axis=-1)
    p = jnp.where(s > _NEG_INF * 0.5, jnp.exp(s - m[..., None]), 0.0)
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    out = out / jnp.maximum(l, 1e-30)[..., None].swapaxes(1, 2)
    lse = m + jnp.log(jnp.maximum(l, 1e-30))
    return out.astype(q.dtype), lse


def _jnp_blk_bwd(q, k, v, out, lse, do, causal, scale):
    b, sq, h, d = q.shape
    sk, hk = k.shape[1], k.shape[2]
    group = h // hk
    ke, ve = k, v
    if group > 1:
        ke = jnp.repeat(k, group, axis=2)
        ve = jnp.repeat(v, group, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, ke).astype(jnp.float32) * scale
    if causal:
        mask = jnp.arange(sq)[:, None] >= jnp.arange(sk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jnp.where(s > _NEG_INF * 0.5, jnp.exp(s - lse[..., None]), 0.0)
    do32 = do.astype(jnp.float32)
    delta = jnp.sum(out.astype(jnp.float32) * do32, axis=-1)  # [b,s,h]
    delta = delta.swapaxes(1, 2)                              # [b,h,s]
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, do32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", do32, ve.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, ke.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    if group > 1:
        dk = dk.reshape(b, sk, hk, group, d).sum(axis=3)
        dv = dv.reshape(b, sk, hk, group, d).sum(axis=3)
    return dq, dk, dv


def _interp_vma_fallback(q) -> bool:
    """Pallas interpret mode (the CPU test vehicle) cannot evaluate
    kernels whose operands carry varying-manual-axes tags (its internal
    dynamic_slices trip the vma checker); use the jnp oracle there.
    Real TPU lowering takes the tagged out_shape fine."""
    from ..ops.pallas.flash_attention import _interpret
    vma = getattr(getattr(q, "aval", None), "vma", None)
    return bool(vma) and _interpret()


def _pallas_blk_fwd(q, k, v, causal, scale):
    if _interp_vma_fallback(q):
        return _jnp_blk_fwd(q, k, v, causal, scale)
    from ..ops.pallas.flash_attention import flash_attention_with_lse
    from ..ops.flash_attention import pallas_attention_plan
    plan = pallas_attention_plan(q, k, min_seq=128) or (None, None)
    return flash_attention_with_lse(q, k, v, causal=causal, scale=scale,
                                    block_q=plan[0] or q.shape[1],
                                    block_k=plan[1] or k.shape[1])


def _pallas_blk_bwd(q, k, v, out, lse, do, causal, scale):
    if _interp_vma_fallback(q):
        return _jnp_blk_bwd(q, k, v, out, lse, do, causal, scale)
    from ..ops.pallas.flash_attention import flash_attention_bwd_block
    from ..ops.flash_attention import pallas_attention_plan
    plan = pallas_attention_plan(q, k, min_seq=128) or (None, None)
    return flash_attention_bwd_block(q, k, v, out, lse, do, causal=causal,
                                     scale=scale,
                                     block_q=plan[0] or q.shape[1],
                                     block_k=plan[1] or k.shape[1])


def _pallas_ok(q_shape, k_shape, halved=False):
    # shared gate with ops.flash_attention (ring shards are often shorter
    # than a full sequence, hence the lower min_seq). Shape-only on
    # purpose: the eligibility decision is Python-static under tracing,
    # so the gate takes shapes, not arrays — the decision provably
    # cannot depend on traced VALUES (and flightcheck's taint pass can
    # see that). halved=True gates the zigzag path, which feeds the
    # kernel half-blocks.
    import jax
    from ..ops.flash_attention import pallas_attention_plan
    qs, ks = list(q_shape), list(k_shape)
    if halved:
        qs[1] //= 2
        ks[1] //= 2
    return pallas_attention_plan(
        jax.ShapeDtypeStruct(tuple(qs), jnp.float32),
        jax.ShapeDtypeStruct(tuple(ks), jnp.float32),
        min_seq=128) is not None


# ---------------------------------------------------------------------------
# zigzag per-step block attention: local q = [early half | late half],
# visiting kv likewise. rel = sign(src - my): -1 earlier, 0 self, +1
# later. Every branch computes exactly the visible half of the work.
# ---------------------------------------------------------------------------

def _merge_pair(o1, l1, o2, l2):
    """Online-softmax merge of two partial results (f32)."""
    l = jnp.logaddexp(l1, l2)
    c1 = jnp.exp(l1 - l).swapaxes(1, 2)[..., None]
    c2 = jnp.exp(l2 - l).swapaxes(1, 2)[..., None]
    return o1.astype(jnp.float32) * c1 + o2.astype(jnp.float32) * c2, l


def _zz_step_fwd(blk_fwd, q, k_cur, v_cur, rel, scale):
    """One zigzag ring step forward → (out f32 [b,s,h,d], lse [b,h,s]);
    invisible q positions carry lse=-inf / out=0 (merge no-ops)."""
    b, s, h, d = q.shape
    half = s // 2
    q_e, q_l = q[:, :half], q[:, half:]
    k_e, k_l = k_cur[:, :half], k_cur[:, half:]
    v_e, v_l = v_cur[:, :half], v_cur[:, half:]
    z_o = jnp.zeros((b, half, h, d), jnp.float32)
    z_l = jnp.full((b, h, half), _NEG_INF, jnp.float32)

    def earlier(_):
        # full q attends the visiting EARLY kv half only
        o, l = blk_fwd(q, k_e, v_e, False, scale)
        return o.astype(jnp.float32), l

    def later(_):
        # only the late q half attends (both kv halves, fully visible)
        o, l = blk_fwd(q_l, k_cur, v_cur, False, scale)
        return (jnp.concatenate([z_o, o.astype(jnp.float32)], axis=1),
                jnp.concatenate([z_l, l], axis=2))

    def diag(_):
        o_e, l_e = blk_fwd(q_e, k_e, v_e, True, scale)
        o_l1, l_l1 = blk_fwd(q_l, k_e, v_e, False, scale)
        o_l2, l_l2 = blk_fwd(q_l, k_l, v_l, True, scale)
        o_l, l_l = _merge_pair(o_l1, l_l1, o_l2, l_l2)
        return (jnp.concatenate([o_e.astype(jnp.float32), o_l], axis=1),
                jnp.concatenate([l_e, l_l], axis=2))

    return jax.lax.switch(rel + 1, [earlier, diag, later], None)


def _zz_step_bwd(blk_bwd, q, k_cur, v_cur, out, lse, do, rel, scale):
    """One zigzag ring step backward → (dq, dk, dv) f32, full shapes.
    out/lse are the MERGED forward results (exactness of per-block
    backward against merged lse — same invariant as the plain ring)."""
    b, s, h, d = q.shape
    half = s // 2
    kvh = k_cur.shape[2]
    q_e, q_l = q[:, :half], q[:, half:]
    k_e, k_l = k_cur[:, :half], k_cur[:, half:]
    v_e, v_l = v_cur[:, :half], v_cur[:, half:]
    o_e, o_l = out[:, :half], out[:, half:]
    do_e, do_l = do[:, :half], do[:, half:]
    lse_e, lse_l = lse[:, :, :half], lse[:, :, half:]
    zq = jnp.zeros((b, half, h, d), jnp.float32)
    zkv = jnp.zeros((b, half, kvh, d), jnp.float32)

    def earlier(_):
        dq, dk_e, dv_e = blk_bwd(q, k_e, v_e, out, lse, do, False, scale)
        return (dq.astype(jnp.float32),
                jnp.concatenate([dk_e.astype(jnp.float32), zkv], axis=1),
                jnp.concatenate([dv_e.astype(jnp.float32), zkv], axis=1))

    def later(_):
        dq_l, dk, dv = blk_bwd(q_l, k_cur, v_cur, o_l, lse_l, do_l,
                               False, scale)
        return (jnp.concatenate([zq, dq_l.astype(jnp.float32)], axis=1),
                dk.astype(jnp.float32), dv.astype(jnp.float32))

    def diag(_):
        dq_e, dk1, dv1 = blk_bwd(q_e, k_e, v_e, o_e, lse_e, do_e, True,
                                 scale)
        dq_l1, dk2, dv2 = blk_bwd(q_l, k_e, v_e, o_l, lse_l, do_l,
                                  False, scale)
        dq_l2, dk3, dv3 = blk_bwd(q_l, k_l, v_l, o_l, lse_l, do_l, True,
                                  scale)
        dq = jnp.concatenate(
            [dq_e.astype(jnp.float32),
             dq_l1.astype(jnp.float32) + dq_l2.astype(jnp.float32)],
            axis=1)
        dk = jnp.concatenate(
            [dk1.astype(jnp.float32) + dk2.astype(jnp.float32),
             dk3.astype(jnp.float32)], axis=1)
        dv = jnp.concatenate(
            [dv1.astype(jnp.float32) + dv2.astype(jnp.float32),
             dv3.astype(jnp.float32)], axis=1)
        return dq, dk, dv

    return jax.lax.switch(rel + 1, [earlier, diag, later], None)


# ---------------------------------------------------------------------------
# the ring (custom_vjp: fwd merges lse online; bwd circulates dk/dv)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _ring_attention_core(q, k, v, axis_name, causal, scale, use_pallas,
                         zigzag):
    out, _ = _ring_fwd(q, k, v, axis_name, causal, scale, use_pallas,
                       zigzag)
    return out


def _ring_fwd(q, k, v, axis_name, causal, scale, use_pallas, zigzag):
    blk_fwd = _pallas_blk_fwd if use_pallas else _jnp_blk_fwd
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        out, lse, k_cur, v_cur = carry
        src = jnp.mod(my - t, n)    # global chunk id we hold this step
        if causal and zigzag:
            rel = jnp.sign(src - my).astype(jnp.int32)
            o_blk, lse_blk = _zz_step_fwd(blk_fwd, q, k_cur, v_cur, rel,
                                          scale)
        elif causal:
            o_blk, lse_blk = jax.lax.cond(
                t == 0,
                lambda a: blk_fwd(a[0], a[1], a[2], True, scale),
                lambda a: blk_fwd(a[0], a[1], a[2], False, scale),
                (q, k_cur, v_cur))
            visible = jnp.logical_or(t == 0, src < my)
            lse_blk = jnp.where(visible, lse_blk, _NEG_INF)
            o_blk = jnp.where(visible, o_blk, 0.0)
        else:
            o_blk, lse_blk = blk_fwd(q, k_cur, v_cur, False, scale)
        out, lse_new = _merge_pair(out, lse, o_blk, lse_blk)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (out, lse_new, k_nxt, v_nxt), None

    # pvary: zero-init carries are axis-invariant constants, but the scan
    # writes axis-varying values into them — required typing under the
    # (default) vma checker when shard_map is manual over a subset axis
    out0 = jax.lax.pvary(jnp.zeros((b, s, h, d), jnp.float32),
                         (axis_name,))
    lse0 = jax.lax.pvary(jnp.full((b, h, s), _NEG_INF, jnp.float32),
                         (axis_name,))
    (out, lse, _, _), _ = jax.lax.scan(
        step, (out0, lse0, k, v), jnp.arange(n))
    return out.astype(q.dtype), lse


def _ring_core_fwd(q, k, v, axis_name, causal, scale, use_pallas,
                   zigzag):
    out, lse = _ring_fwd(q, k, v, axis_name, causal, scale, use_pallas,
                         zigzag)
    return out, (q, k, v, out, lse)


def _ring_core_bwd(axis_name, causal, scale, use_pallas, zigzag, res,
                   do):
    q, k, v, out, lse = res
    blk_bwd = _pallas_blk_bwd if use_pallas else _jnp_blk_bwd
    n = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        dq, k_cur, v_cur, dk_cur, dv_cur = carry
        src = jnp.mod(my - t, n)
        if causal and zigzag:
            rel = jnp.sign(src - my).astype(jnp.int32)
            dq_blk, dk_blk, dv_blk = _zz_step_bwd(
                blk_bwd, q, k_cur, v_cur, out, lse, do, rel, scale)
        elif causal:
            dq_blk, dk_blk, dv_blk = jax.lax.cond(
                t == 0,
                lambda a: blk_bwd(a[0], a[1], a[2], a[3], a[4], a[5],
                                  True, scale),
                lambda a: blk_bwd(a[0], a[1], a[2], a[3], a[4], a[5],
                                  False, scale),
                (q, k_cur, v_cur, out, lse, do))
            vis = jnp.logical_or(t == 0, src < my).astype(jnp.float32)
            dq_blk = dq_blk * vis
            dk_blk = dk_blk * vis
            dv_blk = dv_blk * vis
        else:
            dq_blk, dk_blk, dv_blk = blk_bwd(q, k_cur, v_cur, out, lse,
                                             do, False, scale)
        dq = dq + dq_blk.astype(jnp.float32)
        dk_cur = dk_cur + dk_blk.astype(jnp.float32)
        dv_cur = dv_cur + dv_blk.astype(jnp.float32)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_cur, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_cur, axis_name, perm)
        return (dq, k_nxt, v_nxt, dk_nxt, dv_nxt), None

    dq0 = jax.lax.pvary(jnp.zeros(q.shape, jnp.float32), (axis_name,))
    dk0 = jax.lax.pvary(jnp.zeros(k.shape, jnp.float32), (axis_name,))
    dv0 = jax.lax.pvary(jnp.zeros(v.shape, jnp.float32), (axis_name,))
    (dq, _, _, dk, dv), _ = jax.lax.scan(
        step, (dq0, k, v, dk0, dv0), jnp.arange(n))
    # after n hops the dk/dv accumulators are back at their home shard
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring_attention_core.defvjp(_ring_core_fwd, _ring_core_bwd)


def ring_attention_local(q, k, v, axis_name: str, causal: bool = True,
                         scale: Optional[float] = None,
                         use_pallas: Optional[bool] = None,
                         zigzag: bool = False):
    """Per-shard ring attention body (call inside shard_map).

    q/k/v: the LOCAL sequence chunk [b, s_local, h, d]. With
    zigzag=False the global sequence is the concatenation over
    `axis_name` in axis-index order; with zigzag=True (causal only) each
    rank holds the block PAIR (r, 2n-1-r) of the 2n-block split — see
    zigzag_indices — which halves the causal compute by balancing
    visible work across the ring. kv heads may be fewer than q heads
    (GQA). Differentiable (custom ring backward). Returns the local
    output chunk [b, s_local, h, d].
    """
    if scale is None:
        scale = 1.0 / math.sqrt(q.shape[-1])
    if use_pallas is None:
        # zigzag computes on half-blocks — the kernel gate must pass for
        # the shapes actually fed to it
        use_pallas = _pallas_ok(q.shape, k.shape, halved=zigzag)
    if zigzag:
        if not causal:
            raise ValueError("zigzag placement only helps causal "
                             "attention; pass zigzag=False")
        if q.shape[1] % 2:
            raise ValueError("zigzag needs an even local sequence "
                             f"length, got {q.shape[1]}")
    return _ring_attention_core(q, k, v, axis_name, causal, scale,
                                bool(use_pallas), bool(zigzag))


def ring_attention(q, k, v, mesh, axis: str = "sep", causal: bool = True,
                   scale: Optional[float] = None,
                   use_pallas: Optional[bool] = None,
                   zigzag: Optional[bool] = None):
    """Whole-array entry: q/k/v [b, S_global, h, d] (sharded or not) →
    output with the sequence dim sharded over `axis`.

    zigzag (default: on for causal) load-balances causal work by
    computing in the zigzag sequence order internally — inputs/outputs
    keep the natural contiguous order; the permutation is applied and
    inverted inside."""
    jmesh = mesh.to_jax_mesh() if hasattr(mesh, "to_jax_mesh") else mesh
    n = jmesh.shape[axis]
    if zigzag is None:
        zigzag = bool(causal) and n > 1 and q.shape[1] % (2 * n) == 0
    spec = P(None, axis, None, None)
    # single-axis mesh: manual over everything, vma checker off (the
    # pre-CP behavior; pallas interpret mode dislikes vma tags).
    # multi-axis mesh: manual over `axis` ONLY so dp/mp compose as GSPMD
    # auto axes; the vma checker must stay ON there — jax 0.9
    # mis-validates out_specs when check_vma=False combines with a
    # subset axis_names (it demands the None entries "refer to" the
    # auto axes)
    from .fleet.pp_schedule import partial_manual_ok
    if set(jmesh.axis_names) == {axis} or not partial_manual_ok():
        # jax 0.4.x: partially-manual shard_map neither runs eagerly
        # (shard_map.py `if auto: raise NotImplementedError`) nor
        # lowers its collectives under jit (SPMD partitioner CHECK) —
        # run fully manual; the in/out specs only name `axis`, so other
        # mesh axes see replicated shards and numerics are unchanged
        sm_kwargs = dict(check_vma=False)
    else:
        sm_kwargs = dict(axis_names={axis})
    f = shard_map(
        partial(ring_attention_local, axis_name=axis, causal=causal,
                scale=scale, use_pallas=use_pallas, zigzag=zigzag),
        mesh=jmesh, in_specs=(spec, spec, spec), out_specs=spec,
        **sm_kwargs)
    if not zigzag:
        return f(q, k, v)
    # the permutation is a cross-shard all-to-all; re-pin the layouts so
    # the permuted operands and the final output keep the documented
    # seq-sharded placement instead of decaying to replicated
    ns = jax.sharding.NamedSharding(jmesh, spec)

    def pin(x):
        if isinstance(x, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(x, ns)
        return jax.device_put(x, ns)

    order = jnp.asarray(zigzag_indices(q.shape[1], n))
    inv = jnp.asarray(inverse_zigzag_indices(q.shape[1], n))
    out = f(pin(jnp.take(q, order, axis=1)),
            pin(jnp.take(k, order, axis=1)),
            pin(jnp.take(v, order, axis=1)))
    return pin(jnp.take(out, inv, axis=1))
