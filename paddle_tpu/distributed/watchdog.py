"""Comm/step watchdog: hang detection for training loops and collectives.

Reference parity: CommTask / CommTaskManager timeouts
(/root/reference/paddle/phi/core/distributed/comm_task_manager.h:37, with
the per-task timeout handling at :52) and the store-barrier timeout of
init_parallel_env — the first tool you reach for when a multi-host job
wedges.

TPU-native shape: collectives are in-program (GSPMD), so a hang shows up
as a device step (or an eager collective dispatch) that never completes.
The watchdog is a daemon thread watching two signals:
- step progress: TrainStep (or any loop calling ``notify_step``) bumps a
  heartbeat; no bump for ``timeout`` seconds => hang report.
- active sections: ``watch_section("all_reduce")`` wraps blocking calls
  (the eager collective facade uses it); a section still active past its
  deadline is reported with its name and age.

A hang report dumps every Python thread's stack, the device/mesh state,
and the last-completed step, to stderr and (optionally) a file; an
optional callback supports tests and custom telemetry. Enabled via flags:
FLAGS_enable_watchdog / FLAGS_watchdog_timeout_s, or explicitly.
"""
from __future__ import annotations

import io
import os
import sys
import threading
import time
import traceback
from typing import Callable, Dict, List, Optional

from ..utils.flags import define_flag, FLAGS

__all__ = ["StepWatchdog", "watch_section", "watch_engine",
           "get_default_watchdog", "enable_watchdog", "notify_step"]

define_flag("enable_watchdog", False,
            "start the step/comm watchdog on first TrainStep call")
define_flag("watchdog_timeout_s", 300.0,
            "seconds without step progress (or section completion) "
            "before a hang report")
define_flag("watchdog_dump_path", "",
            "optional file path to append hang reports to")


class StepWatchdog:
    """Daemon monitor thread. Thread-safe; one instance can watch the
    whole process."""

    def __init__(self, timeout: Optional[float] = None,
                 poll_interval: float = 1.0,
                 on_hang: Optional[Callable[[str], None]] = None,
                 dump_path: Optional[str] = None,
                 extra_dump: Optional[Callable[[io.StringIO],
                                              None]] = None):
        self.timeout = float(timeout if timeout is not None
                             else FLAGS.watchdog_timeout_s)
        self.poll_interval = poll_interval
        self.on_hang = on_hang
        self.dump_path = dump_path or (FLAGS.watchdog_dump_path or None)
        # optional domain-specific section of the hang report (e.g.
        # watch_engine appends the serving engine's scheduler state)
        self.extra_dump = extra_dump
        self._lock = threading.Lock()
        self._last_beat = time.monotonic()
        self._step = 0
        self._sections: Dict[int, tuple] = {}   # id -> (name, start, ddl)
        self._next_sid = 0
        self._reported = False
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle --
    def start(self):
        if self._thread is not None:
            return self
        self._stop = threading.Event()   # fresh event: stop() poisons it
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="paddle_tpu-watchdog")
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2 * self.poll_interval)
            self._thread = None

    # -- signals --
    def notify_step(self, step: Optional[int] = None):
        with self._lock:
            self._step = self._step + 1 if step is None else step
            self._last_beat = time.monotonic()
            self._reported = False

    def section(self, name: str, timeout: Optional[float] = None):
        return _Section(self, name, timeout or self.timeout)

    def _begin(self, name, timeout):
        with self._lock:
            sid = self._next_sid
            self._next_sid += 1
            now = time.monotonic()
            self._sections[sid] = (name, now, now + timeout)
        return sid

    def _end(self, sid):
        with self._lock:
            self._sections.pop(sid, None)
            self._last_beat = time.monotonic()
            self._reported = False

    # -- monitor --
    def _run(self):
        while not self._stop.wait(self.poll_interval):
            now = time.monotonic()
            with self._lock:
                expired = [(n, now - t0) for (n, t0, ddl)
                           in self._sections.values() if now > ddl]
                stalled = (now - self._last_beat) > self.timeout
                reported = self._reported
            if (expired or stalled) and not reported:
                self._report(expired, now)
                with self._lock:
                    self._reported = True

    def _report(self, expired: List[tuple], now: float):
        buf = io.StringIO()
        buf.write("\n========== paddle_tpu WATCHDOG: hang detected "
                  "==========\n")
        with self._lock:
            buf.write(f"last completed step: {self._step}; "
                      f"{now - self._last_beat:.1f}s since last "
                      f"progress (timeout {self.timeout:.1f}s)\n")
            active = list(self._sections.values())
        for name, age in expired:
            buf.write(f"  STUCK section: {name!r} running {age:.1f}s\n")
        for name, t0, _ in active:
            buf.write(f"  active section: {name!r} ({now - t0:.1f}s)\n")
        self._dump_env(buf)
        if self.extra_dump is not None:
            try:
                self.extra_dump(buf)
            except Exception as e:           # noqa: BLE001
                buf.write(f"(extra dump failed: {e})\n")
        buf.write("---- python thread stacks ----\n")
        frames = sys._current_frames()
        for tid, frame in frames.items():
            tname = next((t.name for t in threading.enumerate()
                          if t.ident == tid), str(tid))
            buf.write(f"-- thread {tname} --\n")
            buf.write("".join(traceback.format_stack(frame)))
        buf.write("====================================================\n")
        text = buf.getvalue()
        sys.stderr.write(text)
        sys.stderr.flush()
        if self.dump_path:
            try:
                with open(self.dump_path, "a") as f:
                    f.write(text)
            except OSError:
                pass
        if self.on_hang is not None:
            try:
                self.on_hang(text)
            except Exception:
                pass

    def _dump_env(self, buf):
        buf.write("---- device / mesh state ----\n")
        try:
            import jax
            buf.write(f"backend={jax.default_backend()} "
                      f"process={jax.process_index()}/"
                      f"{jax.process_count()} "
                      f"local_devices={len(jax.local_devices())}\n")
        except Exception as e:
            buf.write(f"(jax state unavailable: {e})\n")
        try:
            from .fleet import get_hybrid_communicate_group
            hcg = get_hybrid_communicate_group()
            if hcg is not None:
                buf.write(f"hybrid topology: {hcg.describe()}\n")
        except Exception:
            pass
        for k in ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
                  "MASTER_ADDR", "MASTER_PORT"):
            if k in os.environ:
                buf.write(f"{k}={os.environ[k]}\n")


class _Section:
    def __init__(self, wd: StepWatchdog, name: str, timeout: float):
        self._wd = wd
        self._name = name
        self._timeout = timeout
        self._sid = None

    def __enter__(self):
        self._sid = self._wd._begin(self._name, self._timeout)
        return self

    def __exit__(self, *exc):
        self._wd._end(self._sid)
        return False


_default: Optional[StepWatchdog] = None
_default_lock = threading.Lock()


def get_default_watchdog(create: bool = False) -> Optional[StepWatchdog]:
    global _default
    with _default_lock:
        if _default is None and create:
            _default = StepWatchdog().start()
        return _default


def enable_watchdog(timeout: Optional[float] = None, **kw) -> StepWatchdog:
    """Start (or return) the process-wide watchdog."""
    global _default
    with _default_lock:
        if _default is None:
            _default = StepWatchdog(timeout=timeout, **kw).start()
        return _default


def notify_step(step: Optional[int] = None):
    wd = get_default_watchdog()
    if wd is not None:
        wd.notify_step(step)


def watch_engine(engine, timeout: Optional[float] = None,
                 poll_interval: float = 1.0,
                 on_hang: Optional[Callable[[str], None]] = None,
                 dump_path: Optional[str] = None) -> StepWatchdog:
    """Wrap a ServingEngine's step() with the stall detector (ISSUE 4
    satellite): a dedicated StepWatchdog whose hang report includes the
    engine's scheduler snapshot — per-request states, queue/pipeline
    depth, robustness counters and KV-pool occupancy (debug_dump) —
    on top of the usual thread stacks and device state.

    Each step() runs inside a watched section (a single WEDGED step —
    e.g. a dispatch that never returns through a dead tunnel — is
    reported with its age even though the step never completed) and
    bumps the heartbeat on completion, so "engine alive but stuck" and
    "engine not being stepped" both trip after `timeout` seconds.

    Returns the started watchdog; call .stop() to detach monitoring
    (the step wrapper stays installed but becomes inert sections)."""

    def _dump(buf: io.StringIO):
        # debug_dump() opens with its own "serving engine state:" header
        buf.write(engine.debug_dump())
        # flight recorder (ISSUE 12): the hang report carries the tail
        # of the telemetry ring — what dispatched, retried, preempted
        # or faulted right before the wedge — and, when the report is
        # going to a file, the FULL Perfetto export lands next to it
        # so every hang ships its own post-mortem timeline
        tracer = getattr(engine, "tracer", None)
        if tracer is not None:
            buf.write(tracer.summary())
            if dump_path:
                try:
                    p = tracer.export(dump_path + ".trace.json")
                    buf.write(f"flight recorder exported: {p}\n")
                except Exception as e:     # noqa: BLE001 — the hang
                    # report must survive any export failure
                    buf.write(f"(flight recorder export failed: {e})\n")

    wd = StepWatchdog(timeout=timeout, poll_interval=poll_interval,
                      on_hang=on_hang, dump_path=dump_path,
                      extra_dump=_dump)
    inner = engine.step

    def step():
        with wd.section("ServingEngine.step"):
            out = inner()
        wd.notify_step()
        return out

    engine.step = step
    engine._step_watchdog = wd
    return wd.start()


def watch_section(name: str, timeout: Optional[float] = None):
    """Context manager marking a blocking call (eager collective, store
    barrier) the watchdog should report if it never completes. No-op when
    the watchdog isn't running."""
    wd = get_default_watchdog()
    if wd is None:
        class _Null:
            def __enter__(self):
                return self

            def __exit__(self, *a):
                return False
        return _Null()
    return wd.section(name, timeout)
