"""Static auto-parallel Engine (parity:
/root/reference/python/paddle/distributed/auto_parallel/static/engine.py
:61 Engine.fit / evaluate / predict, plus the Strategy config of
auto_parallel/strategy.py; user entry `fleet.auto.Engine`).

TPU-native realization: the reference pipeline — dist-attr completion
(static/completion.py:219), program Partitioner (static/partitioner.py
:41), Resharder comm insertion (static/reshard.py:1060) — collapses into
GSPMD: Engine.prepare() builds the device mesh from the strategy's
hybrid degrees, applies the parameter-placement recipes
(fleet.distributed_model), and compiles ONE sharded whole-step XLA
program (jit.TrainStep: fwd+bwd+optimizer with donation). fit/evaluate/
predict shard each incoming batch over the data axes and replay the
compiled program; XLA inserts every collective the placements imply.
"""
from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence

import numpy as np

__all__ = ["Engine", "Strategy"]


class Strategy:
    """Auto-parallel strategy (reference auto_parallel/strategy.py): the
    same knobs as fleet.DistributedStrategy, exposed under the names the
    auto API uses. `auto_mode` is accepted for API parity ('semi' only —
    full automatic search lives in distributed.auto_tuner)."""

    def __init__(self):
        from ..fleet.strategy import DistributedStrategy
        self._inner = DistributedStrategy()
        self.auto_mode = "semi"
        self.split_data = True

    def __getattr__(self, name):
        return getattr(object.__getattribute__(self, "_inner"), name)

    def __setattr__(self, name, value):
        if name in ("_inner", "auto_mode", "split_data"):
            object.__setattr__(self, name, value)
        else:
            setattr(self._inner, name, value)


class Engine:
    """High-level semi-automatic parallel driver.

    Usage (reference engine.py:61 example shape):
        engine = auto.Engine(model, loss, optimizer, metrics, strategy=s)
        engine.fit(train_dataset, epochs=2, batch_size=64)
        engine.evaluate(valid_dataset, batch_size=64)
        engine.predict(test_dataset, batch_size=64)
    """

    def __init__(self, model=None, loss=None, optimizer=None,
                 metrics=None, cluster=None, strategy: Optional[Strategy]
                 = None):
        self.model = model
        self.loss = loss
        self.optimizer = optimizer
        self.metrics = metrics if isinstance(metrics, (list, tuple)) \
            else ([metrics] if metrics is not None else [])
        self.strategy = strategy or Strategy()
        self._prepared_mode = None
        self._train_step = None
        self._fwd_fn = None
        self._hcg = None
        self.history: dict = {"loss": []}

    # -- preparation ---------------------------------------------------------
    def prepare(self, mode: str = "train"):
        """Build the mesh, apply placements, compile the step program."""
        if self._prepared_mode == mode:
            return self
        import paddle_tpu as paddle
        from .. import fleet as fleet_mod

        if fleet_mod.get_hybrid_communicate_group() is None:
            inner = getattr(self.strategy, "_inner", self.strategy)
            fleet_mod.init(is_collective=True, strategy=inner)
        self._hcg = fleet_mod.get_hybrid_communicate_group()
        # wrap exactly once: prepare() can run again for a different mode
        # (eval-first then fit) and re-wrapping a PipelineParallel would
        # double-wrap the model
        if not getattr(self, "_model_wrapped", False):
            self.model = fleet_mod.distributed_model(self.model)
            self._model_wrapped = True

        if mode == "train":
            if self.optimizer is None:
                raise ValueError("Engine.fit needs an optimizer")
            # one-time, like the model wrap: re-entering train after an
            # eval prepare must NOT re-wrap the optimizer (nested
            # shard_optimizer wrappers) or rebuild TrainStep (would drop
            # the compiled program and replay the RNG step stream)
            if self._train_step is None:
                self.optimizer = fleet_mod.distributed_optimizer(
                    self.optimizer)
                loss = self.loss

                def loss_fn(out, *labels):
                    if loss is None:
                        return out
                    if hasattr(loss, "forward") or callable(loss):
                        return loss(out, *labels)
                    raise TypeError(f"unsupported loss {loss!r}")

                inner = getattr(self.strategy, "_inner", self.strategy)
                gm_k, gm_avg = (inner.gradient_merge_k()
                                if hasattr(inner, "gradient_merge_k")
                                else (1, True))
                self._train_step = paddle.jit.TrainStep(
                    self.model, loss_fn, self.optimizer,
                    gradient_merge=gm_k, gradient_merge_avg=gm_avg)
        else:
            if self._fwd_fn is None:
                self._fwd_fn = paddle.jit.to_static(self.model)
        self._prepared_mode = mode
        return self

    def _forward(self, ins):
        """Compiled forward for eval/predict (to_static), built lazily so
        a train-prepared Engine can still evaluate."""
        import paddle_tpu as paddle
        if self._fwd_fn is None:
            self._fwd_fn = paddle.jit.to_static(self.model)
        return self._fwd_fn(*ins)

    # -- data handling -------------------------------------------------------
    def _loader(self, data, batch_size, shuffle=False, drop_last=False):
        from ...io import DataLoader
        if data is None:
            return None
        if isinstance(data, DataLoader):
            return data
        if hasattr(data, "__getitem__") or hasattr(data, "__iter__"):
            # train drops the tail partial batch (stable compiled shapes);
            # evaluate/predict must see every sample
            return DataLoader(data, batch_size=batch_size or 1,
                              shuffle=shuffle, drop_last=drop_last)
        raise TypeError(f"unsupported data {type(data)}")

    def _shard_batch(self, t):
        """Shard the batch dim over the data axes of the hybrid mesh.
        Honors strategy.split_data; a tail batch whose size doesn't
        divide the data degree stays replicated (correct, just not
        split) rather than crashing device_put."""
        import paddle_tpu.distributed as dist
        if self._hcg is None or \
                not getattr(self.strategy, "split_data", True):
            return t
        mesh = self._hcg.mesh
        placements = [dist.Shard(0) if name in ("dp", "sharding")
                      else dist.Replicate() for name in mesh.dim_names]
        degree = 1
        for name, size in zip(mesh.dim_names, mesh.shape):
            if name in ("dp", "sharding"):
                degree *= size
        if degree <= 1 or t.shape[0] % degree != 0:
            return t
        return dist.shard_tensor(t, mesh, placements)

    def _split(self, batch, has_labels=True):
        """(inputs, labels) from a dataloader item, sharded. Predict
        passes has_labels=False: the whole item is inputs."""
        import paddle_tpu as paddle
        from ...framework.core import Tensor

        def prep(x):
            t = x if isinstance(x, Tensor) else paddle.to_tensor(x)
            return self._shard_batch(t)

        if not has_labels:
            ins, labs = batch, None
        elif isinstance(batch, (list, tuple)):
            if len(batch) == 1:      # single-field items: inputs only
                ins, labs = batch[0], None
            elif len(batch) == 2:
                ins, labs = batch[0], batch[1]
            else:
                ins, labs = batch[:-1], batch[-1]
        else:
            ins, labs = batch, None
        ins = tuple(prep(x) for x in (
            ins if isinstance(ins, (list, tuple)) else (ins,)))
        if labs is None:
            return ins, ()
        labs = tuple(prep(x) for x in (
            labs if isinstance(labs, (list, tuple)) else (labs,)))
        return ins, labs

    # -- public API ----------------------------------------------------------
    def fit(self, train_data, epochs: int = 1, batch_size: Optional[int]
            = None, steps_per_epoch: Optional[int] = None,
            valid_data=None, log_freq: int = 10, verbose: int = 1):
        self.prepare("train")
        loader = self._loader(train_data, batch_size, shuffle=True,
                              drop_last=True)
        for epoch in range(epochs):
            for step, batch in enumerate(loader):
                if steps_per_epoch is not None and step >= steps_per_epoch:
                    break
                ins, labs = self._split(batch)
                loss = self._train_step(ins, labs)
                val = float(loss)
                self.history["loss"].append(val)
                if verbose and step % log_freq == 0:
                    print(f"[auto.Engine] epoch {epoch} step {step} "
                          f"loss {val:.5f}")
            if valid_data is not None:
                self.evaluate(valid_data, batch_size=batch_size,
                              verbose=verbose)
        return self.history

    def evaluate(self, valid_data, batch_size: Optional[int] = None,
                 steps: Optional[int] = None, verbose: int = 1):
        self.prepare("train" if self._train_step is not None else "eval")
        import paddle_tpu as paddle
        self.model.eval()
        loader = self._loader(valid_data, batch_size)
        for m in self.metrics:
            m.reset()
        losses = []
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            ins, labs = self._split(batch)
            out = self._forward(ins)
            if self.loss is not None and labs:
                losses.append(float(self.loss(out, *labs)))
            if labs:
                for m in self.metrics:
                    m.update(m.compute(out, *labs))
        self.model.train()
        result = {"eval_loss": float(np.mean(losses)) if losses else None}
        for m in self.metrics:
            result[f"eval_{m.name()}"] = m.accumulate()
        if verbose:
            print(f"[auto.Engine] evaluate: {result}")
        return result

    def predict(self, test_data, batch_size: Optional[int] = None,
                steps: Optional[int] = None, has_labels: bool = True):
        """has_labels=True (default) treats dataloader items like
        evaluate does — (inputs..., labels) with labels dropped. Pass
        has_labels=False when items are PURE inputs (e.g. a multi-input
        model with unlabeled data), so no input is mistaken for a
        label."""
        self.prepare("train" if self._train_step is not None else "eval")
        self.model.eval()
        loader = self._loader(test_data, batch_size)
        outs = []
        for i, batch in enumerate(loader):
            if steps is not None and i >= steps:
                break
            ins, _ = self._split(batch, has_labels=has_labels)
            out = self._forward(ins)
            if isinstance(out, (tuple, list)):   # keep ALL outputs
                outs.append(tuple(np.asarray(o.numpy()) for o in out))
            else:
                outs.append(np.asarray(out.numpy()))
        self.model.train()
        return outs

    # reference-API surface: saved artifacts
    def save(self, path: str, training: bool = True):
        import paddle_tpu as paddle
        state = {"model": self.model.state_dict()}
        if training and self.optimizer is not None:
            state["optimizer"] = self.optimizer.state_dict()
        paddle.save(state, path + ".pdparams")

    def load(self, path: str):
        import paddle_tpu as paddle
        state = paddle.load(path + ".pdparams")
        self.model.set_state_dict(state["model"])
        if "optimizer" in state and self.optimizer is not None:
            self.optimizer.set_state_dict(state["optimizer"])

    @property
    def main_program(self):
        """The 'partitioned program' analog: the compiled sharded step."""
        return self._train_step
