"""Multi-host async checkpoint over orbax.

Reference: the reference's distributed checkpoint writes per-rank shard
files + global metadata with dedup and cross-topology restore
(/root/reference/python/paddle/distributed/checkpoint/save_state_dict.py
:104, load_state_dict.py:65). On TPU pods the production-grade engine
for exactly that is orbax: every host writes only its address-able
shards, metadata is global, restore reshards to the destination
sharding, and async_save overlaps serialization with training.

This backend upgrades paddle_tpu.distributed.checkpoint when requested
(use_async=True or multi-process runtime); the np/json backend in
__init__.py remains the single-host default (zero deps, readable
files).
"""
from __future__ import annotations

import threading
from typing import Any, Dict, Optional

import jax
import numpy as np

from ...framework.core import Tensor

__all__ = ["save_state_dict_async", "load_state_dict_orbax",
           "wait_until_finished"]

_checkpointer = None
_lock = threading.Lock()


def _get_checkpointer():
    global _checkpointer
    with _lock:
        if _checkpointer is None:
            import orbax.checkpoint as ocp
            _checkpointer = ocp.AsyncCheckpointer(
                ocp.StandardCheckpointHandler())
        return _checkpointer


def _to_tree(state_dict: Dict[str, Any]):
    return {name: t._value for name, t in state_dict.items()
            if isinstance(t, Tensor)}


def save_state_dict_async(state_dict: Dict[str, Any], path: str,
                          **kwargs):
    """Non-blocking sharded save: each host writes its shards; training
    continues while serialization runs. Call wait_until_finished()
    before exiting (or before a dependent restore)."""
    import os
    ckptr = _get_checkpointer()
    ckptr.save(os.path.abspath(path), _to_tree(state_dict), force=True)


def wait_until_finished():
    if _checkpointer is not None:
        _checkpointer.wait_until_finished()


def load_state_dict_orbax(state_dict: Dict[str, Any], path: str,
                          **kwargs):
    """Restore in-place, resharding every array to the destination
    tensor's CURRENT sharding — topology-changing restore across
    different mesh shapes, per the reference's cross-topology ReadItem
    planning."""
    import os
    import orbax.checkpoint as ocp
    ckptr = _get_checkpointer()
    ckptr.wait_until_finished()
    # restore with target structure: shapes/dtypes/shardings from the
    # destination tensors so orbax reads each host's needed shards only
    targets = {}
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            continue
        v = t._value
        sharding = getattr(v, "sharding", None)
        targets[name] = jax.ShapeDtypeStruct(v.shape, v.dtype,
                                             sharding=sharding)
    import orbax.checkpoint.args as ocp_args
    restored = ckptr.restore(
        os.path.abspath(path),
        args=ocp_args.StandardRestore(targets))
    for name, t in state_dict.items():
        if isinstance(t, Tensor) and name in restored:
            t._replace(restored[name])
