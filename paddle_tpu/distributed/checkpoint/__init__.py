"""Distributed checkpoint: per-shard files + global metadata, dedup of
replicated shards, read-planned topology-changing restore, async save.

Parity:
/root/reference/python/paddle/distributed/checkpoint/save_state_dict.py
:104 (per-rank shard files + dedup :66-101) and load_state_dict.py:65-127
(rank->file read planning + cross-topology reshard).

TPU-native format:
- save walks ``arr.addressable_shards`` and writes one .npy PER SHARD
  (replica_id == 0 only — replicated shards are deduped); a full array is
  NEVER materialized on one host. File names are a pure function of the
  shard's index bounds, so every process writes independently and the
  coordinator can enumerate the global file set from the sharding alone.
- metadata.json records global shape/dtype and every shard's bounds.
- load plans reads per DESTINATION shard: only files intersecting the
  local shard's bounds are opened (np.load mmap — only the needed pages
  are read), assembled host-side, and the global array is built with
  jax.make_array_from_single_device_arrays under the destination
  sharding. Saving on a 2x4 mesh and restoring on 8x1 (or 1-device) just
  works; the reference's ReadItem planning collapses into bounds
  intersection.
- async_save snapshots shards to host (d2h per shard, no gather) and
  hands file IO to a background writer thread; wait_until_finished()
  blocks on the queue. Orbax remains available as an alternative backend
  (checkpoint.orbax_io) for multi-host storage stacks.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ...framework.core import Parameter, Tensor

__all__ = ["save_state_dict", "load_state_dict", "wait_until_finished"]

_META = "metadata.json"
_save_seq = 0


def _bounds(index: Tuple, shape: Sequence[int]) -> List[List[int]]:
    """Normalize a shard index (tuple of slices) to [[start, stop], ...]."""
    out = []
    for sl, n in zip(index, shape):
        start = 0 if sl.start is None else int(sl.start)
        stop = n if sl.stop is None else int(sl.stop)
        out.append([start, stop])
    # scalar arrays: index == ()
    return out


def _shard_fname(name: str, bounds: List[List[int]]) -> str:
    # '/' and '.' both normalize to '_', so distinct keys like 'a.b' and
    # 'a_b' would collide; a short hash of the RAW name disambiguates.
    safe = name.replace("/", "_").replace(".", "_")
    tag = hashlib.md5(name.encode()).hexdigest()[:8]
    if not bounds:
        return f"{safe}.{tag}.scalar.npy"
    span = "-".join(f"{a}_{b}" for a, b in bounds)
    return f"{safe}.{tag}.{span}.npy"


def _np_save(path: str, arr: np.ndarray):
    # bfloat16 (ml_dtypes) isn't np.save-serializable — store the raw bits
    if arr.dtype.name == "bfloat16":
        arr = arr.view(np.uint16)
    np.save(path, arr)


def _np_load(path: str, dtype_name: str, mmap: bool = True):
    arr = np.load(path, mmap_mode="r" if mmap else None)
    if dtype_name == "bfloat16":
        import ml_dtypes
        arr = arr.view(ml_dtypes.bfloat16)
    return arr


class _AsyncWriter:
    """Background file writer: save_state_dict(async_save=True) snapshots
    device shards to host, then returns while this thread writes files."""

    def __init__(self):
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        self._errors: List[BaseException] = []

    def submit(self, work):
        def run():
            try:
                work()
            except BaseException as e:  # surfaced on wait
                with self._lock:
                    self._errors.append(e)
        t = threading.Thread(target=run, daemon=True)
        with self._lock:
            self._threads.append(t)
        t.start()

    def wait(self):
        with self._lock:
            threads, self._threads = self._threads, []
        for t in threads:
            t.join()
        with self._lock:
            errors, self._errors = self._errors, []
        if errors:
            raise errors[0]


_writer = _AsyncWriter()


def wait_until_finished():
    """Block until all async checkpoint writes are durable. Errors from
    either backend's writer propagate — a failed write must never read as
    a durable checkpoint."""
    _writer.wait()
    try:  # orbax backend, only if importable (it may not be installed)
        from .orbax_io import wait_until_finished as _orbax_wait
    except ImportError:
        return
    _orbax_wait()


def _global_shard_table(arr) -> List[List[List[int]]]:
    """All unique shard bounds of the GLOBAL array (not just addressable),
    derived from the sharding — every process computes the same table."""
    shape = arr.shape
    try:
        imap = arr.sharding.devices_indices_map(shape)
        seen, table = set(), []
        for idx in imap.values():
            b = _bounds(idx, shape)
            key = tuple(map(tuple, b))
            if key not in seen:
                seen.add(key)
                table.append(b)
        return table
    except Exception:
        return [_bounds(tuple(slice(0, n) for n in shape), shape)]


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save: bool = False):
    """Write each tensor as per-shard .npy files + metadata.json.

    Never gathers a full array to one host: each process writes only its
    addressable replica-0 shards."""
    os.makedirs(path, exist_ok=True)
    meta = {"format": "paddle_tpu.sharded.v1", "tensors": {}}
    pending = []
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            continue
        arr = t._value
        dtype_name = str(arr.dtype)
        shards_meta = [{"file": _shard_fname(name, b), "bounds": b}
                       for b in _global_shard_table(arr)]
        placements = getattr(t, "placements", None)
        meta["tensors"][name] = {
            "shape": list(arr.shape),
            "dtype": dtype_name,
            "is_param": isinstance(t, Parameter),
            "placements": [repr(p) for p in placements] if placements
            else None,
            "shards": shards_meta,
        }
        # snapshot this process's replica-0 shards to host (no gather)
        for sh in arr.addressable_shards:
            if sh.replica_id != 0:
                continue  # dedup: exactly one replica writes each shard
            b = _bounds(sh.index, arr.shape)
            host = np.asarray(sh.data)
            pending.append((os.path.join(path, _shard_fname(name, b)),
                            host))

    # Commit protocol: every file is written to a temp name and renamed
    # into place; each process then drops a per-save sentinel, and the
    # coordinator renames metadata.json LAST, only after EVERY process's
    # sentinel exists (shared-filesystem barrier) — a crash mid-save
    # never leaves a valid-looking metadata pointing at missing or torn
    # shard files, on one host or many.
    write_meta = jax.process_index() == coordinator_rank
    global _save_seq
    _save_seq += 1
    if unique_id is not None:
        save_id = unique_id
    else:
        # launcher restarts relaunch every rank with a bumped generation,
        # so generation.seq never collides with a crashed run's sentinels
        gen = os.environ.get("PADDLE_RESTART_GENERATION", "0")
        save_id = f"{gen}.{_save_seq}"
    world = jax.process_count()
    my_sentinel = os.path.join(
        path, f".shards_done.{save_id}.{jax.process_index()}")
    # drop any stale sentinel for this (save_id, rank) BEFORE any writer
    # could re-create it — a crashed previous save must not satisfy the
    # coordinator's barrier
    try:
        os.remove(my_sentinel)
    except OSError:
        pass

    def write_files(items=tuple(pending), meta=meta, do_meta=write_meta):
        for fpath, host in items:
            tmp = fpath + ".tmp.npy"   # .npy suffix: np.save won't append
            _np_save(tmp, host)
            os.replace(tmp, fpath)
        with open(my_sentinel, "w") as f:
            f.write("ok")
        if do_meta:
            deadline = time.monotonic() + 600.0
            want = [os.path.join(path, f".shards_done.{save_id}.{r}")
                    for r in range(world)]
            while not all(os.path.exists(w) for w in want):
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"checkpoint save {save_id}: waited 600s for "
                        f"all {world} processes' shard sentinels in "
                        f"{path}")
                time.sleep(0.05)
            mpath = os.path.join(path, _META)
            with open(mpath + ".tmp", "w") as f:
                json.dump(meta, f, indent=1)
            os.replace(mpath + ".tmp", mpath)
            for w in want:
                try:
                    os.remove(w)
                except OSError:
                    pass

    if async_save:
        _writer.submit(write_files)
    else:
        write_files()


def _assemble(dst_bounds: List[List[int]], info: dict, path: str):
    """Read only the saved shards intersecting dst_bounds; returns the
    assembled host array for that destination shard."""
    if info["dtype"] == "bfloat16":
        import ml_dtypes
        out_dtype = ml_dtypes.bfloat16
    else:
        out_dtype = np.dtype(info["dtype"])
    out_shape = [b - a for a, b in dst_bounds]
    out = np.empty(out_shape, out_dtype)
    for sh in info["shards"]:
        src_b = sh["bounds"]
        inter = [[max(a1, a2), min(b1, b2)]
                 for (a1, b1), (a2, b2) in zip(dst_bounds, src_b)]
        if any(a >= b for a, b in inter):
            continue
        src = _np_load(os.path.join(path, sh["file"]), info["dtype"])
        src_sl = tuple(slice(a - sa, b - sa)
                       for (a, b), (sa, _) in zip(inter, src_b))
        dst_sl = tuple(slice(a - da, b - da)
                       for (a, b), (da, _) in zip(inter, dst_bounds))
        out[dst_sl] = src[src_sl]
    return out


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, offload: bool = False):
    """In-place load into the provided state_dict tensors, resharding each
    array to the destination tensor's CURRENT sharding — reading only the
    shard files the destination placement needs."""
    import jax.numpy as jnp
    if not os.path.exists(os.path.join(path, _META)):
        # orbax-format checkpoint (orbax backend save)
        from .orbax_io import load_state_dict_orbax
        return load_state_dict_orbax(state_dict, path)
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    legacy = meta.get("format") is None
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            continue
        info = meta["tensors"].get(name)
        if info is None:
            raise KeyError(f"checkpoint has no tensor named {name!r}")
        cur = t._value
        if legacy:  # round-1 format: one full .npy per tensor
            arr = np.load(os.path.join(path, info["file"]))
            new = jnp.asarray(arr)
            if info["dtype"] == "bfloat16":
                new = new.astype(jnp.bfloat16)
            if hasattr(cur, "sharding") and cur.sharding is not None:
                new = jax.device_put(new, cur.sharding)
            t._replace(new.astype(cur.dtype))
            continue
        shape = tuple(info["shape"])
        if shape != tuple(cur.shape):
            raise ValueError(
                f"shape mismatch for {name!r}: checkpoint {shape} vs "
                f"destination {tuple(cur.shape)}")
        sharding = getattr(cur, "sharding", None)
        if sharding is not None and not sharding.is_fully_replicated \
                and shape != ():
            # plan per destination shard; read only intersecting files.
            # Devices holding identical bounds (replicated mesh dims)
            # share one assembled host array — no redundant reads.
            dst_map = sharding.addressable_devices_indices_map(shape)
            cache: Dict[tuple, np.ndarray] = {}
            bufs = []
            for dev, idx in dst_map.items():
                db = _bounds(idx, shape)
                key = tuple(map(tuple, db))
                host = cache.get(key)
                if host is None:
                    host = cache[key] = _assemble(db, info, path)
                bufs.append(jax.device_put(host, dev))
            new = jax.make_array_from_single_device_arrays(
                shape, sharding, bufs)
        else:
            full = _assemble([[0, n] for n in shape], info, path)
            new = jnp.asarray(full)
            if sharding is not None:
                new = jax.device_put(new, sharding)
        t._replace(new.astype(cur.dtype))
