"""Distributed checkpoint (parity:
/root/reference/python/paddle/distributed/checkpoint/ —
save_state_dict.py:104, load_state_dict.py:65).

TPU-native: sharded arrays save per-shard with a global metadata file;
load reshards to the *current* placements (topology-changing restore) by
constructing the global array then device_put to the new sharding — the
reference's ReadItem planning collapses into jax.device_put.

Single-host implementation now (np per-shard files + metadata json);
multi-host via orbax planned (paddle_tpu.distributed.checkpoint.orbax_io).
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict

import jax
import numpy as np

from ...framework.core import Parameter, Tensor

__all__ = ["save_state_dict", "load_state_dict"]

_META = "metadata.json"


def save_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, async_save=False):
    if async_save or jax.process_count() > 1:
        # multi-host / async → orbax backend (per-host shard writes,
        # overlapped serialization). A synchronous request must not
        # return before the checkpoint is committed.
        from .orbax_io import save_state_dict_async, wait_until_finished
        save_state_dict_async(state_dict, path)
        if not async_save:
            wait_until_finished()
        return
    os.makedirs(path, exist_ok=True)
    meta = {"tensors": {}}
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            continue
        arr = np.asarray(jax.device_get(t._value))
        fname = name.replace("/", "_") + ".npy"
        np.save(os.path.join(path, fname), arr)
        placements = getattr(t, "placements", None)
        meta["tensors"][name] = {
            "file": fname,
            "shape": list(arr.shape),
            "dtype": str(t._value.dtype),
            "is_param": isinstance(t, Parameter),
            "placements": [repr(p) for p in placements] if placements else None,
        }
    with open(os.path.join(path, _META), "w") as f:
        json.dump(meta, f, indent=1)


def load_state_dict(state_dict: Dict[str, Any], path: str,
                    process_group=None, coordinator_rank: int = 0,
                    unique_id=None, offload: bool = False):
    """In-place load into the provided state_dict tensors, resharding each
    array to the destination tensor's current sharding."""
    import jax.numpy as jnp
    if not os.path.exists(os.path.join(path, _META)):
        # orbax-format checkpoint (async/multi-host save)
        from .orbax_io import load_state_dict_orbax
        return load_state_dict_orbax(state_dict, path)
    with open(os.path.join(path, _META)) as f:
        meta = json.load(f)
    for name, t in state_dict.items():
        if not isinstance(t, Tensor):
            continue
        info = meta["tensors"].get(name)
        if info is None:
            raise KeyError(f"checkpoint has no tensor named {name!r}")
        arr = np.load(os.path.join(path, info["file"]))
        new = jnp.asarray(arr)
        if info["dtype"] == "bfloat16":
            new = new.astype(jnp.bfloat16)
        cur = t._value
        if hasattr(cur, "sharding") and cur.sharding is not None:
            # reshard to the destination topology (may differ from save-time)
            new = jax.device_put(new, cur.sharding)
        t._replace(new.astype(cur.dtype))
