"""Auto-parallel API (parity:
/root/reference/python/paddle/distributed/auto_parallel/api.py —
shard_tensor:124, reshard:302, shard_layer:401, shard_optimizer:730).

TPU-native: a "DistTensor" is just a jax.Array with a NamedSharding; the
reference's reshard engine (12 C++ reshard functions,
/root/reference/paddle/phi/core/distributed/auto_parallel/reshard/) is
``jax.device_put`` — XLA emits the collective (all-gather / all-to-all /
reduce-scatter / permute) implied by the placement transition.
"""
from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor
from .mesh import ProcessMesh
from .placement import Partial, Placement, Replicate, Shard

__all__ = ["shard_tensor", "reshard", "shard_layer", "shard_optimizer",
           "dtensor_from_fn", "unshard_dtensor", "placements_to_spec"]


def placements_to_spec(mesh: ProcessMesh, placements: Sequence[Placement]):
    """Map per-mesh-dim placements → PartitionSpec over tensor dims."""
    # placements[i] describes what happens along mesh dim i
    ndim_entries = {}
    for mesh_dim, p in enumerate(placements):
        if isinstance(p, Shard):
            ndim_entries.setdefault(p.dim, []).append(
                mesh.dim_names[mesh_dim])
    if not ndim_entries:
        return jax.sharding.PartitionSpec()
    max_dim = max(ndim_entries.keys())
    spec = []
    for d in range(max_dim + 1):
        names = ndim_entries.get(d)
        if names is None:
            spec.append(None)
        elif len(names) == 1:
            spec.append(names[0])
        else:
            spec.append(tuple(names))
    return jax.sharding.PartitionSpec(*spec)


def _named_sharding(mesh: ProcessMesh, placements):
    return jax.sharding.NamedSharding(
        mesh.to_jax_mesh(), placements_to_spec(mesh, placements))


def shard_tensor(data, mesh: ProcessMesh, placements: Sequence[Placement],
                 dtype=None, place=None, stop_gradient=None) -> Tensor:
    """Place a tensor onto the mesh with the given placements."""
    if isinstance(data, Tensor):
        t = data
    else:
        from ..framework.core import to_tensor
        t = to_tensor(data, dtype=dtype)
    sharding = _named_sharding(mesh, placements)
    arr = jax.device_put(t._value, sharding)
    if isinstance(t, Parameter):
        out = Parameter(arr, trainable=t.trainable, name=t.name)
    else:
        sg = t.stop_gradient if stop_gradient is None else stop_gradient
        out = Tensor(arr, stop_gradient=sg, name=t.name)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def reshard(dist_tensor: Tensor, mesh: ProcessMesh,
            placements: Sequence[Placement]) -> Tensor:
    """Placement transition — the whole reshard engine in one call.

    Partial → Replicate/Shard performs the pending reduction explicitly
    (psum/reduce-scatter), matching the reference's p_to_r/p_to_s
    functions."""
    src_placements = getattr(dist_tensor, "placements", None)
    arr = dist_tensor._value
    if src_placements is not None and any(
            isinstance(p, Partial) for p in src_placements):
        arr = _resolve_partial(arr, mesh, src_placements, placements)
    sharding = _named_sharding(mesh, placements)
    out_arr = jax.device_put(arr, sharding)
    out = Tensor(out_arr, stop_gradient=dist_tensor.stop_gradient,
                 name=dist_tensor.name)
    out.process_mesh = mesh
    out.placements = list(placements)
    return out


def _resolve_partial(arr, mesh, src_placements, dst_placements):
    """Sum partial shards across the partial mesh axes via shard_map psum."""
    from jax import shard_map
    jmesh = mesh.to_jax_mesh()
    partial_axes = [mesh.dim_names[i] for i, p in enumerate(src_placements)
                    if isinstance(p, Partial)]
    # the partial array is stored fully-addressable per shard; emulate by
    # treating the value as already summed if it has no partial metadata
    in_spec = placements_to_spec(mesh, [
        p if isinstance(p, Shard) else Replicate()
        for p in src_placements])
    f = shard_map(lambda x: jax.lax.psum(x, tuple(partial_axes)),
                  mesh=jmesh, in_specs=(in_spec,), out_specs=in_spec)
    return f(arr)


def shard_layer(layer, process_mesh: ProcessMesh,
                shard_fn: Optional[Callable] = None,
                input_fn: Optional[Callable] = None,
                output_fn: Optional[Callable] = None):
    """Shard all parameters of a layer (paddle shard_layer parity). The
    default shard_fn replicates parameters over the mesh."""

    def default_shard_fn(name, sublayer, mesh):
        for pname, param in list(sublayer._parameters.items()):
            if param is None:
                continue
            new_p = shard_tensor(param, mesh,
                                 [Replicate()] * mesh.ndim)
            sublayer._parameters[pname] = new_p
            object.__setattr__(sublayer, pname, new_p)

    fn = shard_fn or default_shard_fn
    for name, sub in layer.named_sublayers(include_self=True):
        fn(name, sub, process_mesh)
    if input_fn is not None:
        layer.register_forward_pre_hook(
            lambda l, inputs: input_fn(inputs, process_mesh))
    if output_fn is not None:
        layer.register_forward_post_hook(
            lambda l, inputs, outputs: output_fn(outputs, process_mesh))
    return layer


def shard_optimizer(optimizer, shard_fn=None):
    """Shard optimizer states like their parameters (ZeRO-ish behavior is a
    placement choice — see fleet.sharding for stage1/2/3 recipes)."""
    orig_init = optimizer.init_state

    def sharded_init(params):
        state = orig_init(params)

        def match(i, arr):
            p = optimizer._parameter_list[i]
            if getattr(p, "process_mesh", None) is None or \
                    arr.shape != tuple(p.shape):
                return arr
            if shard_fn is not None:
                return shard_fn(p, arr)
            # ZeRO-1/2: state placements may shard where the param is
            # replicated (set by fleet.sharding_recipes)
            placements = getattr(p, "_opt_state_placements", None) \
                or p.placements
            return jax.device_put(
                arr, _named_sharding(p.process_mesh, placements))

        for k, v in state.items():
            if isinstance(v, list):
                state[k] = [match(i, a) for i, a in enumerate(v)]
        return state

    optimizer.init_state = sharded_init
    return optimizer


def dtensor_from_fn(fn, mesh: ProcessMesh, placements, *args, **kwargs):
    t = fn(*args, **kwargs)
    return shard_tensor(t, mesh, placements)


def unshard_dtensor(dist_tensor: Tensor) -> Tensor:
    """Gather a DistTensor to a fully-replicated local tensor."""
    arr = dist_tensor._value
    if hasattr(arr, "sharding"):
        mesh = getattr(dist_tensor, "process_mesh", None)
        if mesh is not None:
            arr = jax.device_put(
                arr, _named_sharding(mesh, [Replicate()] * mesh.ndim))
    out = Tensor(arr, stop_gradient=dist_tensor.stop_gradient)
    return out
