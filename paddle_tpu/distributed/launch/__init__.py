"""paddle_tpu.distributed.launch — multi-host job launcher.

TPU-native analog of `python -m paddle.distributed.launch`
(/root/reference/python/paddle/distributed/launch/main.py:20): a Master
rendezvous (the native TCP KV store instead of etcd/HTTP-KV), a Pod of
Container processes per node (/root/reference/python/paddle/distributed/
launch/job/pod.py, container.py), env injection (PADDLE_TRAINER_ID etc.),
per-rank log files, and a watch loop with restart policy.

The TPU twist: JAX is single-controller-per-host — one process per host
drives all local chips, so nproc_per_node defaults to 1 (not
chips-per-host). In-program collectives need no process groups; the
launcher only bootstraps jax.distributed's coordinator and supervises.
"""
from .main import launch, main  # noqa: F401
from .context import Context  # noqa: F401
from .pod import Container, Pod  # noqa: F401
from .master import Master  # noqa: F401
