"""Master rendezvous over the native KV store.

The reference Master (/root/reference/python/paddle/distributed/launch/
controllers/master.py) is an etcd client or a built-in HTTP KV; here the
rank-0 node hosts the C++ TCP KV store (paddle_tpu/core/cc/kv_store.cc)
and every node (including rank 0) joins through a client. Rendezvous
protocol: each candidate registers its endpoint under a generation key,
ranks are assigned by registration order (or honored if fixed), and all
peers fetch the full endpoint list once the quorum is reached.
"""
from __future__ import annotations

import json
import os
import time
from typing import List, Optional, Tuple

import socket as _socket

from ...core.native import TCPStore, TCPStoreServer, available


def _is_local_host(host: str) -> bool:
    """True iff this machine owns `host`'s address. The reliable test is
    binding a socket to the resolved IP: binding a non-local address
    fails with EADDRNOTAVAIL, regardless of /etc/hosts aliasing or
    multi-NIC setups (hostname-comparison heuristics get both wrong)."""
    if host in ("127.0.0.1", "0.0.0.0", "localhost",
                _socket.gethostname()):
        return True
    try:
        ip = _socket.gethostbyname(host)
    except OSError:
        return False
    try:
        with _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM) as s:
            s.bind((ip, 0))  # ephemeral port: tests ownership only
        return True
    except OSError:
        return False


class Master:
    def __init__(self, endpoint: Optional[str], job_id: str = "default",
                 is_lead: bool = False, timeout: float = 300.0):
        """endpoint: "host:port" of the KV store; None → single-node local
        mode (no store at all). is_lead: host the store in-process."""
        self.endpoint = endpoint
        self.job_id = job_id
        self.timeout = timeout
        self._server: Optional[TCPStoreServer] = None
        self._store: Optional[TCPStore] = None
        if endpoint is None:
            return
        if not available():
            raise RuntimeError("native KV store unavailable; cannot "
                               "rendezvous a multi-node job")
        host, port = endpoint.rsplit(":", 1)
        if is_lead and _is_local_host(host):
            # with auto-assigned ranks every LOCAL candidate offers to
            # host; the first bind wins, the rest fall back to client-only.
            # A non-local candidate binding its own port would "win" a
            # store nobody connects to.
            try:
                self._server = TCPStoreServer(int(port))
            except RuntimeError:
                self._server = None
        self._store = TCPStore(host, int(port), timeout=timeout)

    @property
    def store(self) -> Optional[TCPStore]:
        return self._store

    def _k(self, *parts) -> str:
        return "/".join(("job", self.job_id) + parts)

    def sync_peers(self, my_endpoint: str, nnodes: int, rank: int = -1,
                   generation: int = 0) -> Tuple[int, List[str]]:
        """Register and wait for the quorum. Returns (my_rank, all
        endpoints ordered by rank). generation bumps on elastic restarts so
        stale registrations don't collide."""
        if self._store is None:
            return 0, [my_endpoint]
        g = str(generation)
        if rank < 0:
            rank = self._store.add(self._k(g, "seq"), 1) - 1
        if rank >= nnodes:
            raise RuntimeError(
                f"node joined as rank {rank} but the job is fixed at "
                f"nnodes={nnodes}; elastic worlds must re-rendezvous with "
                f"a larger quorum, not join an existing one")
        self._store.set(self._k(g, f"rank{rank}"), my_endpoint.encode())
        arrived = self._store.add(self._k(g, "arrived"), 1)
        if arrived == nnodes:
            eps = [self._store.get(self._k(g, f"rank{r}"),
                                   timeout=self.timeout).decode()
                   for r in range(nnodes)]
            self._store.set(self._k(g, "peers"), json.dumps(eps).encode())
        peers = json.loads(self._store.get(self._k(g, "peers"),
                                           timeout=self.timeout).decode())
        return rank, peers

    def heartbeat(self, rank: int, status: str = "running"):
        if self._store is None:
            return
        try:
            self._store.set(self._k(f"beat{rank}"),
                            json.dumps({"t": time.time(),
                                        "status": status}).encode())
        except RuntimeError:
            pass  # advisory: the leader may already be gone at job end

    def peer_status(self, nnodes: int) -> List[Optional[dict]]:
        if self._store is None:
            return [None] * nnodes
        out = []
        for r in range(nnodes):
            try:
                if self._store.check(self._k(f"beat{r}")):
                    out.append(json.loads(
                        self._store.get(self._k(f"beat{r}"), timeout=5)))
                else:
                    out.append(None)
            except Exception:
                out.append(None)
        return out

    def set_status(self, status: str, generation: int = 0):
        """Generation-scoped: each restart generation has its own status
        key, so 'failed' sticks until every peer has seen it and moved to
        the next generation (no clear-before-peers-poll race)."""
        if self._store is not None:
            try:
                self._store.set(self._k(f"status{generation}"),
                                status.encode())
            except RuntimeError:
                pass  # advisory at job end (leader may be gone)

    def get_status(self, generation: int = 0) -> str:
        if self._store is None:
            return ""
        key = self._k(f"status{generation}")
        try:
            if self._store.check(key):
                return self._store.get(key, timeout=5).decode()
        except Exception:
            pass
        return ""

    def checkout(self, nnodes: int, timeout: float = 20.0):
        """Called on exit: count this node out; the store-hosting leader
        lingers until all nodes checked out (or timeout) so peers' final
        status/heartbeat writes don't hit a dead server."""
        if self._store is None:
            return
        try:
            n = self._store.add(self._k("exited"), 1)
            if self._server is not None:
                deadline = time.time() + timeout
                while n < nnodes and time.time() < deadline:
                    time.sleep(0.1)
                    n = self._store.add(self._k("exited"), 0)
        except RuntimeError:
            pass

    def close(self):
        if self._store is not None:
            self._store.close()
            self._store = None
        if self._server is not None:
            self._server.stop()
            self._server = None
