"""Launch context: argument + environment parsing (the reference's
launch/context/__init__.py Context analog, argument set from
/root/reference/python/paddle/distributed/launch/main.py docopt table)."""
from __future__ import annotations

import argparse
import os
import socket
from dataclasses import dataclass, field
from typing import List, Optional


def free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("", 0))
        return s.getsockname()[1]


@dataclass
class Context:
    master: Optional[str] = None          # host:port of the rendezvous KV
    nnodes: int = 1                       # number of hosts (or "N" / "N:M")
    max_nodes: int = 0                    # elastic upper bound (0 = fixed)
    nproc_per_node: int = 1               # controller processes per host
    rank: int = -1                        # fixed node rank (-1 = assigned)
    job_id: str = "default"
    log_dir: str = "log"
    log_level: str = "INFO"
    devices: Optional[str] = None         # visible accelerator ids
    training_script: str = ""
    training_script_args: List[str] = field(default_factory=list)
    max_restart: int = 3
    elastic_level: int = 0                # 0 off, 1 fault-tolerant, 2 elastic
    host: str = ""

    @classmethod
    def from_args(cls, argv: Optional[List[str]] = None) -> "Context":
        p = argparse.ArgumentParser(
            prog="python -m paddle_tpu.distributed.launch",
            description="paddle_tpu multi-host launcher")
        p.add_argument("--master", default=os.environ.get("PADDLE_MASTER"))
        p.add_argument("--nnodes", default=os.environ.get("PADDLE_NNODES",
                                                          "1"))
        p.add_argument("--nproc_per_node", type=int,
                       default=int(os.environ.get("PADDLE_NPROC_PER_NODE",
                                                  "1")))
        p.add_argument("--rank", type=int, default=-1)
        p.add_argument("--job_id", default=os.environ.get("PADDLE_JOB_ID",
                                                          "default"))
        p.add_argument("--log_dir", default="log")
        p.add_argument("--log_level", default="INFO")
        p.add_argument("--devices", "--gpus", "--xpus", dest="devices",
                       default=None)
        p.add_argument("--max_restart", type=int, default=3)
        p.add_argument("--elastic_level", type=int,
                       default=int(os.environ.get("PADDLE_ELASTIC_LEVEL",
                                                  "0")))
        p.add_argument("training_script")
        p.add_argument("training_script_args", nargs=argparse.REMAINDER)
        a = p.parse_args(argv)

        nnodes, max_nodes = cls._parse_nnodes(str(a.nnodes))
        return cls(master=a.master, nnodes=nnodes, max_nodes=max_nodes,
                   nproc_per_node=a.nproc_per_node, rank=a.rank,
                   job_id=a.job_id, log_dir=a.log_dir,
                   log_level=a.log_level, devices=a.devices,
                   training_script=a.training_script,
                   training_script_args=list(a.training_script_args),
                   max_restart=a.max_restart,
                   elastic_level=a.elastic_level,
                   host=socket.gethostname())

    @staticmethod
    def _parse_nnodes(s: str):
        """"2" → (2,2 fixed); "2:4" → elastic between 2 and 4."""
        if ":" in s:
            lo, hi = s.split(":")
            return int(lo), int(hi)
        return int(s), 0

    @property
    def is_elastic(self) -> bool:
        return self.max_nodes > self.nnodes or self.elastic_level > 0
