"""Launcher controller: rendezvous → env injection → pod supervision.

Collective-controller analog (/root/reference/python/paddle/distributed/
launch/controllers/collective.py:37 build_pod + controller.py watch loop):
on each node, sync peers through the Master KV, assign ranks, start the
training processes with PADDLE_* env injected, then watch; on failure
restart up to --max_restart times (rendezvous generation bumps so peers
re-sync). SIGTERM/SIGINT tear the pod down.
"""
from __future__ import annotations

import os
import signal
import sys
import time
from typing import List, Optional

from .context import Context, free_port
from .master import Master
from .pod import Container, Pod


def _build_pod(ctx: Context, node_rank: int, peers: List[str],
               master_ep: Optional[str], generation: int) -> Pod:
    pod = Pod()
    nnodes = len(peers)
    total = nnodes * ctx.nproc_per_node
    for local in range(ctx.nproc_per_node):
        rank = node_rank * ctx.nproc_per_node + local
        env = {
            "PADDLE_TRAINER_ID": str(rank),
            "PADDLE_TRAINERS_NUM": str(total),
            "PADDLE_LOCAL_RANK": str(local),
            "PADDLE_NNODES": str(nnodes),
            "PADDLE_NODE_RANK": str(node_rank),
            "PADDLE_JOB_ID": ctx.job_id,
            "PADDLE_RESTART_GENERATION": str(generation),
            "PADDLE_TRAINER_ENDPOINTS": ",".join(peers),
        }
        if master_ep:
            host, port = master_ep.rsplit(":", 1)
            # trainers rendezvous one port above the launcher KV
            env["PADDLE_MASTER"] = master_ep
            env["MASTER_ADDR"] = host
            env["MASTER_PORT"] = str(int(port) + 1)
        if ctx.devices:
            env["PADDLE_VISIBLE_DEVICES"] = ctx.devices
        log = os.path.join(ctx.log_dir,
                           f"workerlog.{node_rank}.{local}")
        pod.add(Container([sys.executable, "-u", ctx.training_script,
                           *ctx.training_script_args], env, log, rank))
    return pod


def launch(ctx: Context) -> int:
    """Run the job to completion; returns exit code."""
    single = ctx.nnodes <= 1 and ctx.master is None
    master = Master(None if single else ctx.master, ctx.job_id,
                    is_lead=(not single and ctx.rank in (-1, 0)))
    # NOTE on is_lead with auto-assigned ranks: every candidate tries to
    # bind the KV port; losers fall back to client-only (bind fails fast).
    generation = 0
    restarts = 0
    code = 0
    stop = {"flag": False}

    def _sig(signum, frame):
        stop["flag"] = True

    old_term = signal.signal(signal.SIGTERM, _sig)
    old_int = signal.signal(signal.SIGINT, _sig)
    try:
        while True:
            my_ep = f"{ctx.host}:{free_port()}"
            try:
                node_rank, peers = master.sync_peers(
                    my_ep, ctx.nnodes, ctx.rank, generation)
            except (TimeoutError, RuntimeError) as e:
                sys.stderr.write(
                    f"[launch] rendezvous failed at generation "
                    f"{generation}: {e}\n")
                return 1
            pod = _build_pod(ctx, node_rank, peers, ctx.master, generation)
            pod.start()
            master.heartbeat(node_rank, "running")
            while True:
                time.sleep(0.2)
                if stop["flag"]:
                    pod.terminate()
                    master.set_status("stopped", generation)
                    return 130
                if master.get_status(generation) == "failed":
                    pod.terminate()
                    break  # another node failed → re-rendezvous (restart)
                failed = pod.failed()
                if failed:
                    # must come before the finished() check: with
                    # nproc_per_node=1 a crashed trainer is also "finished"
                    for c in failed:
                        sys.stderr.write(
                            f"[launch] rank {c.rank} exited "
                            f"{c.exit_code}; last log:\n"
                            f"{c.tail_log()}\n")
                    pod.terminate()
                    # generation-scoped so peers reliably observe it (a
                    # shared key cleared right away would race their poll)
                    master.set_status("failed", generation)
                    break
                if pod.finished():
                    break
            master.heartbeat(node_rank, "done")
            if pod.finished() and pod.success():
                # don't clobber a peer's failure report for this
                # generation; a mixed done/failed world is a job failure
                if master.get_status(generation) == "failed":
                    sys.stderr.write(
                        "[launch] local pod succeeded but a peer failed; "
                        "exiting\n")
                    return 1
                master.set_status("done", generation)
                return 0
            # if peers already completed this generation, restarting alone
            # can never re-form the quorum — give up with a clear message
            if master.get_status(generation) == "done":
                sys.stderr.write(
                    "[launch] peers completed generation "
                    f"{generation} but this pod failed; not restarting\n")
                return 1
            restarts += 1
            if restarts > ctx.max_restart:
                sys.stderr.write(
                    f"[launch] giving up after {restarts - 1} restarts\n")
                return 1
            sys.stderr.write(
                f"[launch] restarting (attempt {restarts}/"
                f"{ctx.max_restart})\n")
            generation += 1
            pod.clear()
    finally:
        signal.signal(signal.SIGTERM, old_term)
        signal.signal(signal.SIGINT, old_int)
        master.checkout(ctx.nnodes)
        master.close()
    return code


def main(argv: Optional[List[str]] = None) -> int:
    ctx = Context.from_args(argv)
    return launch(ctx)
