"""Pod/Container process management (reference:
/root/reference/python/paddle/distributed/launch/job/pod.py, container.py —
a Pod is this node's set of trainer processes; each Container wraps one
subprocess with its env + log file)."""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional


class Container:
    def __init__(self, entrypoint: List[str], env: Dict[str, str],
                 log_path: str, rank: int):
        self.entrypoint = entrypoint
        self.env = env
        self.log_path = log_path
        self.rank = rank
        self.proc: Optional[subprocess.Popen] = None
        self._log_file = None

    def start(self):
        os.makedirs(os.path.dirname(self.log_path) or ".", exist_ok=True)
        self._log_file = open(self.log_path, "ab", buffering=0)
        full_env = dict(os.environ)
        full_env.update(self.env)
        self.proc = subprocess.Popen(
            self.entrypoint, env=full_env, stdout=self._log_file,
            stderr=subprocess.STDOUT, start_new_session=True)

    @property
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    @property
    def exit_code(self) -> Optional[int]:
        return None if self.proc is None else self.proc.poll()

    def terminate(self, grace: float = 10.0):
        if self.proc is None or self.proc.poll() is not None:
            self._close_log()
            return
        try:
            os.killpg(self.proc.pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        deadline = time.time() + grace
        while time.time() < deadline and self.proc.poll() is None:
            time.sleep(0.1)
        if self.proc.poll() is None:
            try:
                os.killpg(self.proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
            self.proc.wait()
        self._close_log()

    def _close_log(self):
        if self._log_file is not None:
            self._log_file.close()
            self._log_file = None

    def tail_log(self, n: int = 20) -> str:
        try:
            with open(self.log_path, "rb") as f:
                return b"\n".join(
                    f.read().splitlines()[-n:]).decode(errors="replace")
        except OSError:
            return ""


class Pod:
    """This node's trainer processes."""

    def __init__(self):
        self.containers: List[Container] = []

    def add(self, c: Container):
        self.containers.append(c)

    def start(self):
        for c in self.containers:
            c.start()

    @property
    def alive(self) -> bool:
        return any(c.alive for c in self.containers)

    @property
    def all_alive(self) -> bool:
        return all(c.alive for c in self.containers)

    def failed(self) -> List[Container]:
        return [c for c in self.containers
                if not c.alive and c.exit_code not in (0, None)]

    def finished(self) -> bool:
        return all(not c.alive for c in self.containers)

    def success(self) -> bool:
        return all(c.exit_code == 0 for c in self.containers)

    def terminate(self):
        for c in self.containers:
            c.terminate()

    def clear(self):
        self.terminate()
        self.containers = []
