"""Distributed-config auto-tuner.

Reference: AutoTuner (/root/reference/python/paddle/distributed/auto_tuner/
tuner.py:21) with pruning rules (prune.py) and cost/memory models
(cost_model.py, memory_cost_model.py). TPU-native version: candidates are
mesh layouts (dp/fsdp/tp/pp degrees x micro-batch x remat) over a chip
count; pruning enforces divisibility and the HBM budget from an analytical
transformer memory model; ranking uses a roofline cost model (MXU flops +
ICI collective bytes). The Recorder feeds measured step times back so
search converges on real data (reference recorder.py).
"""
from __future__ import annotations

import itertools
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

__all__ = ["TunableSpace", "ClusterSpec", "ModelSpec", "Candidate",
           "AutoTuner", "Recorder"]


@dataclass
class ClusterSpec:
    num_chips: int = 8
    hbm_bytes: float = 95e9            # v5p: 95GB
    peak_flops: float = 459e12         # bf16
    ici_bw: float = 9e10               # bytes/s per link, one direction
    mxu_efficiency: float = 0.55


@dataclass
class ModelSpec:
    num_layers: int = 32
    hidden: int = 4096
    ffn_hidden: int = 14336
    heads: int = 32
    vocab: int = 128256
    seq_len: int = 8192
    global_batch: int = 64             # sequences
    param_bytes: int = 2               # bf16
    opt_state_bytes: int = 8           # adam f32 m+v

    @property
    def num_params(self) -> float:
        layer = (4 * self.hidden * self.hidden
                 + 3 * self.hidden * self.ffn_hidden)
        return self.num_layers * layer + 2 * self.vocab * self.hidden


@dataclass
class Candidate:
    dp: int = 1
    fsdp: int = 1      # sharding degree (ZeRO-3 analog axis)
    tp: int = 1
    pp: int = 1
    micro_batch: int = 1
    use_recompute: bool = False
    est_memory: float = 0.0
    est_step_time: float = 0.0
    measured_time: Optional[float] = None

    def degrees(self):
        return self.dp * self.fsdp * self.tp * self.pp

    def to_dict(self) -> dict:
        return dict(dp=self.dp, sharding=self.fsdp, mp=self.tp, pp=self.pp,
                    micro_batch_size=self.micro_batch,
                    use_recompute=self.use_recompute)

    def key(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)


@dataclass
class TunableSpace:
    dp_degree: Optional[List[int]] = None
    sharding_degree: Optional[List[int]] = None
    mp_degree: Optional[List[int]] = None
    pp_degree: Optional[List[int]] = None
    micro_batch_size: Optional[List[int]] = None
    use_recompute: List[bool] = field(default_factory=lambda: [False, True])


def _divisors(n: int) -> List[int]:
    return [d for d in range(1, n + 1) if n % d == 0]


class AutoTuner:
    def __init__(self, model: ModelSpec, cluster: ClusterSpec,
                 space: Optional[TunableSpace] = None):
        self.model = model
        self.cluster = cluster
        self.space = space or TunableSpace()
        self.recorder = Recorder()

    # -- candidate generation + pruning (prune.py analog) ------------------
    def candidates(self) -> List[Candidate]:
        n = self.cluster.num_chips
        divs = _divisors(n)
        sp = self.space
        out = []
        for dp, fsdp, tp, pp in itertools.product(
                sp.dp_degree or divs, sp.sharding_degree or divs,
                sp.mp_degree or divs, sp.pp_degree or divs):
            if dp * fsdp * tp * pp != n:
                continue
            if self.model.num_layers % pp != 0:
                continue
            if self.model.heads % tp != 0 or self.model.vocab % tp != 0:
                continue
            data_rank = dp * fsdp
            if self.model.global_batch % data_rank != 0:
                continue
            per_rank = self.model.global_batch // data_rank
            for mb in (sp.micro_batch_size or _divisors(per_rank)):
                if per_rank % mb != 0:
                    continue
                if pp > 1 and per_rank // mb < pp:
                    continue  # not enough micro-batches to fill the pipe
                for rc in sp.use_recompute:
                    c = Candidate(dp, fsdp, tp, pp, mb, rc)
                    c.est_memory = self.estimate_memory(c)
                    if c.est_memory > self.cluster.hbm_bytes:
                        continue
                    c.est_step_time = self.estimate_step_time(c)
                    out.append(c)
        return out

    # -- memory model (memory_cost_model.py analog) ------------------------
    def estimate_memory(self, c: Candidate) -> float:
        m = self.model
        shard = c.tp * c.pp * c.fsdp
        params = m.num_params * m.param_bytes / shard
        grads = m.num_params * m.param_bytes / shard
        opt = m.num_params * m.opt_state_bytes / (c.tp * c.pp * c.fsdp)
        # activations per chip: micro_batch x seq x hidden x layers/pp
        act_per_layer = (2 if c.use_recompute else 14)
        acts = (c.micro_batch * m.seq_len * m.hidden // c.tp
                * act_per_layer * (m.num_layers // c.pp) * m.param_bytes)
        if c.pp > 1:
            acts *= min(c.pp, 2)  # 1F1B in-flight micro-batches bound
        return params + grads + opt + acts

    # -- roofline step-time model (cost_model.py analog) -------------------
    def estimate_step_time(self, c: Candidate) -> float:
        m, cl = self.model, self.cluster
        tokens = m.global_batch * m.seq_len
        flops = 6 * m.num_params * tokens
        if c.use_recompute:
            flops *= 4 / 3
        compute = flops / (cl.num_chips * cl.peak_flops * cl.mxu_efficiency)
        # TP all-reduces: 4 per layer, 2*bytes/bw, on the tp subring
        comm = 0.0
        if c.tp > 1:
            per_layer = (c.micro_batch * m.seq_len * m.hidden
                         * m.param_bytes)
            n_micro = max(1, m.global_batch
                          // (c.dp * c.fsdp * c.micro_batch))
            comm += (4 * m.num_layers * per_layer * 2 * (c.tp - 1) / c.tp
                     / cl.ici_bw) * n_micro / max(1, c.pp)
        if c.fsdp > 1:  # param all-gather + grad reduce-scatter
            comm += 2 * (m.num_params * m.param_bytes / (c.tp * c.pp)
                         * (c.fsdp - 1) / c.fsdp) / cl.ici_bw
        if c.dp > 1:    # grad all-reduce
            comm += 2 * (m.num_params * m.param_bytes / (c.tp * c.pp)
                         * (c.dp - 1) / c.dp) / cl.ici_bw
        if c.pp > 1:    # bubble
            n_micro = max(1, m.global_batch
                          // (c.dp * c.fsdp * c.micro_batch))
            compute *= 1 + (c.pp - 1) / n_micro
        return compute + comm

    # -- search ------------------------------------------------------------
    def tune(self, top_k: int = 5) -> List[Candidate]:
        """Ranked candidates, best (lowest estimated step time) first;
        measured results override estimates in the ordering."""
        cands = self.candidates()

        def score(c: Candidate):
            rec = self.recorder.get(c)
            return rec if rec is not None else c.est_step_time

        return sorted(cands, key=score)[:top_k]

    def best(self) -> Optional[Candidate]:
        top = self.tune(top_k=1)
        return top[0] if top else None


class Recorder:
    """Measured-result store (recorder.py analog)."""

    def __init__(self):
        self._data: Dict[str, float] = {}

    def record(self, cand: Candidate, step_time: float):
        cand.measured_time = step_time
        self._data[cand.key()] = step_time

    def get(self, cand: Candidate) -> Optional[float]:
        return self._data.get(cand.key())

    def save(self, path: str):
        with open(path, "w") as f:
            json.dump(self._data, f, indent=2)

    def load(self, path: str):
        with open(path) as f:
            self._data.update(json.load(f))
