"""paddle_tpu.distributed — mesh-native distributed stack.

Replaces the reference's ProcessGroup/NCCL world
(/root/reference/paddle/fluid/distributed/collective/,
/root/reference/python/paddle/distributed/) with jax.sharding: collectives
inside jitted programs are GSPMD-inserted XLA ops riding ICI; the host-side
layer (init, rank/world bookkeeping, launch) wraps jax.distributed.
"""
from . import parallel  # noqa: F401
from .parallel import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, DataParallel, ParallelEnv,
)
from .mesh import (  # noqa: F401
    ProcessMesh, auto, get_mesh, set_mesh,
)
from .placement import (  # noqa: F401
    Placement, Shard, Replicate, Partial,
)
from .api import (  # noqa: F401
    shard_tensor, reshard, shard_layer, shard_optimizer, dtensor_from_fn,
    unshard_dtensor,
)
from .collective import (  # noqa: F401
    all_reduce, all_gather, all_to_all, broadcast, reduce, reduce_scatter,
    scatter, gather, barrier, send, recv, isend, irecv, new_group,
    ReduceOp, get_group, wait, P2POp, batch_isend_irecv,
)
from . import fleet  # noqa: F401
from . import auto_parallel  # noqa: F401
from . import checkpoint  # noqa: F401
from . import launch  # noqa: F401
from . import auto_tuner  # noqa: F401
from . import rpc  # noqa: F401
from .spawn import spawn  # noqa: F401
from .elastic import ElasticLevel, ElasticManager, ElasticStatus  # noqa: F401
from .ring_attention import (  # noqa: F401
    ring_attention, ring_attention_local, zigzag_indices,
    inverse_zigzag_indices,
)
from .compat import *  # noqa: F401,F403
from .compat import __all__ as _compat_all

__all__ = [
    "init_parallel_env", "get_rank", "get_world_size", "DataParallel",
    "ParallelEnv", "ProcessMesh", "Shard", "Replicate", "Partial",
    "shard_tensor", "reshard", "shard_layer", "shard_optimizer",
    "all_reduce", "all_gather", "all_to_all", "broadcast", "reduce",
    "reduce_scatter", "scatter", "gather", "barrier", "send", "recv",
    "new_group", "ReduceOp", "fleet", "checkpoint", "Strategy",
    "P2POp", "batch_isend_irecv",
] + _compat_all


def __getattr__(name):
    # lazy: auto_parallel imports fleet which imports this package —
    # resolving Strategy at first access breaks the cycle
    if name == "Strategy":
        from .auto_parallel import Strategy
        return Strategy
    raise AttributeError(
        f"module 'paddle_tpu.distributed' has no attribute {name!r}")
