"""Static graph core: Program / Variable / recorder.

TPU-native re-imagination of the reference's Program/Block/Variable IR
(/root/reference/python/paddle/base/framework.py:5742 Program, :1467
Variable, OpDesc protos): instead of a serialized op-desc IR interpreted
by a C++ executor, a Program is a DAG of **pure jax thunks** — each
recorded op holds the same jnp/lax composition the eager path runs.
Shape/dtype propagation (the reference's InferMeta pass,
/root/reference/paddle/phi/infermeta/) is ``jax.eval_shape`` over the
thunk: every Variable carries a concrete ShapeDtypeStruct at build time.
Execution (paddle_tpu/static/executor.py) traces the DAG once under
jax.jit — XLA is the instruction scheduler, stream analyzer and GC that
the reference implements by hand (SURVEY.md §2.5 items 8-9).
"""
from __future__ import annotations

import contextlib
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np

from ..framework import core as fcore
from ..framework import dtype as dtypes
from ..framework.core import Parameter, Tensor

__all__ = [
    "Program", "Variable", "program_guard", "default_main_program",
    "default_startup_program", "in_static_mode", "enable_static",
    "disable_static", "data", "InputSpec",
]


class Node:
    """One recorded op: outputs = fn(*inputs) with non-Variable args
    captured as constants."""

    __slots__ = ("op_name", "fn", "args", "kwargs", "n_out", "out_vars")

    def __init__(self, op_name, fn, args, kwargs):
        self.op_name = op_name
        self.fn = fn
        self.args = args          # mix of Variable / Tensor / python consts
        self.kwargs = kwargs
        self.n_out = 0
        self.out_vars: List["Variable"] = []


class Variable:
    """Symbolic tensor in a Program (reference Variable,
    base/framework.py:1467): named, with a build-time aval. Duck-types the
    Tensor surface that layers touch (shape/dtype/ndim/astype/common
    operators), so `paddle.nn` layers build static graphs unchanged."""

    def __init__(self, program: "Program", aval, name: str,
                 node: Optional[Node] = None, out_idx: int = 0,
                 stop_gradient: bool = True, is_feed: bool = False):
        self.program = program
        self.aval = aval          # jax.ShapeDtypeStruct
        self.name = name
        self.node = node
        self.out_idx = out_idx
        self.stop_gradient = stop_gradient
        self.is_feed = is_feed
        self.persistable = False

    # -- Tensor-like surface ------------------------------------------------
    @property
    def shape(self):
        # feed vars report their declared shape, including -1 dynamic dims
        # (the build-time aval holds placeholder 1 for those; execution
        # re-traces with the real feed shapes, so only introspection of
        # DERIVED vars sees the placeholder)
        decl = getattr(self, "_declared_shape", None)
        if decl is not None:
            return [d if d is not None else -1 for d in decl]
        return list(self.aval.shape)

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def dtype(self):
        return np.dtype(self.aval.dtype)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    def astype(self, dtype):
        d = dtypes.convert_dtype(dtype)
        return fcore.apply("cast", lambda x: x.astype(d), self)

    def cast(self, dtype):
        return self.astype(dtype)

    def __repr__(self):
        return (f"Variable(name={self.name!r}, shape={self.shape}, "
                f"dtype={dtypes.dtype_name(self.dtype)})")

    def __getattr__(self, name):
        # tensor methods (matmul, reshape, sum, ...) monkey-patched onto
        # Tensor work on Variables too: they all route through fcore.apply
        method = fcore._tensor_method_registry.get(name)
        if method is not None:
            return lambda *a, **k: method(self, *a, **k)
        raise AttributeError(
            f"'Variable' object has no attribute {name!r}")


# arithmetic dunders: reuse whatever got patched onto Tensor
def _alias_tensor_dunders():
    for dunder in ("__add__", "__radd__", "__sub__", "__rsub__", "__mul__",
                   "__rmul__", "__truediv__", "__rtruediv__", "__matmul__",
                   "__neg__", "__pow__", "__rpow__", "__mod__", "__lt__",
                   "__le__", "__gt__", "__ge__", "__eq__", "__ne__",
                   "__getitem__"):
        fn = getattr(Tensor, dunder, None)
        # check Variable.__dict__, not hasattr: rich comparisons inherit
        # object defaults, which would silently win and break x == y
        if fn is not None and dunder not in Variable.__dict__:
            setattr(Variable, dunder, fn)


class Program:
    """An op DAG + its feed variables and referenced parameters."""

    _counter = 0

    def __init__(self):
        Program._counter += 1
        self.id = Program._counter
        self.nodes: List[Node] = []
        self.feeds: Dict[str, Variable] = {}
        self.vars: Dict[str, Variable] = {}
        self._name_counter = 0
        self.version = 0           # bumped per appended node (cache key)
        # optimizer state attached by minimize() (executor updates it)
        self._train_spec = None
        # id(node) → replacement fn (clone(for_test): dropout → identity)
        self._node_overrides: Dict[int, Callable] = {}

    # -- naming -------------------------------------------------------------
    def _unique_name(self, prefix: str) -> str:
        self._name_counter += 1
        return f"{prefix}_{self._name_counter}"

    # -- recording ----------------------------------------------------------
    def add_feed(self, name: str, shape, dtype) -> Variable:
        aval = jax.ShapeDtypeStruct(
            tuple(d if d and d > 0 else 1 for d in shape),
            dtypes.convert_dtype(dtype))
        # dynamic dims (None/-1) are materialized per-run from the feed;
        # the build-time aval uses 1 as placeholder
        v = Variable(self, aval, name, stop_gradient=True, is_feed=True)
        v._declared_shape = tuple(shape)
        self.feeds[name] = v
        self.vars[name] = v
        return v

    def record(self, op_name: str, fn: Callable, args: tuple,
               kwargs: dict):
        """Append a node; infer output avals via jax.eval_shape (InferMeta
        analog). Returns Variable or tuple of Variables."""
        node = Node(op_name, fn, args, kwargs)

        sym_pos = [i for i, a in enumerate(args)
                   if isinstance(a, Variable)]
        avals = [args[i].aval for i in sym_pos]

        def abstract(*sym_vals):
            full = list(args)
            for i, v in zip(sym_pos, sym_vals):
                full[i] = v
            full = [a._value if isinstance(a, Tensor) else a for a in full]
            return fn(*full, **kwargs)

        out_aval = jax.eval_shape(abstract, *avals)
        multi = isinstance(out_aval, (tuple, list))
        out_list = list(out_aval) if multi else [out_aval]
        node.n_out = len(out_list)

        any_grad = any(not args[i].stop_gradient for i in sym_pos) or any(
            isinstance(a, Tensor) and not a.stop_gradient for a in args)
        outs = []
        for k, av in enumerate(out_list):
            name = self._unique_name(op_name)
            v = Variable(self, jax.ShapeDtypeStruct(av.shape, av.dtype),
                         name, node, k, stop_gradient=not any_grad)
            self.vars[name] = v
            outs.append(v)
        node.out_vars = outs
        self.nodes.append(node)
        self.version += 1
        return tuple(outs) if multi else outs[0]

    # -- introspection -------------------------------------------------------
    def parameters(self) -> List[Parameter]:
        """Concrete Parameters referenced by recorded nodes (the analog of
        the startup program's persistables)."""
        seen, out = set(), []
        for node in self.nodes:
            for a in node.args:
                if isinstance(a, Parameter) and id(a) not in seen:
                    seen.add(id(a))
                    out.append(a)
        return out

    def list_vars(self):
        return list(self.vars.values())

    def global_block(self):
        return _BlockFacade(self)

    def clone(self, for_test: bool = False) -> "Program":
        p = Program()
        p.nodes = list(self.nodes)
        p.feeds = dict(self.feeds)
        p.vars = dict(self.vars)
        p._name_counter = self._name_counter
        p.version = self.version
        p._node_overrides = dict(self._node_overrides)
        if for_test:
            # reference semantics: strip training-only behavior. Dropout
            # thunks captured training=True at record time, so this clone
            # overrides those nodes with identity (eval dropout in
            # upscale_in_train mode IS identity) — via an override map, so
            # the shared Node/Variable objects of the source program stay
            # untouched. Train-mode batch_norm can't be rewritten post-hoc
            # — build eval programs with is_test=True.
            import warnings
            for node in p.nodes:
                if node.op_name == "dropout":
                    p._node_overrides[id(node)] = \
                        lambda x, *rest, **kw: x
                elif node.op_name == "batch_norm":
                    warnings.warn(
                        "clone(for_test=True) cannot convert a recorded "
                        "train-mode batch_norm to eval mode; build the "
                        "eval program with is_test=True")
            p.version += 1
        return p

    def __repr__(self):
        ops = ", ".join(n.op_name for n in self.nodes[:8])
        more = "..." if len(self.nodes) > 8 else ""
        return (f"Program(id={self.id}, {len(self.nodes)} ops: "
                f"[{ops}{more}], feeds={list(self.feeds)})")


class _BlockFacade:
    """Minimal Block view (reference Block, base/framework.py:3350):
    enough for code that iterates block.ops / block.vars."""

    def __init__(self, program: Program):
        self.program = program

    @property
    def ops(self):
        return self.program.nodes

    @property
    def vars(self):
        return self.program.vars

    def var(self, name):
        return self.program.vars[name]


# ---------------------------------------------------------------------------
# mode + default programs
# ---------------------------------------------------------------------------

class _State(threading.local):
    def __init__(self):
        self.static = False
        self.main: Optional[Program] = None
        self.startup: Optional[Program] = None


_state = _State()


def in_static_mode() -> bool:
    return _state.static


def enable_static():
    _alias_tensor_dunders()
    _state.static = True
    if _state.main is None:
        _state.main = Program()
        _state.startup = Program()
    fcore._set_static_handler(_static_dispatch)


def disable_static():
    _state.static = False
    fcore._set_static_handler(None)


def default_main_program() -> Program:
    if _state.main is None:
        _state.main = Program()
    return _state.main


def default_startup_program() -> Program:
    if _state.startup is None:
        _state.startup = Program()
    return _state.startup


@contextlib.contextmanager
def program_guard(main_program: Program,
                  startup_program: Optional[Program] = None):
    """Reference program_guard parity (base/framework.py:7867)."""
    prev_main, prev_startup = _state.main, _state.startup
    _state.main = main_program
    _state.startup = startup_program or _state.startup
    try:
        yield
    finally:
        _state.main, _state.startup = prev_main, prev_startup


def _static_dispatch(op_name: str, fn: Callable, args: tuple, kwargs: dict):
    """Hook installed into framework.core.apply: record instead of execute
    when static mode is on and symbolic values are involved."""
    if not _state.static:
        return NotImplemented
    involves_sym = any(isinstance(a, Variable) for a in args)
    if not involves_sym:
        # concrete-only op (e.g. param init inside a layer): run eagerly
        return NotImplemented
    return default_main_program().record(op_name, fn, args, kwargs)


# ---------------------------------------------------------------------------
# data / InputSpec
# ---------------------------------------------------------------------------

def data(name: str, shape: Sequence[int], dtype="float32",
         lod_level: int = 0) -> Variable:
    """paddle.static.data parity (python/paddle/static/input.py)."""
    return default_main_program().add_feed(name, shape, dtype)


class InputSpec:
    """Shape/dtype/name spec (python/paddle/static/input.py InputSpec)."""

    def __init__(self, shape, dtype="float32", name=None,
                 stop_gradient=True):
        self.shape = tuple(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)

    def __repr__(self):
        return (f"InputSpec(shape={self.shape}, dtype={self.dtype}, "
                f"name={self.name})")
