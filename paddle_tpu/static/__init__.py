"""paddle_tpu.static — static-graph API (Program/Executor).

Parity with paddle.static (/root/reference/python/paddle/static/,
base/framework.py, base/executor.py), rebuilt TPU-first: the Program is a
DAG of pure jax thunks (program.py), the Executor is whole-program
jax.jit (executor.py), and save/load_inference_model round-trips through
StableHLO via jax.export (io.py) — the serving artifact the reference
gets from ProgramDesc protobufs + AnalysisPredictor.
"""
from .program import (  # noqa: F401
    InputSpec, Program, Variable, data, default_main_program,
    default_startup_program, disable_static, enable_static, in_static_mode,
    program_guard,
)
from .executor import Executor, global_scope  # noqa: F401
from .io import (  # noqa: F401
    load_inference_model, save_inference_model,
)
from . import nn  # noqa: F401
from .compat import *  # noqa: F401,F403
from .compat import __all__ as _compat_all

__all__ = [
    "InputSpec", "Program", "Variable", "data", "default_main_program",
    "default_startup_program", "enable_static", "disable_static",
    "program_guard", "Executor", "global_scope", "save_inference_model",
    "load_inference_model", "nn", "append_backward",
] + _compat_all


def append_backward(loss, parameter_list=None, no_grad_set=None):
    """Reference base/backward.py appends grad OpDescs; here gradients
    materialize inside the compiled train step that optimizer.minimize
    sets up — a separate grad-var graph does not exist, so failing loudly
    beats silently returning nothing."""
    raise NotImplementedError(
        "append_backward has no standalone form in paddle_tpu.static: "
        "gradients are computed by jax.grad inside the compiled step. "
        "Use optimizer.minimize(loss), which fuses forward+backward+"
        "update into one XLA program.")
