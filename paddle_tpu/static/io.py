"""save/load_inference_model over jax.export (StableHLO).

Reference: paddle.static.save_inference_model serializes ProgramDesc
protobuf + persistables, consumed by AnalysisPredictor
(/root/reference/python/paddle/static/io.py, paddle/fluid/inference/).
TPU-native artifact: the traced program exported as serialized StableHLO
(jax.export) — a stable, versioned, runtime-loadable form — plus a numpy
archive of parameters. Loading rebuilds a callable without the Python
model code, exactly the deployment contract the reference's inference
engine provides.
"""
from __future__ import annotations

import os
import pickle
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor
from .executor import _evaluate
from .program import Program, Variable

__all__ = ["save_inference_model", "load_inference_model"]


def save_inference_model(path_prefix: str, feed_vars: Sequence[Variable],
                         fetch_vars: Sequence[Variable], executor=None,
                         program: Optional[Program] = None, **kwargs):
    """Serialize the subgraph feed_vars → fetch_vars.

    Writes <prefix>.pdmodel (pickled {stablehlo, in/out specs}) and
    <prefix>.pdiparams (npz of captured parameters)."""
    feed_vars = list(feed_vars)
    fetch_vars = list(fetch_vars)
    program = program or feed_vars[0].program

    # concrete captures (params/buffers) become explicit inputs so the
    # exported artifact is self-contained and the arrays swappable. Only
    # the fetch closure's nodes are walked — a shared default program may
    # hold unrelated models whose weights must not leak into the artifact.
    needed = set()
    stack = list(fetch_vars)
    while stack:
        v = stack.pop()
        if id(v) in needed:
            continue
        needed.add(id(v))
        if v.node is not None:
            stack.extend(a for a in v.node.args if isinstance(a, Variable))
    captured: List[Tensor] = []
    seen = set()
    for node in program.nodes:
        if not any(id(v) in needed for v in node.out_vars):
            continue
        for a in node.args:
            if isinstance(a, Tensor) and id(a) not in seen:
                seen.add(id(a))
                captured.append(a)

    def fn(feed_arrays, param_arrays):
        env = {id(v): a for v, a in zip(feed_vars, feed_arrays)}
        env.update({id(t): a for t, a in zip(captured, param_arrays)})
        return tuple(_evaluate(program, env, fetch_vars))

    feed_avals = [jax.ShapeDtypeStruct(v.aval.shape, v.aval.dtype)
                  for v in feed_vars]
    param_avals = [jax.ShapeDtypeStruct(t._value.shape, t._value.dtype)
                   for t in captured]
    exported = jax.export.export(jax.jit(fn))(feed_avals, param_avals)
    blob = exported.serialize()

    os.makedirs(os.path.dirname(path_prefix) or ".", exist_ok=True)
    with open(path_prefix + ".pdmodel", "wb") as f:
        pickle.dump({
            "stablehlo": blob,
            "feed_names": [v.name for v in feed_vars],
            "feed_shapes": [tuple(v.aval.shape) for v in feed_vars],
            "feed_dtypes": [str(v.aval.dtype) for v in feed_vars],
            "fetch_names": [v.name for v in fetch_vars],
        }, f)
    np.savez(path_prefix + ".pdiparams",
             **{f"p{i}": np.asarray(t._value)
                for i, t in enumerate(captured)})
    return path_prefix


class _LoadedPredictor:
    """Callable rebuilt from the serialized artifact.

    donate_feeds=True (inference.Config.enable_memory_optim) re-jits the
    exported call with the feed buffers donated — XLA reuses them for
    outputs, the analog of the reference's memory-reuse pass."""

    def __init__(self, path_prefix: str, donate_feeds: bool = False):
        with open(path_prefix + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
        self.feed_names: List[str] = meta["feed_names"]
        self.fetch_names: List[str] = meta["fetch_names"]
        self.feed_shapes = meta["feed_shapes"]
        self.feed_dtypes = meta["feed_dtypes"]
        self._exported = jax.export.deserialize(meta["stablehlo"])
        z = np.load(path_prefix + ".pdiparams.npz")
        stored = [jnp.asarray(z[f"p{i}"]) for i in range(len(z.files))]
        # two artifact layouts share the extension: static.io exports
        # fn(feeds, params); jit.save exports fn(feeds, params, buffers)
        # with n_params marking the split
        if meta.get("kind") == "jit.save":
            n_p = meta["n_params"]
            self._params = stored[:n_p]
            self._buffers: Optional[List] = stored[n_p:]
        else:
            self._params = stored
            self._buffers = None
        self._call = self._exported.call
        if donate_feeds:
            self._call = jax.jit(self._exported.call, donate_argnums=(0,))

    def run(self, feeds: Sequence) -> List[np.ndarray]:
        feed_arrays = [jnp.asarray(x._value if isinstance(x, Tensor) else x)
                       for x in feeds]
        if self._buffers is not None:
            out = self._call(feed_arrays, self._params, self._buffers)
        else:
            out = self._call(feed_arrays, self._params)
        return [np.asarray(o) for o in out]

    def __call__(self, *feeds):
        return self.run(list(feeds))


def load_inference_model(path_prefix: str, executor=None, **kwargs):
    """Returns (predictor, feed_names, fetch_names) — the reference
    returns (program, feed_names, fetch_names); the predictor here plays
    the program role (pass feeds positionally to .run)."""
    pred = _LoadedPredictor(path_prefix)
    return pred, pred.feed_names, pred.fetch_names
