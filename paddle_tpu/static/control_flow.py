"""Control-flow ops: cond / while_loop / switch_case / case.

Reference: paddle.static.nn.cond & control-flow OpDescs
(/root/reference/python/paddle/static/nn/control_flow.py, C++ side
conditional_block/while ops + PIR control_flow_op.cc). TPU-native: these
ARE jax.lax.cond / lax.while_loop / lax.switch — compiler-understood
structured control flow with no interpreter — dispatched through the
framework tape so they differentiate (cond/switch) and jit cleanly.
Branch callables receive and return Tensors; inside they run on traced
arrays like any framework op. Usable in eager, to_static and
static-Program modes.
"""
from __future__ import annotations

from typing import Any, Callable, List, Sequence

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, apply, apply_nodiff, no_grad

__all__ = ["cond", "while_loop", "switch_case", "case"]


def _wrap(arrs):
    return tuple(Tensor(a) for a in arrs)


def _unwrap_outs(out):
    if isinstance(out, Tensor):
        return (out._value,), True
    return tuple(o._value if isinstance(o, Tensor) else jnp.asarray(o)
                 for o in out), False


def _run_branch(fn, arrs):
    out = fn(*_wrap(arrs)) if arrs else fn()
    return _unwrap_outs(out)


def cond(pred, true_fn: Callable, false_fn: Callable,
         inputs: Sequence = (), name=None):
    """paddle.static.nn.cond parity: evaluates ONE branch (lax.cond —
    unlike where/select both sides are not computed). Differentiable
    w.r.t. inputs. Branch outputs must match in structure/shape/dtype
    (same contract as the reference)."""
    inputs = tuple(inputs)

    def f(p, *arrs):
        def tb(a):
            outs, _ = _run_branch(true_fn, a)
            return outs

        def fb(a):
            outs, _ = _run_branch(false_fn, a)
            return outs

        outs = jax.lax.cond(jnp.asarray(p).astype(bool).reshape(()),
                            tb, fb, arrs)
        return outs if len(outs) > 1 else outs[0]

    result = apply("cond", f, pred, *inputs)
    return result


def while_loop(cond_fn: Callable, body_fn: Callable,
               loop_vars: Sequence, is_test: bool = False, name=None):
    """paddle.static.nn.while_loop parity over lax.while_loop.
    cond_fn(*vars) → scalar bool Tensor; body_fn(*vars) → same-structure
    vars. Like the reference (and XLA), the loop is not differentiated
    in reverse mode — use lax.scan-style constructs (or fori with known
    trip count) for training loops."""
    loop_vars = tuple(loop_vars)

    def f(*arrs):
        def c(vs):
            out = cond_fn(*_wrap(vs))
            return jnp.asarray(
                out._value if isinstance(out, Tensor) else out
            ).astype(bool).reshape(())

        def b(vs):
            out = body_fn(*_wrap(vs))
            if isinstance(out, Tensor):
                out = (out,)
            return tuple(o._value if isinstance(o, Tensor)
                         else jnp.asarray(o) for o in out)

        outs = jax.lax.while_loop(c, b, arrs)
        return outs if len(outs) > 1 else outs[0]

    result = apply_nodiff("while_loop", f, *loop_vars)
    return list(result) if isinstance(result, tuple) else [result]


def switch_case(branch_index, branch_fns, default=None, name=None):
    """paddle.static.nn.switch_case parity over lax.switch.
    branch_fns: dict {index: fn} or list of (index, fn) / fns. default
    runs when the index matches nothing."""
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        items = sorted((int(i), f) for i, f in branch_fns)
    else:
        items = list(enumerate(branch_fns))
    keys = [k for k, _ in items]
    fns = [f for _, f in items]
    if default is None:
        default = fns[-1]

    def f(idx):
        # map the user index onto 0..n (n = default) with a lookup table
        table = jnp.asarray(keys)
        i = jnp.asarray(idx).reshape(()).astype(jnp.int32)
        matches = (table == i)
        pos = jnp.where(matches.any(),
                        jnp.argmax(matches).astype(jnp.int32),
                        jnp.int32(len(fns)))

        def mk(fn):
            def branch(_):
                outs, single_out = _run_branch(fn, ())
                return outs
            return branch

        outs = jax.lax.switch(pos, [mk(f_) for f_ in fns]
                              + [mk(default)], ())
        return outs if len(outs) > 1 else outs[0]

    return apply_nodiff("switch_case", f, branch_index)


def case(pred_fn_pairs, default=None, name=None):
    """paddle.static.nn.case parity: first pair whose pred is True runs.
    Lowers to nested lax.cond."""
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]

    preds = [p for p, _ in pairs]
    fns = [f for _, f in pairs]

    def f(*pred_arrs):
        def build(i):
            if i == len(fns):
                def d(_):
                    outs, _s = _run_branch(default, ())
                    return outs
                return d

            def branch(_):
                def taken(__):
                    outs, _s = _run_branch(fns[i], ())
                    return outs
                return jax.lax.cond(
                    jnp.asarray(pred_arrs[i]).astype(bool).reshape(()),
                    taken, build(i + 1), ())
            return branch

        outs = build(0)(())
        return outs if len(outs) > 1 else outs[0]

    return apply_nodiff("case", f, *preds)
