"""paddle.static.nn parity (/root/reference/python/paddle/static/nn/):
graph-building layer functions. Each creates concrete Parameters (eager)
and records the compute symbolically through the shared functional ops —
the same split the reference has between startup (param init) and main
(compute) programs.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

from ..framework.core import Parameter
from ..framework import dtype as dtypes
from .. import nn as _nn
from ..nn import functional as F

__all__ = ["fc", "conv2d", "batch_norm", "embedding", "layer_norm",
           "dropout", "cond", "while_loop", "switch_case", "case"]

from .control_flow import case, cond, switch_case, while_loop  # noqa: E402,F401


def _param(shape, dtype, initializer=None, name=None):
    from ..nn.initializer import XavierNormal
    init = initializer or XavierNormal()
    d = dtypes.convert_dtype(dtype)
    return Parameter(init(tuple(shape), d))


def fc(x, size: int, num_flatten_dims: int = 1, weight_attr=None,
       bias_attr=None, activation: Optional[str] = None, name=None):
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _param((in_dim, size), x.dtype)
    b = _param((size,), x.dtype) if bias_attr is not False else None
    h = x
    if len(x.shape) > num_flatten_dims + 1:
        h = h.reshape([*x.shape[:num_flatten_dims], in_dim])
    out = h.matmul(w)
    if b is not None:
        out = out + b
    if activation:
        out = getattr(F, activation)(out)
    return out


def conv2d(input, num_filters: int, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act: Optional[str] = None, data_format="NCHW", name=None):
    k = filter_size if isinstance(filter_size, (tuple, list)) \
        else (filter_size, filter_size)
    in_c = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    w = _param((num_filters, in_c // groups, *k), input.dtype)
    b = _param((num_filters,), input.dtype) if bias_attr is not False \
        else None
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups,
                   data_format=data_format)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               is_test=False, name=None):
    c = input.shape[1] if data_layout == "NCHW" else input.shape[-1]
    import jax.numpy as jnp
    from ..framework.core import Tensor
    scale = _param((c,), input.dtype)
    scale.set_value(np.ones(c, np.float32))
    bias = _param((c,), input.dtype)
    bias.set_value(np.zeros(c, np.float32))
    mean = Tensor(jnp.zeros(c, dtypes.convert_dtype(input.dtype)))
    var = Tensor(jnp.ones(c, dtypes.convert_dtype(input.dtype)))
    out = F.batch_norm(input, mean, var, scale, bias, training=not is_test,
                       momentum=momentum, epsilon=epsilon,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def embedding(input, size: Sequence[int], is_sparse=False,
              param_attr=None, dtype="float32", name=None):
    w = _param(tuple(size), dtype)
    return F.embedding(input, w)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    shape = tuple(input.shape[begin_norm_axis:])
    import jax.numpy as jnp
    from ..framework.core import Tensor
    w = Parameter(jnp.ones(shape, dtypes.convert_dtype(input.dtype))) \
        if scale else None
    b = Parameter(jnp.zeros(shape, dtypes.convert_dtype(input.dtype))) \
        if shift else None
    out = F.layer_norm(input, shape, w, b, epsilon)
    if act:
        out = getattr(F, act)(out)
    return out


def dropout(x, dropout_prob=0.5, is_test=False, name=None):
    return F.dropout(x, p=dropout_prob, training=not is_test)
