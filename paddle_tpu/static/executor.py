"""Static-graph Executor: whole-program XLA compilation.

Reference: Executor (/root/reference/python/paddle/base/executor.py:1153)
→ _ExecutorCache → C++ StandaloneExecutor/PirInterpreter
(/root/reference/paddle/fluid/framework/new_executor/) which hand-builds
instruction lists, dependency DAGs, stream assignments and GC. The
TPU-native executor deletes all of that machinery: Executor.run traces
the Program's thunk-DAG into ONE jitted function (keyed by program
version + feed shapes + fetch set), and XLA performs scheduling, fusion,
memory planning and buffer reuse. Training programs (after
optimizer.minimize) compile forward+backward+update with donated
parameter buffers — in-place updates in HBM, the analog of the
reference's inplace/GC passes at zero runtime cost.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor
from .program import Program, Variable, default_main_program

__all__ = ["Executor", "global_scope"]


class _Scope:
    """Name → concrete value store (reference global scope analog)."""

    def __init__(self):
        self._vars: Dict[str, Any] = {}

    def var(self, name):
        return self._vars.get(name)

    def set(self, name, value):
        self._vars[name] = value

    def find_var(self, name):
        return self._vars.get(name)


_global_scope = _Scope()


def global_scope() -> _Scope:
    return _global_scope


def _evaluate(program: Program, env: Dict[int, Any],
              targets: Sequence[Variable]):
    """Evaluate the DAG for `targets` given initial env {id(Variable) →
    value}. Pure: concrete Tensors resolve through env when present
    (swapped-in trainable params) else their current arrays (captured
    constants)."""
    # iterative worklist (deep programs would blow Python's recursion cap)
    needed_ids = set()
    stack = [t for t in targets if isinstance(t, Variable)]
    while stack:
        v = stack.pop()
        if id(v) in needed_ids:
            continue
        needed_ids.add(id(v))
        if v.node is not None:
            stack.extend(a for a in v.node.args
                         if isinstance(a, Variable)
                         and id(a) not in needed_ids)

    def value_of(x):
        if isinstance(x, Variable):
            if id(x) not in env:
                raise KeyError(
                    f"Variable {x.name!r} has no value: feed it or check "
                    f"it belongs to this program")
            return env[id(x)]
        if isinstance(x, Tensor):
            return env.get(id(x), x._value)
        return x

    for node in program.nodes:
        if not any(id(v) in needed_ids for v in node.out_vars):
            continue
        if all(id(v) in env for v in node.out_vars):
            continue
        fn, vals = resolve_node(program, node, value_of)
        out = fn(*vals, **node.kwargs)
        outs = list(out) if isinstance(out, (tuple, list)) else [out]
        for v, o in zip(node.out_vars, outs):
            env[id(v)] = o
    return [value_of(t) for t in targets]


def resolve_node(program, node, value_of):
    """The one place node-execution semantics live (arg resolution +
    override lookup) — shared by the executor walk above and
    cost_model.profile_measure so the profiled semantics can never
    drift from the executed ones."""
    vals = [value_of(a) for a in node.args]
    fn = program._node_overrides.get(id(node), node.fn)
    return fn, vals


class Executor:
    def __init__(self, place=None):
        self.place = place
        self._cache: Dict[tuple, Any] = {}

    def run(self, program: Optional[Program] = None,
            feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[Sequence] = None,
            return_numpy: bool = True, **kwargs):
        """Compile (cached) + run. Returns list of fetched values."""
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = list(fetch_list or [])

        fetch_vars = []
        for f in fetch_list:
            if isinstance(f, str):
                fetch_vars.append(program.vars[f])
            else:
                fetch_vars.append(f)

        feed_names = sorted(feed.keys())
        feed_arrays = []
        for n in feed_names:
            a = feed[n]
            a = a._value if isinstance(a, Tensor) else jnp.asarray(a)
            feed_arrays.append(a)

        # train step only when the fetch actually wants the loss: fetching
        # e.g. predictions alone is evaluation and must neither require
        # the label feeds nor update parameters (the reference executor
        # prunes to the fetch list the same way)
        spec = program._train_spec
        if spec is not None and not any(v is spec["loss"]
                                        for v in fetch_vars):
            spec = None
        params = program.parameters()
        trainable = [p for p in params if not p.stop_gradient] \
            if spec is not None else []

        from ..decomposition.register import prim_enabled
        key = (program.id, program.version,
               tuple(id(v) for v in fetch_vars), tuple(feed_names),
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               # compiled step closes over the optimizer and loss: a new
               # minimize() must recompile, not reuse the old update rule
               None if spec is None else (id(spec["optimizer"]),
                                          id(spec["loss"])),
               # DecompAware kernels read the prim flag at trace time —
               # a toggle must recompile, not reuse the other mode's trace
               prim_enabled())
        compiled = self._cache.get(key)
        if compiled is None:
            compiled = self._build(program, fetch_vars, feed_names,
                                   trainable, spec)
            self._cache[key] = compiled

        if spec is not None:
            opt = spec["optimizer"]
            if opt._state is None:
                opt._state = opt.init_state([p._value for p in trainable])
            lr = jnp.asarray(opt.get_lr(), jnp.float32)
            outs, new_params, new_state = compiled(
                feed_arrays, [p._value for p in trainable], opt._state, lr)
            for p, a in zip(trainable, new_params):
                p._replace(a)
            opt._state = new_state
            opt._step_count += 1
        else:
            outs = compiled(feed_arrays)

        if return_numpy:
            return [np.asarray(o) for o in outs]
        return [Tensor(o) for o in outs]

    def _build(self, program: Program, fetch_vars, feed_names, trainable,
               spec):
        feed_vars = [program.feeds[n] for n in feed_names]

        if spec is None:
            def pure(feed_arrays):
                env = {id(v): a for v, a in zip(feed_vars, feed_arrays)}
                return _evaluate(program, env, fetch_vars)
            return jax.jit(pure)

        loss_var = spec["loss"]

        def step(feed_arrays, param_arrays, opt_state, lr):
            def loss_fn(tp):
                env = {id(v): a for v, a in zip(feed_vars, feed_arrays)}
                env.update({id(p): a for p, a in zip(trainable, tp)})
                outs = _evaluate(program, env,
                                 [loss_var] + list(fetch_vars))
                return outs[0].astype(jnp.float32), outs[1:]

            (_, fetches), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(param_arrays)
            opt = spec["optimizer"]
            new_params, new_state = opt.update(
                param_arrays, list(grads), opt_state, lr)
            return fetches, new_params, new_state

        return jax.jit(step, donate_argnums=(1, 2))

    def close(self):
        self._cache.clear()
