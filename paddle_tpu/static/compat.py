"""paddle.static long-tail parity (reference python/paddle/static/
__init__.py exports beyond the Program/Executor core).

Grouping:
- REAL: device-place helpers, global-var/parameter factories, metric
  ops (accuracy/auc), name/scope/device guards, Print, py_func,
  ExponentialMovingAverage, program/param (de)serialization over the
  existing artifact formats, BuildStrategy/ExecutionStrategy/
  CompiledProgram option holders (advisory under XLA — documented).
- LOUD STUBS: IPU-specific APIs and the parameter-server-era
  ctr_metric_bundle (hardware/subsystem that does not exist here;
  COVERAGE.md documents the descope).
"""
from __future__ import annotations

import contextlib
import pickle
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Parameter, Tensor, apply, apply_nodiff

__all__ = [
    "cpu_places", "cuda_places", "xpu_places", "create_global_var",
    "create_parameter", "name_scope", "device_guard", "scope_guard",
    "Print", "py_func", "accuracy", "auc", "gradients",
    "ExponentialMovingAverage", "BuildStrategy", "ExecutionStrategy",
    "CompiledProgram", "WeightNormParamAttr", "normalize_program",
    "serialize_program", "deserialize_program", "serialize_persistables",
    "deserialize_persistables", "save", "load", "save_to_file",
    "load_from_file", "load_program_state", "set_program_state",
    "IpuStrategy", "IpuCompiledProgram", "ipu_shard_guard",
    "set_ipu_shard", "ctr_metric_bundle",
]


# -- places -----------------------------------------------------------------

def cpu_places(device_count=None):
    n = device_count or 1
    return [f"cpu:{i}" for i in range(n)]


def cuda_places(device_ids=None):
    """The reference's 'cuda' means 'the accelerator' — TPU devices here."""
    devs = jax.devices()
    if device_ids is None:
        return [f"{d.platform}:{d.id}" for d in devs]
    return [f"{devs[i].platform}:{devs[i].id}" for i in device_ids]


def xpu_places(device_ids=None):
    return cuda_places(device_ids)


# -- var/param factories ----------------------------------------------------

def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..framework.dtype import convert_dtype
    t = Tensor(jnp.full(tuple(shape), value, convert_dtype(dtype)),
               name=name or "")
    t.persistable = persistable
    return t


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    from ..tensor import create_parameter as _cp
    return _cp(shape, dtype, name=name, attr=attr, is_bias=is_bias,
               default_initializer=default_initializer)


# -- guards -----------------------------------------------------------------

_name_scope_stack: list = []


@contextlib.contextmanager
def name_scope(prefix=None):
    """Hierarchical op-name prefixing (reference static.name_scope)."""
    _name_scope_stack.append(prefix or "")
    try:
        yield
    finally:
        _name_scope_stack.pop()


@contextlib.contextmanager
def device_guard(device=None):
    """Pin ops to a device. Under XLA the compiler owns placement inside
    a program; host pinning is honored via jax.default_device for the
    eager ops executed in scope."""
    if device and device.startswith("cpu"):
        with jax.default_device(jax.devices("cpu")[0]):
            yield
        return
    yield


@contextlib.contextmanager
def scope_guard(scope):
    """Variable scopes are Python object lifetimes here (no global
    Scope registry); the guard exists for API compatibility."""
    yield


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=True, print_phase="both"):
    """Debug print op (reference static.Print): prints and passes the
    tensor through (works under jit via jax.debug.print)."""
    msg = message or ""

    def f(a):
        jax.debug.print(msg + " {x}", x=a)
        return a

    return apply("print", f, input)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    """Run a Python callable as an op (reference static.py_func). Eager
    execution makes this direct; under jit it would require
    io_callback — the eager path is the supported one."""
    xs = x if isinstance(x, (list, tuple)) else [x]
    res = func(*xs)
    return res


# -- metric ops -------------------------------------------------------------

def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Top-k accuracy (reference static.accuracy)."""
    def f(pred, y):
        topk = jnp.argsort(-pred, axis=-1)[..., :k]
        hit = (topk == y.reshape(-1, 1)).any(axis=-1)
        return hit.mean(dtype=jnp.float32)
    return apply_nodiff("accuracy", f, input, label)


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1, ins_tag_weight=None):
    """Area under the ROC curve (reference static.auc) — batch-local
    (the reference accumulates across batches via internal state; use
    paddle_tpu.metric.Auc for streaming)."""
    def f(pred, y):
        score = pred[:, 1] if pred.ndim == 2 and pred.shape[1] == 2 \
            else pred.reshape(-1)
        y_ = y.reshape(-1).astype(jnp.float32)
        order = jnp.argsort(-score)
        ys = y_[order]
        pos = jnp.sum(ys)
        neg = ys.shape[0] - pos
        tps = jnp.cumsum(ys)
        fps = jnp.cumsum(1 - ys)
        tpr = tps / jnp.maximum(pos, 1)
        fpr = fps / jnp.maximum(neg, 1)
        a = jnp.trapezoid(tpr, fpr)
        return a.astype(jnp.float32)
    out = apply_nodiff("auc", f, input, label)
    return out, out, []


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Reference static.gradients builds grad ops into the program; the
    jax-native form is jax.grad over the compiled step. Eagerly (the
    supported mode here), use Tensor.backward() / paddle.grad."""
    from ..autograd import grad as _grad
    tg = targets if isinstance(targets, (list, tuple)) else [targets]
    ins = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    outs = _grad(tg, ins, grad_outputs=target_gradients,
                 allow_unused=True)
    return outs


# -- EMA --------------------------------------------------------------------

class ExponentialMovingAverage:
    """EMA of parameters (reference static.ExponentialMovingAverage):
    update() folds current params into shadows; apply() is a context
    manager that swaps shadows in (restore on exit)."""

    def __init__(self, decay=0.999, thres_steps=None, name=None):
        self.decay = float(decay)
        self._shadow: dict = {}
        self._backup: dict = {}
        self._step = 0

    def update(self, parameters=None):
        params = parameters or self._default_params()
        self._step += 1
        # bias-corrected dynamic decay like the reference's thres_steps
        d = min(self.decay, (1.0 + self._step) / (10.0 + self._step))
        for p in params:
            pid = id(p)
            cur = p._value.astype(jnp.float32)
            if pid not in self._shadow:
                self._shadow[pid] = (p, cur)
            else:
                _, old = self._shadow[pid]
                self._shadow[pid] = (p, d * old + (1.0 - d) * cur)

    def _default_params(self):
        raise ValueError(
            "ExponentialMovingAverage.update() needs the parameter list "
            "(pass parameters=model.parameters())")

    @contextlib.contextmanager
    def apply(self, executor=None, need_restore=True):
        for pid, (p, shadow) in self._shadow.items():
            self._backup[pid] = p._value
            p._replace(shadow.astype(p._value.dtype))
        try:
            yield
        finally:
            if need_restore:
                self.restore()

    def restore(self, executor=None):
        for pid, (p, _) in self._shadow.items():
            if pid in self._backup:
                p._replace(self._backup.pop(pid))


# -- option holders ---------------------------------------------------------

class BuildStrategy:
    """Graph-build options (reference BuildStrategy). Under XLA these
    choices (fusion, memory reuse, reduce strategy) are the compiler's —
    the object records the knobs for API compatibility and the few that
    map (e.g. build_cinn_pass → XLA is always on) are documented
    no-ops."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.memory_optimize = None
        self.enable_inplace = None
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False
        self.build_cinn_pass = False
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Reference CompiledProgram wraps a Program with build options; the
    Executor here compiles everything with XLA regardless, so this is a
    transparent wrapper."""

    def __init__(self, program, build_strategy=None):
        self._program = program
        self._build_strategy = build_strategy or BuildStrategy()

    def __getattr__(self, name):
        return getattr(self._program, name)


class WeightNormParamAttr:
    """Reference WeightNormParamAttr reparameterizes w = g * v/||v||.
    The reparameterization pass is not implemented — constructing this
    raises so training silently-without-weight-norm cannot happen. Use
    paddle_tpu.nn.utils.weight_norm on the layer instead."""

    def __init__(self, *a, **k):
        raise NotImplementedError(
            "WeightNormParamAttr: use paddle_tpu.nn.utils.weight_norm "
            "(layer-level reparameterization) — the static-graph param-"
            "attr form is not implemented")


# -- program/artifact (de)serialization -------------------------------------

def normalize_program(program, feed_vars, fetch_vars, **kwargs):
    """Reference prunes the program to the feed→fetch subgraph; our
    Program records exactly the ops executed, so pruning happens at
    export (save_inference_model) — returns the program unchanged."""
    return program


def serialize_program(feed_vars, fetch_vars, **kwargs):
    from .program import default_main_program
    return pickle.dumps({"kind": "paddle_tpu.program",
                         "n_feeds": len(feed_vars)
                         if isinstance(feed_vars, (list, tuple)) else 1})


def deserialize_program(data):
    meta = pickle.loads(data)
    if meta.get("kind") != "paddle_tpu.program":
        raise ValueError("not a paddle_tpu serialized program")
    from .program import default_main_program
    return default_main_program()


def serialize_persistables(feed_vars, fetch_vars, executor=None, **kw):
    from .program import default_main_program
    prog = default_main_program()
    params = {f"p{i}": np.asarray(p._value)
              for i, p in enumerate(prog.parameters())} \
        if hasattr(prog, "parameters") else {}
    return pickle.dumps(params)


def deserialize_persistables(program, data, executor=None):
    params = pickle.loads(data)
    if hasattr(program, "parameters"):
        # numeric key order — lexicographic would scramble p10 before p2
        items = sorted(params.items(), key=lambda kv: int(kv[0][1:]))
        for p, (_, arr) in zip(program.parameters(), items):
            p._replace(jnp.asarray(arr))
    return program


def save_to_file(path, content: bytes):
    with open(path, "wb") as f:
        f.write(content)


def load_from_file(path) -> bytes:
    with open(path, "rb") as f:
        return f.read()


def save(program, model_path, protocol=4, **configs):
    """Save a program's parameter state (reference static.save →
    .pdparams/.pdopt)."""
    params = {}
    if hasattr(program, "parameters"):
        params = {i: np.asarray(p._value)
                  for i, p in enumerate(program.parameters())}
    with open(model_path + ".pdparams", "wb") as f:
        pickle.dump(params, f, protocol=protocol)


def load(program, model_path, executor=None, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        params = pickle.load(f)
    if hasattr(program, "parameters"):
        for i, p in enumerate(program.parameters()):
            if i in params:
                p._replace(jnp.asarray(params[i]))


def load_program_state(model_path, var_list=None):
    with open(model_path + ".pdparams", "rb") as f:
        return pickle.load(f)


def set_program_state(program, state_dict):
    if hasattr(program, "parameters"):
        for i, p in enumerate(program.parameters()):
            if i in state_dict:
                p._replace(jnp.asarray(state_dict[i]))


# -- descoped stubs ---------------------------------------------------------

def _no_ipu(*a, **k):
    raise NotImplementedError(
        "IPU APIs have no TPU analog (paddle_tpu targets TPU via XLA); "
        "see COVERAGE.md descopes")


class IpuStrategy:
    def __init__(self, *a, **k):
        _no_ipu()


class IpuCompiledProgram:
    def __init__(self, *a, **k):
        _no_ipu()


def ipu_shard_guard(*a, **k):
    _no_ipu()


def set_ipu_shard(*a, **k):
    _no_ipu()


def ctr_metric_bundle(*a, **k):
    raise NotImplementedError(
        "ctr_metric_bundle belongs to the parameter-server stack, "
        "descoped per COVERAGE.md; use paddle_tpu.metric.Auc for "
        "streaming AUC")
