"""Linear algebra ops (paddle.tensor.linalg parity,
/root/reference/python/paddle/tensor/linalg.py). matmul maps straight onto
the MXU; keep operands batched and let XLA tile."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply, apply_nodiff

__all__ = [
    "matmul", "mm", "bmm", "dot", "mv", "t", "transpose", "norm", "dist",
    "cross", "einsum", "trace", "kron", "multi_dot", "matrix_transpose",
    # linalg namespace
    "cholesky", "inv", "pinv", "det", "slogdet", "svd", "qr", "eigh",
    "eigvalsh", "solve", "triangular_solve", "lstsq", "matrix_power",
    "matrix_rank", "cond", "lu", "householder_product", "cov", "corrcoef",
]


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def f(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim > 1 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim > 1 else b
        return jnp.matmul(a, b)
    return apply("matmul", f, x, y)


def mm(input, mat2, name=None):
    return matmul(input, mat2)


def bmm(x, y, name=None):
    return apply("bmm", jnp.matmul, x, y)


def dot(x, y, name=None):
    return apply("dot", lambda a, b: jnp.sum(a * b, axis=-1), x, y)


def mv(x, vec, name=None):
    return apply("mv", jnp.matmul, x, vec)


def t(input, name=None):
    return apply("t", lambda a: a.T if a.ndim >= 2 else a, input)


def transpose(x, perm, name=None):
    return apply("transpose", lambda a: jnp.transpose(a, axes=perm), x)


def matrix_transpose(x, name=None):
    return apply("matrix_transpose", lambda a: jnp.swapaxes(a, -1, -2), x)


def norm(x, p=None, axis=None, keepdim=False, name=None):
    def f(a):
        if p is None or p == "fro":
            if axis is None:
                return jnp.sqrt(jnp.sum(jnp.square(a)))
            return jnp.linalg.norm(a, ord=None, axis=_ax(axis), keepdims=keepdim)
        if p == float("inf") or p == "inf":
            return jnp.max(jnp.abs(a), axis=_ax(axis), keepdims=keepdim)
        if p == float("-inf") or p == "-inf":
            return jnp.min(jnp.abs(a), axis=_ax(axis), keepdims=keepdim)
        if axis is None:
            flat = jnp.abs(a.reshape(-1))
            return jnp.power(jnp.sum(jnp.power(flat, p)), 1.0 / p)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=_ax(axis), keepdims=keepdim), 1.0 / p)
    return apply("norm", f, x)


def _ax(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


def dist(x, y, p=2, name=None):
    return apply("dist", lambda a, b: _pnorm(a - b, p), x, y)


def _pnorm(d, p):
    d = jnp.abs(d).reshape(-1)
    if p == 0:
        return jnp.sum((d != 0).astype(d.dtype))
    if p == float("inf"):
        return jnp.max(d)
    if p == float("-inf"):
        return jnp.min(d)
    return jnp.power(jnp.sum(jnp.power(d, p)), 1.0 / p)


def cross(x, y, axis=9, name=None):
    def f(a, b):
        ax = axis
        if ax == 9:
            ax = next(i for i, s in enumerate(a.shape) if s == 3)
        return jnp.cross(a, b, axis=ax)
    return apply("cross", f, x, y)


def einsum(equation, *operands):
    return apply("einsum", lambda *xs: jnp.einsum(equation, *xs), *operands)


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("trace", lambda a: jnp.trace(a, offset=offset, axis1=axis1, axis2=axis2), x)


def kron(x, y, name=None):
    return apply("kron", jnp.kron, x, y)


def multi_dot(x, name=None):
    return apply("multi_dot", lambda *xs: jnp.linalg.multi_dot(xs), *x)


def cholesky(x, upper=False, name=None):
    def f(a):
        l = jnp.linalg.cholesky(a)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply("cholesky", f, x)


def inv(x, name=None):
    return apply("inv", jnp.linalg.inv, x)


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply("pinv", lambda a: jnp.linalg.pinv(a, rcond=rcond, hermitian=hermitian), x)


def det(x, name=None):
    return apply("det", jnp.linalg.det, x)


def slogdet(x, name=None):
    def f(a):
        s, l = jnp.linalg.slogdet(a)
        return jnp.stack([s, l])
    return apply("slogdet", f, x)


def svd(x, full_matrices=False, name=None):
    return apply("svd", lambda a: tuple(jnp.linalg.svd(a, full_matrices=full_matrices)), x)


def qr(x, mode="reduced", name=None):
    return apply("qr", lambda a: tuple(jnp.linalg.qr(a, mode=mode)), x)


def eigh(x, UPLO="L", name=None):
    return apply("eigh", lambda a: tuple(jnp.linalg.eigh(a, UPLO=UPLO)), x)


def eigvalsh(x, UPLO="L", name=None):
    return apply("eigvalsh", lambda a: jnp.linalg.eigvalsh(a, UPLO=UPLO), x)


def solve(x, y, name=None):
    return apply("solve", jnp.linalg.solve, x, y)


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    def f(a, b):
        return jax.scipy.linalg.solve_triangular(
            a, b, lower=not upper, trans=1 if transpose else 0,
            unit_diagonal=unitriangular)
    return apply("triangular_solve", f, x, y)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def f(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return sol, res, rank, sv
    return apply_nodiff("lstsq", f, x, y)


def matrix_power(x, n, name=None):
    return apply("matrix_power", lambda a: jnp.linalg.matrix_power(a, n), x)


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_nodiff("matrix_rank", lambda a: jnp.linalg.matrix_rank(a, tol=tol), x)


def cond(x, p=None, name=None):
    return apply("cond", lambda a: jnp.linalg.cond(a, p=p), x)


def lu(x, pivot=True, get_infos=False, name=None):
    def f(a):
        lu_, piv = jax.scipy.linalg.lu_factor(a)
        return lu_, piv.astype(jnp.int32)
    outs = apply_nodiff("lu", f, x)
    if get_infos:
        z = Tensor(jnp.zeros((), jnp.int32))
        return outs[0], outs[1], z
    return outs


def householder_product(x, tau, name=None):
    def f(a, t):
        m, n = a.shape[-2], a.shape[-1]
        q = jnp.eye(m, dtype=a.dtype)
        for i in range(n):
            v = jnp.concatenate([jnp.zeros((i,), a.dtype), jnp.ones((1,), a.dtype), a[i + 1:, i]])
            q = q - t[i] * (q @ v[:, None]) @ v[None, :]
        return q
    return apply("householder_product", f, x, tau)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply("cov", lambda a: jnp.cov(a, rowvar=rowvar, ddof=1 if ddof else 0), x)


def corrcoef(x, rowvar=True, name=None):
    return apply("corrcoef", lambda a: jnp.corrcoef(a, rowvar=rowvar), x)


def cholesky_solve(x, y, upper=False, name=None):
    """Solve A X = B given the Cholesky factor of A (reference
    paddle.linalg.cholesky_solve; y is the factor, x the rhs)."""
    def f(b, c):
        return jax.scipy.linalg.cho_solve((c, not upper), b)
    return apply("cholesky_solve", f, x, y)


def matrix_exp(x, name=None):
    """Matrix exponential (reference paddle.linalg.matrix_exp)."""
    return apply("matrix_exp", jax.scipy.linalg.expm, x)


def _on_cpu(fn):
    """Run fn on the host CPU backend (general eig has no TPU lowering
    — the reference computes it on host LAPACK too)."""
    def wrapped(a):
        cpu = jax.devices("cpu")[0]
        with jax.default_device(cpu):
            return fn(jax.device_put(a, cpu))
    return wrapped


def eig(x, name=None):
    """General (non-symmetric) eigendecomposition (reference
    paddle.linalg.eig). Complex outputs; computed on the host CPU
    backend (no TPU lowering exists — same as the reference's LAPACK
    path)."""
    def f(a):
        return _on_cpu(jnp.linalg.eig)(a)
    return apply_nodiff("eig", f, x)


def eigvals(x, name=None):
    def f(a):
        return _on_cpu(jnp.linalg.eigvals)(a)
    return apply_nodiff("eigvals", f, x)


def lu_unpack(x, y, unpack_ludata=True, unpack_pivots=True, name=None):
    """Unpack lu()'s packed factorization into (P, L, U) (reference
    paddle.linalg.lu_unpack; y is the pivot vector). unpack_pivots
    gates P (and its permutation cost); unpack_ludata gates L/U."""
    def f(lu_, piv):
        outs = []
        m, n = lu_.shape[-2], lu_.shape[-1]
        if unpack_pivots:
            perm = jnp.arange(m)

            def body(i, p):
                j = piv[i]
                pi, pj = p[i], p[j]
                return p.at[i].set(pj).at[j].set(pi)
            perm = jax.lax.fori_loop(0, piv.shape[-1], body, perm)
            outs.append(jnp.eye(m, dtype=lu_.dtype)[perm].T)
        if unpack_ludata:
            k = min(m, n)
            outs.append(jnp.tril(lu_[..., :, :k], -1)
                        + jnp.eye(m, k, dtype=lu_.dtype))
            outs.append(jnp.triu(lu_[..., :k, :]))
        return tuple(outs)
    outs = apply_nodiff("lu_unpack", f, x, y)
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    it = iter(outs)
    p_out = next(it) if unpack_pivots else None
    l_out = next(it) if unpack_ludata else None
    u_out = next(it) if unpack_ludata else None
    return p_out, l_out, u_out
