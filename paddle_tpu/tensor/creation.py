"""Tensor creation ops (paddle.tensor.creation parity,
/root/reference/python/paddle/tensor/creation.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..decomposition.register import DecompAware
from ..framework.core import Tensor, apply, apply_nodiff, to_tensor

__all__ = [
    "to_tensor", "zeros", "ones", "full", "empty", "zeros_like", "ones_like",
    "full_like", "empty_like", "arange", "linspace", "logspace", "eye",
    "diag", "diagflat", "tril", "triu", "meshgrid", "assign", "clone",
    "tril_indices", "triu_indices", "complex",
]


def _d(dtype, default=None):
    if dtype is None:
        return default if default is not None else dtypes.get_default_dtype()
    return dtypes.convert_dtype(dtype)


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), _d(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), _d(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value._value
    if dtype is None:
        arr = jnp.full(_shape(shape), fill_value)
        if arr.dtype == jnp.float64:
            arr = arr.astype(dtypes.get_default_dtype())
        return Tensor(arr)
    return Tensor(jnp.full(_shape(shape), fill_value, _d(dtype)))


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def zeros_like(x, dtype=None, name=None):
    return apply_nodiff("zeros_like", lambda a: jnp.zeros_like(a, dtype=_d(dtype, np.dtype(x.dtype))), x)


def ones_like(x, dtype=None, name=None):
    return apply_nodiff("ones_like", lambda a: jnp.ones_like(a, dtype=_d(dtype, np.dtype(x.dtype))), x)


def full_like(x, fill_value, dtype=None, name=None):
    d = _d(dtype, np.dtype(x.dtype))
    return apply_nodiff("full_like", DecompAware(
        "full_like", lambda a: jnp.full_like(a, fill_value, dtype=d),
        fill_value=fill_value, dtype=d), x)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def _v(v):
        return v._value.item() if isinstance(v, Tensor) else v
    start, end, step = _v(start), _v(end), _v(step)
    if end is None:
        start, end = 0, start
    if dtype is None:
        if all(isinstance(v, (int, np.integer)) for v in (start, end, step)):
            dtype = jnp.int64
        else:
            dtype = dtypes.get_default_dtype()
    return Tensor(jnp.arange(start, end, step, dtype=dtypes.convert_dtype(dtype)))


def linspace(start, stop, num, dtype=None, name=None):
    def _v(v):
        return v._value.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(_v(start), _v(stop), int(_v(num)),
                               dtype=_d(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def _v(v):
        return v._value.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.logspace(_v(start), _v(stop), int(_v(num)), base=_v(base),
                               dtype=_d(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(num_rows, num_columns, dtype=_d(dtype)))


def diag(x, offset=0, padding_value=0, name=None):
    def f(a):
        if a.ndim == 1 and padding_value != 0:
            n = a.shape[0] + abs(offset)
            base = jnp.full((n, n), padding_value, a.dtype)
            return base + jnp.diag(a - jnp.zeros((), a.dtype), k=offset) - jnp.diag(jnp.full(a.shape, padding_value, a.dtype), k=offset)
        return jnp.diag(a, k=offset)
    return apply("diag", f, x)


def diagflat(x, offset=0, name=None):
    return apply("diagflat", lambda a: jnp.diagflat(a, k=offset), x)


def tril(x, diagonal=0, name=None):
    return apply("tril", lambda a: jnp.tril(a, k=diagonal), x)


def triu(x, diagonal=0, name=None):
    return apply("triu", lambda a: jnp.triu(a, k=diagonal), x)


def tril_indices(row, col=None, offset=0, dtype="int64"):
    r = jnp.tril_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r[0], r[1]]).astype(dtypes.convert_dtype(dtype)))


def triu_indices(row, col=None, offset=0, dtype="int64"):
    r = jnp.triu_indices(row, k=offset, m=col)
    return Tensor(jnp.stack([r[0], r[1]]).astype(dtypes.convert_dtype(dtype)))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = tuple(args[0])
    outs = apply("meshgrid", lambda *xs: tuple(jnp.meshgrid(*xs, indexing="ij")), *args)
    return list(outs)


def assign(x, output=None):
    src = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    if output is None:
        return Tensor(src)
    output.set_value(src)
    return output


def clone(x, name=None):
    return apply("clone", lambda a: a + jnp.zeros((), a.dtype), x)


def complex(real, imag, name=None):
    return apply("complex", jax.lax.complex, real, imag)
