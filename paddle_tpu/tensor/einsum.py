"""paddle.einsum parity (/root/reference/python/paddle/tensor/einsum.py) —
delegates to jnp.einsum, which XLA lowers to MXU-shaped dots."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import apply

__all__ = ["einsum"]


def einsum(equation, *operands):
    if len(operands) == 1 and isinstance(operands[0], (list, tuple)):
        operands = tuple(operands[0])
    return apply("einsum", lambda *xs: jnp.einsum(equation, *xs), *operands)
