"""Comparison / logical ops (paddle.tensor.logic parity,
/root/reference/python/paddle/tensor/logic.py)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply_nodiff

__all__ = [
    "equal", "not_equal", "less_than", "less_equal", "greater_than",
    "greater_equal", "logical_and", "logical_or", "logical_not",
    "logical_xor", "bitwise_and", "bitwise_or", "bitwise_not", "bitwise_xor",
    "bitwise_left_shift", "bitwise_right_shift", "allclose", "isclose",
    "equal_all", "is_empty", "is_tensor",
]


def _cmp(op_name, fn):
    def op(x, y, name=None):  # `name` = paddle output-name arg
        return apply_nodiff(op_name, fn, x, y)
    op.__name__ = op_name
    return op


equal = _cmp("equal", jnp.equal)
not_equal = _cmp("not_equal", jnp.not_equal)
less_than = _cmp("less_than", jnp.less)
less_equal = _cmp("less_equal", jnp.less_equal)
greater_than = _cmp("greater_than", jnp.greater)
greater_equal = _cmp("greater_equal", jnp.greater_equal)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)
bitwise_left_shift = _cmp("bitwise_left_shift", jnp.left_shift)
bitwise_right_shift = _cmp("bitwise_right_shift", jnp.right_shift)


def logical_not(x, out=None, name=None):
    return apply_nodiff("logical_not", jnp.logical_not, x)


def bitwise_not(x, out=None, name=None):
    return apply_nodiff("bitwise_not", jnp.bitwise_not, x)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_nodiff("allclose",
                        lambda a, b: jnp.allclose(a, b, rtol=rtol, atol=atol,
                                                  equal_nan=equal_nan), x, y)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_nodiff("isclose",
                        lambda a, b: jnp.isclose(a, b, rtol=rtol, atol=atol,
                                                 equal_nan=equal_nan), x, y)


def equal_all(x, y, name=None):
    return apply_nodiff("equal_all",
                        lambda a, b: jnp.array_equal(a, b), x, y)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(x.size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)
