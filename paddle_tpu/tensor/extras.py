"""Long-tail tensor ops completing paddle.tensor parity
(/root/reference/python/paddle/tensor/: math.py, linalg.py,
manipulation.py entries not covered by the main modules). Same dispatch
contract as everything else: pure jnp/lax compositions on the tape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..decomposition.register import DecompAware
from ..framework.core import Tensor, apply, apply_nodiff, default_generator

__all__ = [
    "add_n", "as_complex", "as_real", "broadcast_shape", "cast",
    "cholesky_solve", "combinations", "copysign", "cumulative_trapezoid",
    "diag_embed", "diagonal", "diagonal_scatter", "eig", "eigvals",
    "floor_mod", "frexp", "gammaln", "hypot", "i0", "i0e", "i1", "i1e",
    "index_fill", "index_sample", "inverse", "ldexp", "logaddexp",
    "logcumsumexp", "lu_unpack", "multigammaln", "nextafter", "polar",
    "polygamma", "renorm", "reverse", "select_scatter", "sgn", "signbit",
    "slice_scatter", "unflatten", "vander", "top_p_sampling",
]


def add_n(inputs, name=None):
    """Sum of a tensor list (reference math.py add_n)."""
    if isinstance(inputs, Tensor):
        return apply("add_n", lambda a: a, inputs)
    return apply("add_n", DecompAware(
        "add_n", lambda *xs: sum(xs[1:], xs[0])), *inputs)


def as_complex(x, name=None):
    return apply("as_complex",
                 lambda a: jax.lax.complex(a[..., 0], a[..., 1]), x)


def as_real(x, name=None):
    return apply("as_real",
                 lambda a: jnp.stack([jnp.real(a), jnp.imag(a)], -1), x)


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


def cast(x, dtype):
    d = dtypes.convert_dtype(dtype)
    return apply("cast", lambda a: a.astype(d), x)


def cholesky_solve(x, y, upper=False, name=None):
    """Solve A @ out = x given y = chol(A) (reference linalg)."""
    def f(b, L):
        return jax.scipy.linalg.cho_solve((L, not upper), b)
    return apply("cholesky_solve", f, x, y)


def combinations(x, r: int = 2, with_replacement: bool = False, name=None):
    import itertools
    n = x.shape[0]
    gen = itertools.combinations_with_replacement if with_replacement \
        else itertools.combinations
    idx = np.asarray(list(gen(range(n), r)), np.int32).reshape(-1, r)
    return apply("combinations", lambda a: a[jnp.asarray(idx)], x)


def copysign(x, y, name=None):
    return apply("copysign", jnp.copysign, x, y)


def cumulative_trapezoid(y, x=None, dx=None, axis=-1, name=None):
    def f(ya, *rest):
        if rest:
            xa = rest[0]
            d = jnp.diff(xa, axis=axis)
        else:
            d = dx if dx is not None else 1.0
        yl = jax.lax.slice_in_dim(ya, 0, ya.shape[axis] - 1, axis=axis)
        yr = jax.lax.slice_in_dim(ya, 1, ya.shape[axis], axis=axis)
        return jnp.cumsum((yl + yr) * d / 2.0, axis=axis)
    args = (y,) + ((x,) if x is not None else ())
    return apply("cumulative_trapezoid", f, *args)


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):
    def f(a):
        n = a.shape[-1] + abs(offset)
        out = jnp.zeros(a.shape[:-1] + (n, n), a.dtype)
        idx = jnp.arange(a.shape[-1])
        r = idx + max(-offset, 0)
        c = idx + max(offset, 0)
        out = out.at[..., r, c].set(a)
        # move the two new dims to dim1/dim2
        nd = out.ndim
        d1 = dim1 % nd
        d2 = dim2 % nd
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for pos, src in order:
            perm.insert(pos, src)
        return jnp.transpose(out, perm)
    return apply("diag_embed", f, input)


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    return apply("diagonal",
                 lambda a: jnp.diagonal(a, offset, axis1, axis2), x)


def diagonal_scatter(x, y, offset=0, axis1=0, axis2=1, name=None):
    def f(a, b):
        k = b.shape[-1]
        i = jnp.arange(k) + max(-offset, 0)
        j = jnp.arange(k) + max(offset, 0)
        am = jnp.moveaxis(a, (axis1, axis2), (0, 1))
        bm = jnp.moveaxis(b, -1, 0)
        am = am.at[i, j].set(bm)
        return jnp.moveaxis(am, (0, 1), (axis1, axis2))
    return apply("diagonal_scatter", f, x, y)


def eig(x, name=None):
    """General eigendecomposition — CPU-only in XLA; computed on host
    (the reference's eig is CPU-only too)."""
    arr = np.asarray(jax.device_get(
        x._value if isinstance(x, Tensor) else x))
    w, v = np.linalg.eig(arr)
    return Tensor(jnp.asarray(w)), Tensor(jnp.asarray(v))


def eigvals(x, name=None):
    arr = np.asarray(jax.device_get(
        x._value if isinstance(x, Tensor) else x))
    return Tensor(jnp.asarray(np.linalg.eigvals(arr)))


from .math import mod as floor_mod  # noqa: E402 — reference alias


def frexp(x, name=None):
    def f(a):
        mm, ee = jnp.frexp(a)
        return mm, ee.astype(jnp.int32)
    return apply("frexp", f, x)


def gammaln(x, name=None):
    return apply("gammaln", jax.scipy.special.gammaln, x)


def hypot(x, y, name=None):
    return apply("hypot", jnp.hypot, x, y)


def i0(x, name=None):
    return apply("i0", jax.scipy.special.i0, x)


def i0e(x, name=None):
    return apply("i0e", jax.scipy.special.i0e, x)


def i1(x, name=None):
    return apply("i1", jax.scipy.special.i1, x)


def i1e(x, name=None):
    return apply("i1e", jax.scipy.special.i1e, x)


def index_fill(x, index, axis, value, name=None):
    def f(a, idx):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[idx].set(value)
        return jnp.moveaxis(moved, 0, axis)
    return apply("index_fill", f, x, index)


def index_sample(x, index):
    """Per-row gather: out[i, j] = x[i, index[i, j]] (reference
    index_sample)."""
    return apply("index_sample",
                 lambda a, idx: jnp.take_along_axis(a, idx, axis=1),
                 x, index)


def inverse(x, name=None):
    return apply("inverse", jnp.linalg.inv, x)


def ldexp(x, y, name=None):
    return apply("ldexp", lambda a, b: jnp.ldexp(a, b.astype(jnp.int32)),
                 x, y)


def logaddexp(x, y, name=None):
    return apply("logaddexp", jnp.logaddexp, x, y)


def logcumsumexp(x, axis=None, name=None):
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        else:
            ax = axis % a.ndim
        # numerically stable prefix logsumexp as an associative scan
        return jax.lax.associative_scan(jnp.logaddexp, a, axis=ax)
    return apply("logcumsumexp", f, x)


def lu_unpack(lu_data, lu_pivots, unpack_ludata=True, unpack_pivots=True,
              name=None):
    L = U = P = None
    if unpack_ludata:
        def f_lu(lu):
            n = lu.shape[-2]
            L_ = jnp.tril(lu, -1) + jnp.eye(n, lu.shape[-1],
                                            dtype=lu.dtype)
            return L_, jnp.triu(lu)
        L, U = apply_nodiff("lu_unpack_lu", f_lu, lu_data)
    if unpack_pivots:
        def f_p(lu, piv):
            n = lu.shape[-2]
            # pivots (1-based sequential swaps) → permutation matrices,
            # batched over every leading dim
            pv = np.asarray(jax.device_get(piv)).reshape(
                -1, piv.shape[-1])
            perms = []
            for row in pv:
                perm = np.arange(n)
                for i, p in enumerate(row[:n]):
                    j = int(p) - 1
                    perm[[i, j]] = perm[[j, i]]
                perms.append(np.eye(n)[perm].T)
            return jnp.asarray(np.stack(perms).reshape(
                piv.shape[:-1] + (n, n)), lu.dtype)
        P = apply_nodiff("lu_unpack_pivots", f_p, lu_data, lu_pivots)
    return P, L, U


def multigammaln(x, p, name=None):
    return apply("multigammaln",
                 lambda a: jax.scipy.special.multigammaln(a, p), x)


def nextafter(x, y, name=None):
    return apply_nodiff("nextafter", jnp.nextafter, x, y)


def polar(abs, angle, name=None):
    return apply("polar",
                 lambda r, t: jax.lax.complex(r * jnp.cos(t),
                                              r * jnp.sin(t)),
                 abs, angle)


def polygamma(x, n, name=None):
    return apply("polygamma",
                 lambda a: jax.scipy.special.polygamma(n, a), x)


def renorm(x, p, axis, max_norm, name=None):
    def f(a):
        moved = jnp.moveaxis(a, axis, 0)
        flat = moved.reshape(moved.shape[0], -1)
        norms = jnp.linalg.norm(flat, ord=p, axis=1)
        scale = jnp.where(norms > max_norm,
                          max_norm / jnp.maximum(norms, 1e-12), 1.0)
        out = flat * scale[:, None]
        return jnp.moveaxis(out.reshape(moved.shape), 0, axis)
    return apply("renorm", f, x)


from .manipulation import flip as reverse  # noqa: E402 — reference alias


def select_scatter(x, values, axis, index, name=None):
    def f(a, v):
        moved = jnp.moveaxis(a, axis, 0)
        moved = moved.at[index].set(v)
        return jnp.moveaxis(moved, 0, axis)
    return apply("select_scatter", f, x, values)


def sgn(x, name=None):
    """sign for real; x/|x| for complex (reference sgn)."""
    def f(a):
        if jnp.issubdtype(a.dtype, jnp.complexfloating):
            mag = jnp.abs(a)
            return jnp.where(mag == 0, 0, a / jnp.maximum(mag, 1e-300))
        return jnp.sign(a)
    return apply("sgn", f, x)


def signbit(x, name=None):
    return apply_nodiff("signbit", jnp.signbit, x)


def slice_scatter(x, value, axes, starts, ends, strides, name=None):
    def f(a, v):
        idx = [slice(None)] * a.ndim
        for ax, s, e, st in zip(axes, starts, ends, strides):
            idx[ax] = slice(s, e, st)
        return a.at[tuple(idx)].set(v)
    return apply("slice_scatter", f, x, value)


def unflatten(x, axis, shape, name=None):
    def f(a):
        ax = axis % a.ndim
        new = list(a.shape[:ax]) + list(shape) + list(a.shape[ax + 1:])
        return a.reshape(new)
    return apply("unflatten", f, x)


def vander(x, n=None, increasing=False, name=None):
    def f(a):
        return jnp.vander(a, n, increasing=increasing)
    return apply("vander", f, x)


def top_p_sampling(x, ps, threshold=None, seed=None, name=None):
    """Nucleus sampling over the last axis (reference top_p_sampling):
    returns (sampled values, sampled ids). seed fixes the draw;
    threshold additionally drops tokens whose probability is below it."""
    def f(logits, p):
        key = jax.random.PRNGKey(seed) if seed is not None \
            else default_generator.next_key()
        sorted_idx = jnp.argsort(-logits, axis=-1)
        sorted_logits = jnp.take_along_axis(logits, sorted_idx, -1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep = cum - probs < p[..., None]  # always keep the top token
        if threshold is not None:
            keep = keep & (probs >= threshold)
            keep = keep.at[..., 0].set(True)  # never drop every token
        masked = jnp.where(keep, sorted_logits, -jnp.inf)
        choice = jax.random.categorical(key, masked, axis=-1)
        ids = jnp.take_along_axis(sorted_idx, choice[..., None], -1)
        vals = jnp.take_along_axis(logits, ids, -1)
        return vals, ids.astype(jnp.int64)
    return apply_nodiff("top_p_sampling", f, x, ps)
