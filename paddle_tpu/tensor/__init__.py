"""paddle_tpu.tensor — op namespace + Tensor method/operator patching.

Mirrors the reference's monkey-patch approach
(/root/reference/python/paddle/base/dygraph/math_op_patch.py:60 and
tensor_patch_methods.py:78): every public op is also installed as a Tensor
method, and Python operators route through the autograd-aware dispatcher.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, Parameter, apply, apply_nodiff, to_tensor

from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .linalg import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .random import *  # noqa: F401,F403
from .logic import *  # noqa: F401,F403
from .stat import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403

from . import (creation, math, linalg, manipulation, random, logic, stat,
               extras)

from .einsum import einsum  # noqa: F401  (overrides linalg.einsum alias)


def is_floating_point(x):
    from ..framework import dtype as dtypes
    return dtypes.is_floating_point(x.dtype)


def is_integer(x):
    from ..framework import dtype as dtypes
    return dtypes.is_integer(x.dtype)


def is_complex(x):
    from ..framework import dtype as dtypes
    return dtypes.is_complex(x.dtype)


def rank(input):
    return Tensor(jnp.asarray(input.ndim))


def shape(input):
    return Tensor(jnp.asarray(input.shape, dtype=jnp.int32))


def numel(x, name=None):
    return stat.numel(x)


# ---------------------------------------------------------------------------
# Operator overloads
# ---------------------------------------------------------------------------

def _unwrap_index(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(_unwrap_index(i) for i in idx)
    if isinstance(idx, list):
        return [_unwrap_index(i) for i in idx]
    return idx


def _getitem(self, idx):
    uidx = _unwrap_index(idx)
    return apply("getitem", lambda a: a[uidx], self)


def _setitem(self, idx, value):
    uidx = _unwrap_index(idx)
    if isinstance(value, Tensor):
        out = apply("setitem", lambda a, v: a.at[uidx].set(v.astype(a.dtype)), self, value)
    else:
        out = apply("setitem", lambda a: a.at[uidx].set(value), self)
    self._value = out._value
    self._node = out._node
    self._out_idx = out._out_idx
    self.stop_gradient = out.stop_gradient


_BINOPS = {
    "__add__": math.add,
    "__radd__": lambda x, y: math.add(x, y),
    "__sub__": math.subtract,
    "__rsub__": lambda x, y: apply("rsub", lambda a: y - a if not isinstance(y, Tensor) else None, x)
        if not isinstance(y, Tensor) else math.subtract(y, x),
    "__mul__": math.multiply,
    "__rmul__": lambda x, y: math.multiply(x, y),
    "__truediv__": math.divide,
    "__rdiv__": None,
    "__floordiv__": math.floor_divide,
    "__mod__": math.mod,
    "__pow__": math.pow,
    "__matmul__": linalg.matmul,
}


def _install_operators():
    T = Tensor
    T.__add__ = lambda s, o: math.add(s, o)
    T.__radd__ = lambda s, o: math.add(s, o)
    T.__sub__ = lambda s, o: math.subtract(s, o)
    T.__rsub__ = lambda s, o: apply("rsub", lambda a: jnp.subtract(o._value if isinstance(o, Tensor) else o, a), s)
    T.__mul__ = lambda s, o: math.multiply(s, o)
    T.__rmul__ = lambda s, o: math.multiply(s, o)
    T.__truediv__ = lambda s, o: math.divide(s, o)
    T.__rtruediv__ = lambda s, o: apply("rdiv", lambda a: jnp.divide(o._value if isinstance(o, Tensor) else o, a), s)
    T.__floordiv__ = lambda s, o: math.floor_divide(s, o)
    T.__rfloordiv__ = lambda s, o: apply_nodiff("rfloordiv", lambda a: jnp.floor_divide(o._value if isinstance(o, Tensor) else o, a), s)
    T.__mod__ = lambda s, o: math.mod(s, o)
    T.__pow__ = lambda s, o: math.pow(s, o)
    T.__rpow__ = lambda s, o: apply("rpow", lambda a: jnp.power(o._value if isinstance(o, Tensor) else o, a), s)
    T.__matmul__ = lambda s, o: linalg.matmul(s, o)
    T.__rmatmul__ = lambda s, o: linalg.matmul(o, s) if isinstance(o, Tensor) else apply("rmatmul", lambda a: jnp.matmul(o, a), s)
    T.__neg__ = lambda s: math.neg(s)
    T.__abs__ = lambda s: math.abs(s)
    T.__invert__ = lambda s: logic.logical_not(s)
    T.__eq__ = lambda s, o: logic.equal(s, o)
    T.__ne__ = lambda s, o: logic.not_equal(s, o)
    T.__lt__ = lambda s, o: logic.less_than(s, o)
    T.__le__ = lambda s, o: logic.less_equal(s, o)
    T.__gt__ = lambda s, o: logic.greater_than(s, o)
    T.__ge__ = lambda s, o: logic.greater_equal(s, o)
    T.__and__ = lambda s, o: logic.logical_and(s, o) if s.dtype == np.bool_ else logic.bitwise_and(s, o)
    T.__or__ = lambda s, o: logic.logical_or(s, o) if s.dtype == np.bool_ else logic.bitwise_or(s, o)
    T.__xor__ = lambda s, o: logic.logical_xor(s, o) if s.dtype == np.bool_ else logic.bitwise_xor(s, o)
    T.__getitem__ = _getitem
    T.__setitem__ = _setitem


_NO_PATCH = {"to_tensor", "is_tensor", "shape", "rand", "randn", "randint",
             "randperm", "zeros", "ones", "full", "empty", "eye", "arange",
             "linspace", "logspace", "meshgrid", "einsum", "tril_indices",
             "triu_indices", "scatter_nd", "complex"}


def _install_methods():
    import inspect
    mods = [creation, math, linalg, manipulation, random, logic, stat,
            extras]
    for mod in mods:
        for name in getattr(mod, "__all__", []):
            if name in _NO_PATCH:
                continue
            fn = getattr(mod, name)
            if not callable(fn):
                continue
            if getattr(Tensor, name, None) is None or name in ("abs", "t"):
                Tensor._register_method(name, fn)
    # extra conveniences
    Tensor._register_method("is_floating_point", is_floating_point)
    Tensor._register_method("is_integer", is_integer)
    Tensor._register_method("is_complex", is_complex)
    Tensor._register_method("dim", lambda s: s.ndim)
    Tensor._register_method("rank", lambda s: rank(s))
    Tensor._register_method("numel", lambda s: stat.numel(s))
    Tensor._register_method("mm", linalg.mm)
    Tensor._register_method("dot", linalg.dot)


_install_operators()
_install_methods()


# ---------------------------------------------------------------------------
# in-place variants (reference: python/paddle/tensor/__init__.py
# tensor_method_func's trailing-underscore entries). Functionally the
# out-of-place op + a write-back into the SAME Tensor (and its method
# form returns self, paddle-style chaining). Autograd note: like the
# reference, in-place writes on leaves that already require grad don't
# rewrite history — the write-back targets the Tensor's VALUE only.
# ---------------------------------------------------------------------------

_INPLACE_NAMES = [
    "abs_", "acos_", "acosh_", "addmm_", "asin_", "asinh_", "atan_",
    "atanh_", "bitwise_and_", "bitwise_left_shift_", "bitwise_not_",
    "bitwise_or_", "bitwise_right_shift_", "bitwise_xor_", "cast_",
    "ceil_", "clip_", "copysign_", "cos_", "cosh_", "cumprod_",
    "cumsum_", "digamma_", "divide_", "equal_", "erfinv_", "exp_",
    "flatten_", "floor_", "floor_divide_", "floor_mod_", "frac_",
    "gammaln_", "gcd_", "greater_equal_", "greater_than_", "hypot_",
    "i0_", "index_add_", "index_fill_", "index_put_", "lcm_", "ldexp_",
    "lerp_", "less_equal_", "less_than_", "lgamma_", "log10_", "log1p_",
    "log2_", "log_", "logical_and_", "logical_not_", "logical_or_",
    "logical_xor_", "logit_", "masked_fill_", "masked_scatter_", "mod_",
    "multigammaln_", "nan_to_num_", "neg_", "not_equal_", "polygamma_",
    "pow_", "put_along_axis_", "reciprocal_", "remainder_", "renorm_",
    "round_", "rsqrt_", "scale_", "scatter_", "sigmoid_", "sin_",
    "sinh_", "sqrt_", "t_", "tan_", "tanh_", "transpose_", "tril_",
    "triu_", "trunc_", "where_", "erf_", "expm1_", "square_",
]


def _make_inplace(base_fn, name):
    def _inplace(x, *args, **kwargs):
        out = base_fn(x, *args, **kwargs)
        x._replace(out._value if isinstance(out, Tensor) else out)
        return x
    _inplace.__name__ = name
    _inplace.__qualname__ = name
    _inplace.__doc__ = (f"In-place variant of `{name[:-1]}` (reference "
                        f"paddle.{name}): computes out-of-place, writes "
                        "the result back into x, returns x.")
    return _inplace


def _install_inplace():
    g = globals()
    for name in _INPLACE_NAMES:
        base = g.get(name[:-1])
        if base is None or name in g:
            continue
        fn = _make_inplace(base, name)
        g[name] = fn
        Tensor._register_method(name, fn)


def cauchy_(x, loc=0, scale=1, name=None):
    """Fill x in place with Cauchy(loc, scale) samples (reference
    paddle.Tensor.cauchy_)."""
    from ..framework.core import default_generator
    import jax
    key = default_generator.next_key()
    u = jax.random.uniform(key, x.shape, jnp.float32, 1e-7, 1 - 1e-7)
    vals = loc + scale * jnp.tan(jnp.pi * (u - 0.5))
    x._replace(vals.astype(x._value.dtype))
    return x


def geometric_(x, probs, name=None):
    """Fill x in place with Geometric(probs) samples (reference
    paddle.Tensor.geometric_)."""
    from ..framework.core import default_generator
    import jax
    key = default_generator.next_key()
    u = jax.random.uniform(key, x.shape, jnp.float32, 1e-7, 1 - 1e-7)
    vals = jnp.ceil(jnp.log(u) / jnp.log1p(-jnp.asarray(probs,
                                                        jnp.float32)))
    x._replace(vals.astype(x._value.dtype))
    return x


def create_tensor(dtype, name=None, persistable=False):
    """Reference paddle.create_tensor: an empty (0-size) typed tensor."""
    from ..framework.dtype import convert_dtype
    return Tensor(jnp.zeros((0,), convert_dtype(dtype)))


def create_parameter(shape, dtype, name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """Reference paddle.create_parameter."""
    import jax
    from ..framework.core import default_generator
    from ..framework.dtype import convert_dtype
    jdt = convert_dtype(dtype)
    if default_initializer is not None:
        t = Parameter(jnp.zeros(shape, jdt))
        default_initializer(t)
        return t
    if is_bias:
        return Parameter(jnp.zeros(shape, jdt))
    key = default_generator.next_key()
    fan_in = shape[0] if shape else 1
    # NB: builtins.max — the module-level `max` is the tensor reduction
    import builtins
    bound = float(np.sqrt(6.0 / builtins.max(1, fan_in)))
    return Parameter(jax.random.uniform(key, tuple(shape), jdt,
                                        -bound, bound))


def pca_lowrank(x, q=None, center=True, niter=2, name=None):
    """Randomized PCA (reference paddle.linalg.pca_lowrank): returns
    (U, S, V) with V's columns the principal directions."""
    a = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    m, n = a.shape[-2], a.shape[-1]
    if q is None:
        q = min(6, m, n)
    if center:
        a = a - a.mean(axis=-2, keepdims=True)
    u, s, vt = jnp.linalg.svd(a, full_matrices=False)
    return (Tensor(u[..., :q]), Tensor(s[..., :q]),
            Tensor(jnp.swapaxes(vt, -1, -2)[..., :q]))


_install_inplace()
Tensor._register_method("cauchy_", cauchy_)
Tensor._register_method("geometric_", geometric_)


# signal-processing methods (reference exposes these as Tensor methods)
def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False,
         onesided=True, name=None):
    from ..signal import stft as _stft
    return _stft(x, n_fft, hop_length, win_length, window, center,
                 pad_mode, normalized, onesided, name)


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    from ..signal import istft as _istft
    return _istft(x, n_fft, hop_length, win_length, window, center,
                  normalized, onesided, length, return_complex, name)


Tensor._register_method("stft", stft)
Tensor._register_method("istft", istft)
