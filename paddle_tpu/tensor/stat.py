"""Statistics ops (paddle.tensor.stat parity,
/root/reference/python/paddle/tensor/stat.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import apply, apply_nodiff

__all__ = ["mean", "std", "var", "numel", "histogram", "histogramdd", "bincount"]

from .math import mean  # shared


def _axis(axis):
    if isinstance(axis, (list, tuple)):
        return tuple(axis)
    return axis


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("std", lambda a: jnp.std(a, axis=_axis(axis),
                                          ddof=1 if unbiased else 0,
                                          keepdims=keepdim), x)


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    return apply("var", lambda a: jnp.var(a, axis=_axis(axis),
                                          ddof=1 if unbiased else 0,
                                          keepdims=keepdim), x)


def numel(x, name=None):
    return apply_nodiff("numel", lambda a: jnp.asarray(a.size), x)


def histogram(input, bins=100, min=0, max=0, weight=None, density=False, name=None):
    def f(a):
        lo, hi = (min, max) if (min != 0 or max != 0) else (a.min(), a.max())
        h, _ = jnp.histogram(a.reshape(-1), bins=bins, range=(lo, hi),
                             density=density)
        return h if density else h.astype(jnp.int64)
    return apply_nodiff("histogram", f, input)


def histogramdd(x, bins=10, ranges=None, density=False, weights=None, name=None):
    def f(a):
        h, edges = jnp.histogramdd(a, bins=bins, range=ranges, density=density)
        return (h,) + tuple(edges)
    outs = apply_nodiff("histogramdd", f, x)
    return outs[0], list(outs[1:])


def bincount(x, weights=None, minlength=0, name=None):
    if weights is not None:
        return apply_nodiff("bincount",
                            lambda a, w: jnp.bincount(a, weights=w, minlength=minlength),
                            x, weights)
    return apply_nodiff("bincount",
                        lambda a: jnp.bincount(a, minlength=minlength), x)
