"""Random ops over the global Generator's threaded PRNG key stream
(paddle.tensor.random parity, /root/reference/python/paddle/tensor/random.py).
Inside jit.TrainStep these draw from a traced base key (see
framework.core.with_rng_key), keeping compiled steps pure."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, default_generator, apply_nodiff

__all__ = [
    "rand", "randn", "randint", "randint_like", "randperm", "uniform",
    "uniform_", "normal", "normal_", "standard_normal", "bernoulli",
    "multinomial", "poisson", "exponential_", "rand_like", "randn_like",
    "binomial", "standard_gamma",
]


def _d(dtype):
    return dtypes.convert_dtype(dtype) if dtype is not None else dtypes.get_default_dtype()


def _shape(shape):
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in np.asarray(shape._value))
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    out = []
    for s in shape:
        out.append(int(np.asarray(s._value)) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def rand(shape, dtype=None, name=None):
    key = default_generator.next_key()
    return Tensor(jax.random.uniform(key, _shape(shape), _d(dtype)))


def randn(shape, dtype=None, name=None):
    key = default_generator.next_key()
    return Tensor(jax.random.normal(key, _shape(shape), _d(dtype)))


standard_normal = randn


def randint(low=0, high=None, shape=(1,), dtype="int64", name=None):
    if high is None:
        low, high = 0, low
    key = default_generator.next_key()
    return Tensor(jax.random.randint(key, _shape(shape), low, high,
                                     dtypes.convert_dtype(dtype)))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    d = dtype if dtype is not None else x.dtype
    return randint(low, high, tuple(x.shape), d)


def randperm(n, dtype="int64", name=None):
    key = default_generator.next_key()
    return Tensor(jax.random.permutation(key, n).astype(dtypes.convert_dtype(dtype)))


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    key = jax.random.PRNGKey(seed) if seed else default_generator.next_key()
    d = _d(dtype)
    return Tensor(jax.random.uniform(key, _shape(shape), d,
                                     jnp.asarray(min, d), jnp.asarray(max, d)))


def uniform_(x, min=-1.0, max=1.0, seed=0, name=None):
    out = uniform(tuple(x.shape), x.dtype, min, max, seed)
    x._replace(out._value)
    return x


def normal(mean=0.0, std=1.0, shape=None, name=None):
    key = default_generator.next_key()
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m = mean._value if isinstance(mean, Tensor) else mean
        s = std._value if isinstance(std, Tensor) else std
        shp = jnp.broadcast_shapes(jnp.shape(m), jnp.shape(s))
        eps = jax.random.normal(key, shp, dtypes.get_default_dtype())
        return Tensor(m + s * eps)
    shp = _shape(shape) if shape is not None else ()
    eps = jax.random.normal(key, shp, dtypes.get_default_dtype())
    return Tensor(mean + std * eps)


def normal_(x, mean=0.0, std=1.0, name=None):
    key = default_generator.next_key()
    eps = jax.random.normal(key, tuple(x.shape), np.dtype(x.dtype)
                            if dtypes.is_floating_point(x.dtype) else jnp.float32)
    x._replace((mean + std * eps).astype(np.dtype(x.dtype)))
    return x


def rand_like(x, dtype=None, name=None):
    return rand(tuple(x.shape), dtype if dtype is not None else x.dtype)


def randn_like(x, dtype=None, name=None):
    return randn(tuple(x.shape), dtype if dtype is not None else x.dtype)


def bernoulli(x, name=None):
    # key rides as a positional arg, not a closure cell: the partial-
    # capture segment cache fingerprints closures by cell CONTENT, so a
    # captured per-call key would force a retrace every call (FC203)
    key = default_generator.next_key()
    return apply_nodiff("bernoulli",
                        lambda p, k: jax.random.bernoulli(k, p).astype(p.dtype),
                        x, key)


def multinomial(x, num_samples=1, replacement=False, name=None):
    key = default_generator.next_key()
    def f(p, k):
        if p.ndim == 1:
            return jax.random.choice(k, p.shape[-1], (num_samples,),
                                     replace=replacement, p=p / p.sum()).astype(jnp.int64)
        ks = jax.random.split(k, p.shape[0])
        return jax.vmap(lambda k_, pr: jax.random.choice(
            k_, p.shape[-1], (num_samples,), replace=replacement,
            p=pr / pr.sum()))(ks, p).astype(jnp.int64)
    return apply_nodiff("multinomial", f, x, key)


def poisson(x, name=None):
    key = default_generator.next_key()
    return apply_nodiff("poisson",
                        lambda lam, k: jax.random.poisson(k, lam).astype(lam.dtype),
                        x, key)


def exponential_(x, lam=1.0, name=None):
    key = default_generator.next_key()
    u = jax.random.uniform(key, tuple(x.shape), np.dtype(x.dtype))
    x._replace(-jnp.log(1.0 - u) / lam)
    return x


def binomial(count, prob, name=None):
    key = default_generator.next_key()
    def f(n, p, k):
        return jax.random.binomial(k, n.astype(jnp.float32), p).astype(jnp.int64)
    return apply_nodiff("binomial", f, count, prob, key)


def standard_gamma(x, name=None):
    key = default_generator.next_key()
    return apply_nodiff("standard_gamma",
                        lambda a, k: jax.random.gamma(k, a), x, key)
