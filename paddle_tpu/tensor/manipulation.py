"""Shape / layout manipulation ops (paddle.tensor.manipulation parity,
/root/reference/python/paddle/tensor/manipulation.py). All static-shape,
XLA-friendly: no data-dependent output shapes except the documented
exceptions (nonzero/unique/masked_select) which are eager-only."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..decomposition.register import DecompAware
from ..framework.core import Tensor, apply, apply_nodiff

__all__ = [
    "reshape", "reshape_", "flatten", "transpose", "squeeze", "squeeze_",
    "unsqueeze", "unsqueeze_", "concat", "stack", "split", "chunk", "unbind",
    "tile", "expand", "expand_as", "broadcast_to", "broadcast_tensors",
    "flip", "rot90", "roll", "gather", "gather_nd", "scatter", "scatter_nd",
    "scatter_nd_add", "index_select", "index_add", "index_put", "take",
    "take_along_axis", "put_along_axis", "masked_select", "masked_fill",
    "masked_scatter", "where", "nonzero", "unique", "unique_consecutive",
    "sort", "argsort", "argmax", "argmin", "topk", "searchsorted",
    "bucketize", "kthvalue",
    "mode", "median", "nanmedian", "quantile", "nanquantile",
    "pad", "slice", "strided_slice", "crop", "repeat_interleave",
    "as_strided", "view", "view_as", "unfold", "tensordot", "moveaxis",
    "swapaxes", "atleast_1d", "atleast_2d", "atleast_3d", "unstack",
    "tensor_split", "hsplit", "vsplit", "dsplit", "hstack", "vstack",
    "dstack", "column_stack", "row_stack", "shard_index", "cdist",
]

from .linalg import transpose  # shared


def _ints(v):
    if isinstance(v, Tensor):
        return tuple(int(x) for x in np.atleast_1d(np.asarray(v._value)))
    if isinstance(v, (int, np.integer)):
        return (int(v),)
    out = []
    for e in v:
        if isinstance(e, Tensor):
            out.append(int(np.asarray(e._value)))
        else:
            out.append(int(e))
    return tuple(out)


def reshape(x, shape, name=None):
    s = _ints(shape)
    return apply("reshape", lambda a: jnp.reshape(a, s), x)


def reshape_(x, shape, name=None):
    out = reshape(x, shape)
    x._value, x._node, x._out_idx = out._value, out._node, out._out_idx
    x.stop_gradient = out.stop_gradient
    return x


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    def f(a):
        nd = a.ndim
        s = start_axis % nd if nd else 0
        e = stop_axis % nd if nd else 0
        new_shape = a.shape[:s] + (-1,) + a.shape[e + 1:]
        return jnp.reshape(a, new_shape)
    return apply("flatten", f, x)


def squeeze(x, axis=None, name=None):
    def f(a):
        if axis is None:
            return jnp.squeeze(a)
        axes = _ints(axis)
        axes = tuple(ax % a.ndim for ax in axes)
        axes = tuple(ax for ax in axes if a.shape[ax] == 1)
        return jnp.squeeze(a, axis=axes) if axes else a
    return apply("squeeze", DecompAware("squeeze", f, axis=axis), x)


def squeeze_(x, axis=None, name=None):
    out = squeeze(x, axis)
    x._value, x._node, x._out_idx = out._value, out._node, out._out_idx
    return x


def unsqueeze(x, axis, name=None):
    axes = _ints(axis)
    def f(a):
        out = a
        for ax in sorted(axes):
            out = jnp.expand_dims(out, ax)
        return out
    return apply("unsqueeze", DecompAware("unsqueeze", f, axis=axes), x)


def unsqueeze_(x, axis, name=None):
    out = unsqueeze(x, axis)
    x._value, x._node, x._out_idx = out._value, out._node, out._out_idx
    return x


def concat(x, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return apply("concat", lambda *xs: jnp.concatenate(xs, axis=ax), *x)


def stack(x, axis=0, name=None):
    return apply("stack", DecompAware(
        "stack", lambda *xs: jnp.stack(xs, axis=axis), axis=axis), *x)


def split(x, num_or_sections, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    def f(a):
        if isinstance(num_or_sections, int):
            return tuple(jnp.split(a, num_or_sections, axis=ax))
        secs = list(num_or_sections)
        total = a.shape[ax]
        known = builtins_sum(s for s in secs if s not in (-1,))
        secs = [total - known if s == -1 else s for s in secs]
        idx = np.cumsum(secs)[:-1]
        return tuple(jnp.split(a, idx, axis=ax))
    return list(apply("split", f, x))


def builtins_sum(it):
    tot = 0
    for v in it:
        tot += v
    return tot


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def unbind(input, axis=0, name=None):
    n = input.shape[axis]
    def f(a):
        return tuple(jnp.squeeze(s, axis=axis) for s in jnp.split(a, n, axis=axis))
    return list(apply("unbind", f, input))


unstack = unbind


def tile(x, repeat_times, name=None):
    reps = _ints(repeat_times)
    return apply("tile", lambda a: jnp.tile(a, reps), x)


def expand(x, shape, name=None):
    s = _ints(shape)
    def f(a):
        tgt = list(s)
        # -1 means keep original dim
        off = len(tgt) - a.ndim
        for i in range(len(tgt)):
            if tgt[i] == -1:
                tgt[i] = a.shape[i - off]
        return jnp.broadcast_to(a, tuple(tgt))
    return apply("expand", f, x)


def expand_as(x, y, name=None):
    return apply("expand_as", lambda a, b: jnp.broadcast_to(a, b.shape), x, y)


def broadcast_to(x, shape, name=None):
    s = _ints(shape)
    return apply("broadcast_to", lambda a: jnp.broadcast_to(a, s), x)


def broadcast_tensors(input, name=None):
    return list(apply("broadcast_tensors", lambda *xs: tuple(jnp.broadcast_arrays(*xs)), *input))


def flip(x, axis, name=None):
    axes = _ints(axis)
    return apply("flip", lambda a: jnp.flip(a, axis=axes), x)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply("rot90", lambda a: jnp.rot90(a, k=k, axes=tuple(axes)), x)


def roll(x, shifts, axis=None, name=None):
    sh = _ints(shifts)
    sh = sh[0] if len(sh) == 1 and not isinstance(shifts, (list, tuple)) else sh
    ax = _ints(axis) if axis is not None else None
    if isinstance(sh, tuple) and ax is not None and len(sh) == len(ax):
        return apply("roll", lambda a: jnp.roll(a, sh, axis=ax), x)
    return apply("roll", lambda a: jnp.roll(a, sh if not isinstance(sh, tuple) else sh[0],
                                            axis=None if ax is None else ax[0]), x)


def gather(x, index, axis=0, name=None):
    ax = int(axis._value) if isinstance(axis, Tensor) else int(axis)
    return apply("gather", lambda a, i: jnp.take(a, i.reshape(-1) if i.ndim > 1 else i, axis=ax), x, index)


def gather_nd(x, index, name=None):
    def f(a, idx):
        k = idx.shape[-1]
        flat_idx = tuple(idx[..., i] for i in range(k))
        return a[flat_idx]
    return apply("gather_nd", f, x, index)


def scatter(x, index, updates, overwrite=True, name=None):
    def f(a, i, u):
        i = i.reshape(-1)
        if overwrite:
            # paddle overwrite semantics: later rows win; emulate with set
            return a.at[i].set(u)
        base = a.at[i].set(jnp.zeros_like(u))
        return base.at[i].add(u)
    return apply("scatter", f, x, index, updates)


def scatter_nd(index, updates, shape, name=None):
    s = _ints(shape)
    def f(i, u):
        out = jnp.zeros(s, u.dtype)
        k = i.shape[-1]
        return out.at[tuple(i[..., d] for d in range(k))].add(u)
    return apply("scatter_nd", f, index, updates)


def scatter_nd_add(x, index, updates, name=None):
    def f(a, i, u):
        k = i.shape[-1]
        return a.at[tuple(i[..., d] for d in range(k))].add(u)
    return apply("scatter_nd_add", f, x, index, updates)


def index_select(x, index, axis=0, name=None):
    return apply("index_select", DecompAware(
        "index_select", lambda a, i: jnp.take(a, i, axis=axis),
        axis=axis), x, index)


def index_add(x, index, axis, value, name=None):
    def f(a, i, v):
        idx = [builtins_slice(None)] * a.ndim
        idx[axis] = i
        return a.at[tuple(idx)].add(v)
    return apply("index_add", f, x, index, value)


def builtins_slice(*a):
    import builtins
    return builtins.slice(*a)


def index_put(x, indices, value, accumulate=False, name=None):
    def f(a, v, *idx):
        if accumulate:
            return a.at[tuple(idx)].add(v)
        return a.at[tuple(idx)].set(v)
    return apply("index_put", f, x, value, *indices)


def take(x, index, mode="raise", name=None):
    m = {"raise": "clip", "clip": "clip", "wrap": "wrap"}[mode]
    return apply("take", lambda a, i: jnp.take(a.reshape(-1), i, mode=m), x, index)


def take_along_axis(arr, indices, axis, broadcast=True, name=None):
    return apply("take_along_axis", lambda a, i: jnp.take_along_axis(a, i, axis=axis), arr, indices)


def put_along_axis(arr, indices, values, axis, reduce="assign",
                   include_self=True, broadcast=True, name=None):
    def f(a, i, v):
        v = jnp.broadcast_to(v, i.shape) if jnp.ndim(v) else jnp.full(i.shape, v, a.dtype)
        if reduce == "assign":
            return jnp.put_along_axis(a, i, v, axis=axis, inplace=False)
        if reduce == "add":
            idx = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(i.ndim)])
                   for k, s in enumerate(i.shape)]
            idx[axis] = i
            return a.at[tuple(idx)].add(v)
        if reduce in ("mul", "multiply"):
            idx = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(i.ndim)])
                   for k, s in enumerate(i.shape)]
            idx[axis] = i
            return a.at[tuple(idx)].multiply(v)
        raise ValueError(reduce)
    return apply("put_along_axis", f, arr, indices, values)


def masked_select(x, mask, name=None):
    # Data-dependent output shape: eager-only (documented XLA exception).
    xv = np.asarray(x._value)
    mv = np.asarray(mask._value)
    return Tensor(jnp.asarray(xv[mv]))


def masked_fill(x, mask, value, name=None):
    v = value._value if isinstance(value, Tensor) else value
    return apply("masked_fill", lambda a, m: jnp.where(m, jnp.asarray(v, a.dtype), a), x, mask)


def masked_scatter(x, mask, value, name=None):
    xv = np.asarray(x._value)
    mv = np.asarray(mask._value)
    vv = np.asarray(value._value).reshape(-1)
    out = xv.copy()
    out[mv] = vv[: int(mv.sum())]
    return Tensor(jnp.asarray(out))


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    return apply("where", lambda c, a, b: jnp.where(c, a, b), condition, x, y)


def nonzero(x, as_tuple=False):
    xv = np.asarray(x._value)
    nz = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(i.reshape(-1, 1))) for i in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype="int64", name=None):
    xv = np.asarray(x._value)
    res = np.unique(xv, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not (return_index or return_inverse or return_counts):
        return Tensor(jnp.asarray(res))
    out = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(out)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype="int64", name=None):
    xv = np.asarray(x._value)
    if axis is None:
        xv = xv.reshape(-1)
        change = np.concatenate([[True], xv[1:] != xv[:-1]])
    else:
        raise NotImplementedError("axis not supported yet")
    vals = xv[change]
    outs = [Tensor(jnp.asarray(vals))]
    if return_inverse:
        inv = np.cumsum(change) - 1
        outs.append(Tensor(jnp.asarray(inv.astype(np.int64))))
    if return_counts:
        idx = np.nonzero(change)[0]
        counts = np.diff(np.concatenate([idx, [len(xv)]]))
        outs.append(Tensor(jnp.asarray(counts.astype(np.int64))))
    return outs[0] if len(outs) == 1 else tuple(outs)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    def f(a):
        if axis is None:
            out = jnp.argmax(a.reshape(-1))
            return (out.reshape((1,) * a.ndim) if keepdim else out).astype(d)
        return jnp.argmax(a, axis=axis, keepdims=keepdim).astype(d)
    return apply_nodiff("argmax", f, x)


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    d = dtypes.convert_dtype(dtype)
    def f(a):
        if axis is None:
            out = jnp.argmin(a.reshape(-1))
            return (out.reshape((1,) * a.ndim) if keepdim else out).astype(d)
        return jnp.argmin(a, axis=axis, keepdims=keepdim).astype(d)
    return apply_nodiff("argmin", f, x)


def sort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        out = jnp.sort(a, axis=axis, stable=stable)
        return jnp.flip(out, axis=axis) if descending else out
    return apply("sort", f, x)


def argsort(x, axis=-1, descending=False, stable=False, name=None):
    def f(a):
        i = jnp.argsort(a, axis=axis, stable=stable)
        return jnp.flip(i, axis=axis) if descending else i
    return apply_nodiff("argsort", f, x)


def topk(x, k, axis=-1, largest=True, sorted=True, name=None):
    kk = int(k._value) if isinstance(k, Tensor) else int(k)
    def f(a):
        ax = axis % a.ndim
        moved = jnp.moveaxis(a, ax, -1)
        if largest:
            v, i = jax.lax.top_k(moved, kk)
        else:
            v, i = jax.lax.top_k(-moved, kk)
            v = -v
        return jnp.moveaxis(v, -1, ax), jnp.moveaxis(i, -1, ax).astype(jnp.int64)
    vals, idxs = apply("topk", f, x)
    idxs.stop_gradient = True
    return vals, idxs


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = "right" if right else "left"
    d = jnp.int32 if out_int32 else jnp.int64
    def f(s, v):
        if s.ndim == 1:
            return jnp.searchsorted(s, v, side=side).astype(d)
        flat_s = s.reshape(-1, s.shape[-1])
        flat_v = v.reshape(-1, v.shape[-1])
        out = jax.vmap(lambda ss, vv: jnp.searchsorted(ss, vv, side=side))(flat_s, flat_v)
        return out.reshape(v.shape).astype(d)
    return apply_nodiff("searchsorted", f, sorted_sequence, values)


def bucketize(x, sorted_sequence, out_int32=False, right=False, name=None):
    return searchsorted(sorted_sequence, x, out_int32=out_int32, right=right)


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    def f(a):
        si = jnp.argsort(a, axis=axis)
        i = jnp.take(si, k - 1, axis=axis)
        v = jnp.take_along_axis(a, jnp.expand_dims(i, axis), axis=axis)
        v = v if keepdim else jnp.squeeze(v, axis)
        i = (jnp.expand_dims(i, axis) if keepdim else i).astype(jnp.int64)
        return v, i
    vals, idxs = apply("kthvalue", f, x)
    idxs.stop_gradient = True
    return vals, idxs


def mode(x, axis=-1, keepdim=False, name=None):
    """Most frequent value along `axis` (+ its index); ties break to
    the smallest value. Pure jnp so gradients flow to the selected
    element (the scipy path returned graph-less Tensors, which broke
    backward once scipy started reporting float counts). O(n log n):
    sort, then per-position run length from the run-start cummax and
    next-run-start cummin — no pairwise n×n comparison."""
    def f(a):
        last = a.ndim - 1
        m = jnp.moveaxis(a, axis, -1)
        n = m.shape[-1]
        s = jnp.sort(m, axis=-1)
        order = jnp.argsort(m, axis=-1)          # stable, ascending
        p = jnp.arange(n)
        change = jnp.concatenate(
            [jnp.ones(m.shape[:-1] + (1,), bool),
             s[..., 1:] != s[..., :-1]], axis=-1)
        start = jax.lax.cummax(jnp.where(change, p, 0), axis=last)
        nxt = jax.lax.cummin(jnp.where(change, p, n)[..., ::-1],
                             axis=last)[..., ::-1]
        end = jnp.concatenate(
            [nxt[..., 1:], jnp.full(m.shape[:-1] + (1,), n)], axis=-1)
        counts = end - start                     # run length at each pos
        # first max → leftmost run → smallest value among count ties;
        # the reported index is the LAST original occurrence (paddle
        # semantics): the run's final sorted slot, whose stable-argsort
        # entry is the largest original index of that value
        best = jnp.argmax(counts, axis=-1, keepdims=True)
        pick = jnp.take_along_axis(
            order, jnp.take_along_axis(end, best, -1) - 1, -1)
        v = jnp.moveaxis(jnp.take_along_axis(m, pick, -1), -1, axis)
        i = jnp.moveaxis(pick, -1, axis)
        if not keepdim:
            v = jnp.squeeze(v, axis)
            i = jnp.squeeze(i, axis)
        idx_dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        return v, i.astype(idx_dtype)
    vals, idxs = apply("mode", f, x)
    idxs.stop_gradient = True
    return vals, idxs


def median(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply("median", lambda a: jnp.median(a, axis=axis, keepdims=keepdim), x)


def nanmedian(x, axis=None, keepdim=False, mode="avg", name=None):
    return apply("nanmedian", lambda a: jnp.nanmedian(a, axis=axis, keepdims=keepdim), x)


def quantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply("quantile", lambda a: jnp.quantile(a, jnp.asarray(q), axis=axis,
                                                    keepdims=keepdim, method=interpolation), x)


def nanquantile(x, q, axis=None, keepdim=False, interpolation="linear", name=None):
    return apply("nanquantile", lambda a: jnp.nanquantile(a, jnp.asarray(q), axis=axis,
                                                          keepdims=keepdim, method=interpolation), x)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    p = _ints(pad)
    def f(a):
        nd = a.ndim
        if len(p) == 2 * nd:
            # full pad spec: paddle order is (before_0, after_0, ...)? paddle uses
            # flat [d0_l, d0_r, d1_l, d1_r, ...] over all dims
            widths = [(p[2 * i], p[2 * i + 1]) for i in range(nd)]
        else:
            # partial spec applies to trailing spatial dims, reversed pairs like torch
            k = len(p) // 2
            widths = [(0, 0)] * nd
            if data_format.startswith("N") and len(data_format) == nd + 0:
                pass
            # paddle semantics: pairs start from the LAST spatial dim
            # (e.g. NCHW len-4 pad = [W_l, W_r, H_l, H_r])
            if data_format in ("NCHW", "NCDHW", "NCL"):
                dims = list(range(2, nd))
            elif data_format in ("NHWC", "NDHWC", "NLC"):
                dims = list(range(1, nd - 1))
            else:
                dims = list(range(nd - k, nd))
            if not dims or k > len(dims):
                raise ValueError(
                    f"pad: partial pad of length {len(p)} does not fit a "
                    f"{nd}-D input with data_format={data_format!r}; "
                    "pass the full 2*ndim spec (silently padding "
                    "nothing would hide the mistake)")
            for j, d in enumerate(reversed(dims[-k:])):
                widths[d] = (p[2 * j], p[2 * j + 1])
        jmode = {"constant": "constant", "reflect": "reflect",
                 "replicate": "edge", "circular": "wrap"}[mode]
        if jmode == "constant":
            return jnp.pad(a, widths, mode=jmode, constant_values=value)
        return jnp.pad(a, widths, mode=jmode)
    return apply("pad", f, x)


def slice(input, axes, starts, ends, name=None):
    ax = _ints(axes)
    st = _ints(starts)
    en = _ints(ends)
    def f(a):
        idx = [builtins_slice(None)] * a.ndim
        for d, s, e in zip(ax, st, en):
            idx[d] = builtins_slice(s, e)
        return a[tuple(idx)]
    return apply("slice", f, input)


def strided_slice(x, axes, starts, ends, strides, name=None):
    ax, st, en, sr = _ints(axes), _ints(starts), _ints(ends), _ints(strides)
    def f(a):
        idx = [builtins_slice(None)] * a.ndim
        for d, s, e, r in zip(ax, st, en, sr):
            idx[d] = builtins_slice(s, e, r)
        return a[tuple(idx)]
    return apply("strided_slice", f, x)


def crop(x, shape=None, offsets=None, name=None):
    s = _ints(shape)
    o = _ints(offsets) if offsets is not None else (0,) * len(s)
    def f(a):
        idx = tuple(builtins_slice(oo, oo + (ss if ss != -1 else a.shape[d] - oo))
                    for d, (oo, ss) in enumerate(zip(o, s)))
        return a[idx]
    return apply("crop", f, x)


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        return apply("repeat_interleave",
                     lambda a, r: jnp.repeat(a, r, axis=axis,
                                             total_repeat_length=int(np.asarray(repeats._value).sum())),
                     x, repeats)
    return apply("repeat_interleave", lambda a: jnp.repeat(a, repeats, axis=axis), x)


def as_strided(x, shape, stride, offset=0, name=None):
    def f(a):
        flat = a.reshape(-1)
        idx = np.zeros(tuple(shape), dtype=np.int64) + offset
        for d, (s, st) in enumerate(zip(shape, stride)):
            r = np.arange(s) * st
            idx = idx + r.reshape([-1 if i == d else 1 for i in range(len(shape))])
        return flat[jnp.asarray(idx)]
    return apply("as_strided", f, x)


def view(x, shape_or_dtype, name=None):
    if isinstance(shape_or_dtype, (list, tuple)):
        return reshape(x, shape_or_dtype)
    return apply("view_dtype", lambda a: a.view(dtypes.convert_dtype(shape_or_dtype)), x)


def view_as(x, other, name=None):
    return reshape(x, other.shape)


def unfold(x, axis, size, step, name=None):
    def f(a):
        n = (a.shape[axis] - size) // step + 1
        idx = jnp.arange(n)[:, None] * step + jnp.arange(size)[None, :]
        moved = jnp.moveaxis(a, axis, 0)
        out = moved[idx]  # (n, size, ...)
        out = jnp.moveaxis(out, (0, 1), (axis, a.ndim))
        return out
    return apply("unfold", f, x)


def tensordot(x, y, axes=2, name=None):
    ax = axes
    if isinstance(ax, Tensor):
        ax = np.asarray(ax._value).tolist()
    if isinstance(ax, (list, tuple)):
        ax = tuple(tuple(_ints(a)) if isinstance(a, (list, tuple, Tensor)) else a for a in ax)
    return apply("tensordot", lambda a, b: jnp.tensordot(a, b, axes=ax), x, y)


def moveaxis(x, source, destination, name=None):
    return apply("moveaxis", lambda a: jnp.moveaxis(a, source, destination), x)


def swapaxes(x, axis0, axis1, name=None):
    return apply("swapaxes", lambda a: jnp.swapaxes(a, axis0, axis1), x)


def atleast_1d(*inputs, name=None):
    outs = [apply("atleast_1d", jnp.atleast_1d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply("atleast_2d", jnp.atleast_2d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply("atleast_3d", jnp.atleast_3d, x) for x in inputs]
    return outs[0] if len(outs) == 1 else outs


def tensor_split(x, num_or_indices, axis=0, name=None):
    def f(a):
        return tuple(jnp.array_split(a, num_or_indices, axis=axis)) \
            if isinstance(num_or_indices, int) else \
            tuple(jnp.split(a, list(num_or_indices), axis=axis))
    return list(apply("tensor_split", f, x))


def hsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=1 if x.ndim > 1 else 0)


def vsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=0)


def dsplit(x, num_or_indices, name=None):
    return tensor_split(x, num_or_indices, axis=2)


def hstack(x, name=None):
    return apply("hstack", lambda *xs: jnp.hstack(xs), *x)


def vstack(x, name=None):
    return apply("vstack", lambda *xs: jnp.vstack(xs), *x)


def dstack(x, name=None):
    return apply("dstack", lambda *xs: jnp.dstack(xs), *x)


def column_stack(x, name=None):
    return apply("column_stack", lambda *xs: jnp.column_stack(xs), *x)


row_stack = vstack


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    def f(i):
        shard_size = (index_num + nshards - 1) // nshards
        in_shard = (i // shard_size) == shard_id
        return jnp.where(in_shard, i % shard_size, ignore_value)
    return apply_nodiff("shard_index", f, input)


def cdist(x, y, p=2.0, compute_mode="use_mm_for_euclid_dist_if_necessary", name=None):
    def f(a, b):
        diff = a[..., :, None, :] - b[..., None, :, :]
        if p == 2.0:
            return jnp.sqrt(jnp.sum(diff * diff, axis=-1) + 1e-30)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(diff), p), axis=-1), 1.0 / p)
    return apply("cdist", f, x, y)
