"""Elementwise + reduction math ops (paddle.tensor.math parity,
/root/reference/python/paddle/tensor/math.py). Every op is a jnp/lax
composition dispatched through the autograd tape; XLA fuses the rest."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..decomposition.register import DecompAware
from ..framework.core import Tensor, apply, apply_nodiff

__all__ = [
    "add", "subtract", "multiply", "divide", "floor_divide", "mod",
    "remainder", "pow", "float_power", "maximum", "minimum", "fmax", "fmin",
    "abs", "neg", "ceil", "floor", "round", "trunc", "frac", "exp", "expm1",
    "log", "log2", "log10", "log1p", "sqrt", "rsqrt", "square", "sign",
    "reciprocal", "sin", "cos", "tan", "asin", "acos", "atan", "atan2",
    "sinh", "cosh", "tanh", "asinh", "acosh", "atanh", "erf", "erfinv",
    "lgamma", "digamma", "sigmoid", "logit", "clip", "lerp", "nan_to_num",
    "sum", "nansum", "mean", "nanmean", "prod", "max", "min", "amax", "amin",
    "logsumexp", "all", "any", "cumsum", "cumprod", "cummax", "cummin",
    "isfinite", "isinf", "isnan", "count_nonzero", "addmm", "inner", "outer",
    "heaviside", "rad2deg", "deg2rad", "gcd", "lcm", "diff", "angle",
    "conj", "real", "imag", "trapezoid", "multiply_", "add_", "subtract_",
    "scale", "stanh", "multiplex", "increment", "log_normalize",
]


def _ew(op_name, fn):
    def op(x, y, name=None):  # `name` is the paddle API's output-name
        return apply(op_name, fn, x, y)  # arg — NOT the op identifier
    op.__name__ = op_name
    return op


add = _ew("add", jnp.add)
subtract = _ew("subtract", jnp.subtract)
multiply = _ew("multiply", jnp.multiply)
divide = _ew("divide", jnp.divide)
maximum = _ew("maximum", jnp.maximum)
minimum = _ew("minimum", jnp.minimum)
fmax = _ew("fmax", jnp.fmax)
fmin = _ew("fmin", jnp.fmin)
atan2 = _ew("atan2", jnp.arctan2)
heaviside = _ew("heaviside", jnp.heaviside)


def floor_divide(x, y, name=None):
    return apply_nodiff("floor_divide", jnp.floor_divide, x, y)


def mod(x, y, name=None):
    return apply("mod", jnp.mod, x, y)


remainder = mod


def pow(x, y, name=None):
    return apply("pow", jnp.power, x, y)


def float_power(x, y, name=None):
    # paddle promises float64 math; x64 must be enabled in jax or the
    # cast silently narrows, so promote as far as the backend allows
    def f(a, b):
        target = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
        return jnp.power(a.astype(target), b)
    return apply("float_power", f, x, y)


def _uw(op_name, fn):
    # DecompAware: any unary op picks up a registered decomposition rule
    # under enable_prim() with no per-site wiring (see paddle.decomposition)
    aware = DecompAware(op_name, fn)

    def op(x, name=None):  # `name` = paddle output-name arg
        return apply(op_name, aware, x)
    op.__name__ = op_name
    return op


abs = _uw("abs", jnp.abs)
neg = _uw("neg", jnp.negative)
exp = _uw("exp", jnp.exp)
expm1 = _uw("expm1", jnp.expm1)
log = _uw("log", jnp.log)
log2 = _uw("log2", jnp.log2)
log10 = _uw("log10", jnp.log10)
log1p = _uw("log1p", jnp.log1p)
sqrt = _uw("sqrt", jnp.sqrt)
rsqrt = _uw("rsqrt", jax.lax.rsqrt)
square = _uw("square", jnp.square)
sin = _uw("sin", jnp.sin)
cos = _uw("cos", jnp.cos)
tan = _uw("tan", jnp.tan)
asin = _uw("asin", jnp.arcsin)
acos = _uw("acos", jnp.arccos)
atan = _uw("atan", jnp.arctan)
sinh = _uw("sinh", jnp.sinh)
cosh = _uw("cosh", jnp.cosh)
tanh = _uw("tanh", jnp.tanh)
asinh = _uw("asinh", jnp.arcsinh)
acosh = _uw("acosh", jnp.arccosh)
atanh = _uw("atanh", jnp.arctanh)
erf = _uw("erf", jax.scipy.special.erf)
erfinv = _uw("erfinv", jax.scipy.special.erfinv)
lgamma = _uw("lgamma", jax.scipy.special.gammaln)
digamma = _uw("digamma", jax.scipy.special.digamma)
sigmoid = _uw("sigmoid", jax.nn.sigmoid)
reciprocal = _uw("reciprocal", jnp.reciprocal)
rad2deg = _uw("rad2deg", jnp.rad2deg)
deg2rad = _uw("deg2rad", jnp.deg2rad)
angle = _uw("angle", jnp.angle)
conj = _uw("conj", jnp.conjugate)
real = _uw("real", jnp.real)
imag = _uw("imag", jnp.imag)


def sign(x, name=None):
    return apply_nodiff("sign", jnp.sign, x)


def ceil(x, name=None):
    return apply("ceil", jnp.ceil, x)


def floor(x, name=None):
    return apply("floor", jnp.floor, x)


def round(x, decimals=0, name=None):
    return apply("round", lambda a: jnp.round(a, decimals), x)


def trunc(x, name=None):
    return apply("trunc", jnp.trunc, x)


def frac(x, name=None):
    return apply("frac", lambda a: a - jnp.trunc(a), x)


def logit(x, eps=None, name=None):
    def f(a):
        if eps is not None:
            a = jnp.clip(a, eps, 1.0 - eps)
        return jnp.log(a / (1.0 - a))
    return apply("logit", f, x)


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply("stanh", lambda a: scale_b * jnp.tanh(scale_a * a), x)


def clip(x, min=None, max=None, name=None):
    lo = min._value if isinstance(min, Tensor) else min
    hi = max._value if isinstance(max, Tensor) else max
    return apply("clip", lambda a: jnp.clip(a, lo, hi), x)


def lerp(x, y, weight, name=None):
    return apply("lerp", lambda a, b, w: a + w * (b - a), x, y, weight)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply("nan_to_num",
                 lambda a: jnp.nan_to_num(a, nan=nan, posinf=posinf, neginf=neginf), x)


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    def f(a, s):
        if bias_after_scale:
            return s * a + jnp.asarray(bias, a.dtype)
        return s * (a + jnp.asarray(bias, a.dtype))
    if isinstance(scale, Tensor):
        return apply("scale", f, x, scale)
    return apply("scale", lambda a: f(a, jnp.asarray(scale, a.dtype)), x)


def increment(x, value=1.0, name=None):
    out = apply("increment", lambda a: a + jnp.asarray(value, a.dtype), x)
    x._replace(out._value)
    return x


# -- reductions -------------------------------------------------------------

def _axis(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        a = np.asarray(axis._value)
        return tuple(int(v) for v in np.atleast_1d(a))
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    def f(a):
        out = jnp.sum(a, axis=_axis(axis), keepdims=keepdim, dtype=d)
        return out
    return apply("sum", f, x)


def nansum(x, axis=None, dtype=None, keepdim=False, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("nansum", lambda a: jnp.nansum(a, axis=_axis(axis), keepdims=keepdim, dtype=d), x)


def mean(x, axis=None, keepdim=False, name=None):
    return apply("mean", DecompAware(
        "mean", lambda a: jnp.mean(a, axis=_axis(axis), keepdims=keepdim),
        axis=_axis(axis), keepdim=keepdim), x)


def nanmean(x, axis=None, keepdim=False, name=None):
    return apply("nanmean", lambda a: jnp.nanmean(a, axis=_axis(axis), keepdims=keepdim), x)


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("prod", lambda a: jnp.prod(a, axis=_axis(axis), keepdims=keepdim, dtype=d), x)


def max(x, axis=None, keepdim=False, name=None):
    return apply("max", lambda a: jnp.max(a, axis=_axis(axis), keepdims=keepdim), x)


def min(x, axis=None, keepdim=False, name=None):
    return apply("min", lambda a: jnp.min(a, axis=_axis(axis), keepdims=keepdim), x)


amax = max
amin = min


def logsumexp(x, axis=None, keepdim=False, name=None):
    return apply("logsumexp", lambda a: jax.scipy.special.logsumexp(a, axis=_axis(axis), keepdims=keepdim), x)


def all(x, axis=None, keepdim=False, name=None):
    return apply_nodiff("all", lambda a: jnp.all(a, axis=_axis(axis), keepdims=keepdim), x)


def any(x, axis=None, keepdim=False, name=None):
    return apply_nodiff("any", lambda a: jnp.any(a, axis=_axis(axis), keepdims=keepdim), x)


def count_nonzero(x, axis=None, keepdim=False, name=None):
    return apply_nodiff("count_nonzero", lambda a: jnp.count_nonzero(a, axis=_axis(axis), keepdims=keepdim), x)


def cumsum(x, axis=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    def f(a):
        if axis is None:
            a = a.reshape(-1)
            return jnp.cumsum(a, dtype=d)
        return jnp.cumsum(a, axis=int(axis), dtype=d)
    return apply("cumsum", f, x)


def cumprod(x, dim=None, dtype=None, name=None):
    d = dtypes.convert_dtype(dtype) if dtype else None
    return apply("cumprod", lambda a: jnp.cumprod(a, axis=dim, dtype=d), x)


def cummax(x, axis=None, dtype="int64", name=None):
    def f(a):
        ax = -1 if axis is None else axis
        if axis is None:
            a = a.reshape(-1)
        vals = jax.lax.associative_scan(jnp.maximum, a, axis=ax)
        return vals
    vals = apply("cummax", f, x)
    # indices: argmax of running max == current
    def fi(a):
        ax = 0 if axis is None else axis
        if axis is None:
            a = a.reshape(-1)
            ax = 0
        n = a.shape[ax]
        idx = jnp.arange(n).reshape([-1 if i == (ax % a.ndim) else 1 for i in range(a.ndim)])
        vals_ = jax.lax.associative_scan(jnp.maximum, a, axis=ax)
        is_new = a >= vals_
        idx_b = jnp.where(is_new, idx, 0)
        inds = jax.lax.associative_scan(jnp.maximum, idx_b, axis=ax)
        return inds.astype(dtypes.convert_dtype(dtype))
    inds = apply_nodiff("cummax_idx", fi, x)
    return vals, inds


def cummin(x, axis=None, dtype="int64", name=None):
    from . import math as _m
    neg_vals, inds = cummax(_m.neg(x), axis=axis, dtype=dtype)
    return _m.neg(neg_vals), inds


def isfinite(x, name=None):
    return apply_nodiff("isfinite", jnp.isfinite, x)


def isinf(x, name=None):
    return apply_nodiff("isinf", jnp.isinf, x)


def isnan(x, name=None):
    return apply_nodiff("isnan", jnp.isnan, x)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply("addmm", lambda i, a, b: beta * i + alpha * (a @ b), input, x, y)


def inner(x, y, name=None):
    return apply("inner", jnp.inner, x, y)


def outer(x, y, name=None):
    return apply("outer", lambda a, b: jnp.outer(a, b), x, y)


def gcd(x, y, name=None):
    return apply_nodiff("gcd", jnp.gcd, x, y)


def lcm(x, y, name=None):
    return apply_nodiff("lcm", jnp.lcm, x, y)


def diff(x, n=1, axis=-1, prepend=None, append=None, name=None):
    pre = prepend._value if isinstance(prepend, Tensor) else prepend
    app = append._value if isinstance(append, Tensor) else append
    return apply("diff", lambda a: jnp.diff(a, n=n, axis=axis, prepend=pre, append=app), x)


def trapezoid(y, x=None, dx=None, axis=-1, name=None):
    if x is not None:
        return apply("trapezoid", lambda a, b: jnp.trapezoid(a, x=b, axis=axis), y, x)
    return apply("trapezoid", lambda a: jnp.trapezoid(a, dx=1.0 if dx is None else dx, axis=axis), y)


def multiplex(inputs, index, name=None):
    def f(idx, *xs):
        stacked = jnp.stack(xs, axis=0)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx.reshape(-1), rows]
    return apply("multiplex", f, index, *inputs)


def log_normalize(x, axis=-1):
    return apply("log_normalize", lambda a: a - jax.scipy.special.logsumexp(a, axis=axis, keepdims=True), x)


# -- in-place variants (mutate the Tensor object) ---------------------------

def _inplace(fn):
    def op(x, y, name=None):
        # snapshot x before recording: a node whose input is the tensor
        # being overwritten would self-cycle and sever upstream grads
        snap = Tensor(x._value, stop_gradient=x.stop_gradient)
        snap._node = x._node
        snap._out_idx = x._out_idx
        out = fn(snap, y)
        x._value = out._value
        x._node = out._node
        x._out_idx = out._out_idx
        x.stop_gradient = out.stop_gradient
        return x
    return op


add_ = _inplace(add)
subtract_ = _inplace(subtract)
multiply_ = _inplace(multiply)
