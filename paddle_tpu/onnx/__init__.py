"""paddle.onnx — ONNX export surface (reference:
/root/reference/python/paddle/onnx/export.py, a thin wrapper over the
external paddle2onnx package).

Descoped with a loud redirect: ONNX is a CPU/GPU-runtime interchange
format; the TPU-native deployment artifact is StableHLO —
``paddle.jit.save`` exports a jax.export archive that the serving stack
(inference.Config/create_predictor) loads AOT. See COVERAGE.md descope
table.
"""
from __future__ import annotations

__all__ = ["export"]


def export(layer, path, input_spec=None, opset_version=9, **configs):
    raise NotImplementedError(
        "paddle.onnx.export is not provided by this TPU-native build "
        "(the reference delegates to the external paddle2onnx package). "
        "Export a StableHLO artifact instead: paddle.jit.save(layer, "
        "path, input_spec=...) produces an AOT archive servable via "
        "paddle.inference.Config/create_predictor.")
