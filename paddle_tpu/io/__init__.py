"""paddle_tpu.io — Dataset/DataLoader parity
(/root/reference/python/paddle/io/reader.py:216, dataloader/).

TPU-native redesign: instead of the reference's multiprocess worker pool +
LoDTensor blocking queue (/root/reference/python/paddle/io/dataloader/
dataloader_iter.py:358), the loader is a thread-prefetched numpy pipeline —
host CPU prepares batches while the chip runs the previous step (async
dispatch gives the overlap). Multiprocess workers can be added per-dataset
via num_workers (threads here: JAX arrays are created on the main thread).
"""
from __future__ import annotations

import itertools
import queue
import threading
from typing import Any, Callable, Iterable, List, Optional, Sequence

import numpy as np

from ..framework.core import Tensor, to_tensor, default_generator

__all__ = [
    "Dataset", "IterableDataset", "TensorDataset", "ComposeDataset",
    "ChainDataset", "Subset", "random_split", "Sampler", "SequenceSampler",
    "RandomSampler", "WeightedRandomSampler", "BatchSampler",
    "DistributedBatchSampler", "DataLoader", "get_worker_info",
    "ConcatDataset", "SubsetRandomSampler",
]


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not indexable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence[Tensor]):
        lens = {t.shape[0] for t in tensors}
        assert len(lens) == 1, "tensors must share dim 0"
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            item = d[idx]
            out.extend(item if isinstance(item, (tuple, list)) else [item])
        return tuple(out)

    def __len__(self):
        return min(len(d) for d in self.datasets)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = len(dataset)
    if all(isinstance(l, float) for l in lengths):
        lengths = [int(round(l * total)) for l in lengths]
        lengths[-1] = total - sum(lengths[:-1])
    assert sum(lengths) == total
    perm = np.random.RandomState(
        default_generator.initial_seed or None).permutation(total)
    out, offset = [], 0
    for l in lengths:
        out.append(Subset(dataset, perm[offset:offset + l].tolist()))
        offset += l
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))

    def __len__(self):
        return len(self.data_source)


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.RandomState()
        if self.replacement:
            return iter(rng.randint(0, n, size=self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (parity:
    /root/reference/python/paddle/io/dataloader/batch_sampler.py)."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import get_world_size, get_rank
        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else get_world_size()
        self.local_rank = rank if rank is not None else get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate(
            [indices, indices[: self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


_worker_info = threading.local()


def get_worker_info():
    return getattr(_worker_info, "info", None)


def default_collate_fn(batch: List[Any]):
    """Stack samples into Tensor batches (parity:
    /root/reference/python/paddle/io/dataloader/collate.py). The numpy
    stacking (_collate_numpy) is shared with the shm worker path, which
    must not create jax arrays in child processes."""
    return _np_tree_to_tensor(_collate_numpy(batch))


class DataLoader:
    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False, shm_slot_bytes=64 << 20):
        self.dataset = dataset
        self._user_collate_fn = collate_fn
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = prefetch_factor
        self.use_shared_memory = use_shared_memory
        self.shm_slot_bytes = shm_slot_bytes
        self.worker_init_fn = worker_init_fn
        self.timeout = timeout
        self._epoch_count = 0
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(
                dataset, shuffle=shuffle, batch_size=batch_size,
                drop_last=drop_last)

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)

    def _iter_batches(self):
        if self._iterable_mode:
            it = iter(self.dataset)
            while True:
                batch = list(itertools.islice(it, self.batch_size))
                if not batch:
                    return
                if len(batch) < self.batch_size and self.drop_last:
                    return
                yield self.collate_fn(batch)
        else:
            for idx_batch in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in idx_batch])

    def __iter__(self):
        if self.num_workers == 0:
            yield from self._iter_batches()
            return
        if self.use_shared_memory:
            from paddle_tpu.core import native
            if native.available():
                yield from self._iter_shm()
                return
            # fall through to the thread pipeline if native lib is missing
        # thread-prefetch pipeline
        q: queue.Queue = queue.Queue(maxsize=self.prefetch_factor *
                                     max(self.num_workers, 1))
        sentinel = object()
        err_holder = []

        def producer():
            try:
                for b in self._iter_batches():
                    q.put(b)
            except BaseException as e:  # propagate to consumer
                err_holder.append(e)
            finally:
                q.put(sentinel)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is sentinel:
                if err_holder:
                    raise err_holder[0]
                return
            yield item

    def _iter_shm(self):
        """Multiprocess workers over the native shared-memory ring
        (paddle_tpu/io/shm_loader.py). Batches come back as numpy trees;
        collate to Tensors happens here on the main process (jax array
        creation must stay on the consumer side).

        Note: like the reference (and torch), a non-sharding
        IterableDataset is iterated once PER WORKER here — use
        get_worker_info() in __iter__ to shard; num_workers=0 or the
        thread pipeline iterate it exactly once."""
        from .shm_loader import ShmBatchLoader

        # user collate runs in the worker; Tensor leaves are converted to
        # numpy for the shm crossing. Default collate builds numpy directly.
        collate = self._user_collate_fn  # None → workers use _collate_numpy
        # fresh augmentation randomness each epoch (reference draws a new
        # base seed per iterator)
        seed = (default_generator.initial_seed
                + 1000003 * self._epoch_count) % (2 ** 31)
        self._epoch_count += 1
        timeout = self.timeout if self.timeout else None  # 0 = no timeout
        if self._iterable_mode:
            loader = ShmBatchLoader(
                self.dataset, None, self.num_workers, collate,
                worker_init_fn=self.worker_init_fn, seed=seed,
                slot_bytes=self.shm_slot_bytes,
                iterable_batch_size=self.batch_size,
                drop_last=self.drop_last, timeout=timeout)
        else:
            batch_indices = list(self.batch_sampler)
            loader = ShmBatchLoader(
                self.dataset, batch_indices, self.num_workers,
                collate, worker_init_fn=self.worker_init_fn, seed=seed,
                slot_bytes=self.shm_slot_bytes, timeout=timeout)
        for np_batch in loader:
            yield _np_tree_to_tensor(np_batch)


def _collate_numpy(batch):
    sample = batch[0]
    if isinstance(sample, Tensor):
        return np.stack([np.asarray(s._value) for s in batch])
    if isinstance(sample, np.ndarray):
        return np.stack(batch)
    if isinstance(sample, (int, np.integer)):
        return np.asarray(batch, dtype=np.int64)
    if isinstance(sample, (float, np.floating)):
        return np.asarray(batch, dtype=np.float32)
    if isinstance(sample, (tuple, list)):
        transposed = list(zip(*batch))
        return type(sample)(_collate_numpy(list(s)) for s in transposed)
    if isinstance(sample, dict):
        return {k: _collate_numpy([d[k] for d in batch]) for k in sample}
    return batch


def _np_tree_to_tensor(obj):
    if isinstance(obj, np.ndarray):
        return to_tensor(obj)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_np_tree_to_tensor(e) for e in obj)
    if isinstance(obj, dict):
        return {k: _np_tree_to_tensor(v) for k, v in obj.items()}
    return obj


class ConcatDataset(Dataset):
    """Concatenation of datasets (reference paddle.io.ConcatDataset)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        if not self.datasets:
            raise ValueError("ConcatDataset needs at least one dataset")
        self.cumulative_sizes = []
        total = 0
        for d in self.datasets:
            total += len(d)
            self.cumulative_sizes.append(total)

    def __len__(self):
        return self.cumulative_sizes[-1]

    def __getitem__(self, idx):
        if idx < 0:
            idx += len(self)
        import bisect
        di = bisect.bisect_right(self.cumulative_sizes, idx)
        prev = self.cumulative_sizes[di - 1] if di > 0 else 0
        return self.datasets[di][idx - prev]


class SubsetRandomSampler(Sampler):
    """Sample randomly from a fixed index subset (reference
    paddle.io.SubsetRandomSampler)."""

    def __init__(self, indices):
        if len(indices) == 0:
            raise ValueError("indices cannot be empty")
        self.indices = list(indices)

    def __iter__(self):
        import numpy as _np
        order = _np.random.permutation(len(self.indices))
        return iter(self.indices[i] for i in order)

    def __len__(self):
        return len(self.indices)
