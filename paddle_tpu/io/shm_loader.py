"""Multiprocess DataLoader workers over the native shared-memory ring.

The reference's worker pool pickles samples through multiprocessing queues
(/root/reference/python/paddle/io/dataloader/worker.py:273 _worker_loop,
dataloader_iter.py:358 _DataLoaderIterMultiProcess). Here worker processes
run dataset.__getitem__ + collate to numpy, then serialize each batch
straight into a POSIX shared-memory ring (paddle_tpu/core/cc/shm_ring.cc);
the main process reconstructs numpy arrays from the mapped pages with one
copy into the jax staging path. Batch *order* is restored by batch index
(the reference's out-of-order cache, dataloader_iter.py) — workers claim
ring slots in completion order, the consumer reorders by meta.

Batch wire format:
    u64 header_len | pickle(header) | payload (arrays back-to-back, each
    64B-aligned)
header = list of ("arr", dtype_str, shape, offset) / ("obj", pickled) in
flattened pytree order + the treedef spec.
"""
from __future__ import annotations

import os
import pickle
import struct
import time
import traceback
from typing import Any, List, Optional

import numpy as np

__all__ = ["ShmBatchLoader", "serialize_batch", "deserialize_batch"]

_ALIGN = 64

_META_ERROR = -2
_META_STOP = -3


def _flatten(obj, out):
    """Flatten nested tuples/lists/dicts of arrays into a spec tree +
    leaf list. Tensors are unwrapped to numpy by the caller."""
    if isinstance(obj, np.ndarray):
        out.append(obj)
        return ("a", len(out) - 1)
    if isinstance(obj, (list, tuple)):
        spec = [_flatten(e, out) for e in obj]
        return ("t" if isinstance(obj, tuple) else "l", spec)
    if isinstance(obj, dict):
        return ("d", {k: _flatten(v, out) for k, v in obj.items()})
    return ("o", obj)


def _unflatten(spec, leaves):
    kind = spec[0]
    if kind == "a":
        return leaves[spec[1]]
    if kind in ("t", "l"):
        seq = [_unflatten(s, leaves) for s in spec[1]]
        return tuple(seq) if kind == "t" else seq
    if kind == "d":
        return {k: _unflatten(v, leaves) for k, v in spec[1].items()}
    return spec[1]


def serialize_batch(batch) -> bytes:
    leaves: List[np.ndarray] = []
    spec = _flatten(batch, leaves)
    metas = []
    offset = 0
    for arr in leaves:
        offset = (offset + _ALIGN - 1) & ~(_ALIGN - 1)
        metas.append((str(arr.dtype), arr.shape, offset))
        offset += arr.nbytes
    header = pickle.dumps((spec, metas), protocol=pickle.HIGHEST_PROTOCOL)
    total = 8 + len(header)
    pay_base = (total + _ALIGN - 1) & ~(_ALIGN - 1)
    buf = bytearray(pay_base + offset)
    buf[:8] = struct.pack("<Q", len(header))
    buf[8:8 + len(header)] = header
    for arr, (_, _, off) in zip(leaves, metas):
        a = np.ascontiguousarray(arr)
        buf[pay_base + off:pay_base + off + a.nbytes] = a.tobytes()
    return bytes(buf)


def deserialize_batch(view) -> Any:
    (hlen,) = struct.unpack_from("<Q", view, 0)
    spec, metas = pickle.loads(bytes(view[8:8 + hlen]))
    pay_base = (8 + hlen + _ALIGN - 1) & ~(_ALIGN - 1)
    leaves = []
    for dtype_s, shape, off in metas:
        dt = np.dtype(dtype_s)
        n = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(view, dtype=dt, count=n,
                            offset=pay_base + off).reshape(shape)
        leaves.append(arr.copy())  # one copy out of shared pages
    return _unflatten(spec, leaves)


def _to_numpy_tree(obj):
    """Convert Tensors / jax arrays inside a collated batch to numpy so the
    batch can cross the process boundary."""
    from ..framework.core import Tensor
    if isinstance(obj, Tensor):
        return np.asarray(obj._value)
    if isinstance(obj, (list, tuple)):
        return type(obj)(_to_numpy_tree(e) for e in obj)
    if isinstance(obj, dict):
        return {k: _to_numpy_tree(v) for k, v in obj.items()}
    if hasattr(obj, "dtype") and hasattr(obj, "shape") and \
            not isinstance(obj, np.ndarray):
        return np.asarray(obj)
    return obj


def _worker_main(ring_name: str, dataset, batch_indices: List[List[int]],
                 worker_id: int, num_workers: int, collate_src,
                 worker_init_fn, seed: int, iterable_batch_size: int,
                 drop_last: bool):
    """Entry point of one spawned worker (the reference's _worker_loop
    analog). Map-style: processes batches worker_id::num_workers.
    Iterable-style: iterates its shard (get_worker_info-based)."""
    from paddle_tpu.core.native import ShmRing
    from . import _collate_numpy, IterableDataset, _worker_info

    # default collate builds numpy directly (jax arrays must not be
    # created inside workers); a user collate_fn runs as-is and its Tensor
    # leaves are converted by _to_numpy_tree below.
    collate = collate_src if collate_src is not None else _collate_numpy
    # reseed BOTH RNG families: vision transforms draw from `random`, and
    # fork start would otherwise clone the parent's state into every
    # worker every epoch
    import random as _pyrandom
    np.random.seed((seed + 7919 * worker_id) % (2 ** 31))
    _pyrandom.seed(seed * 2654435761 + worker_id)

    class _Info:
        id = worker_id
        num_workers_ = num_workers
        dataset_ = dataset

    info = _Info()
    info.num_workers = num_workers
    info.dataset = dataset
    _worker_info.info = info
    if worker_init_fn is not None:
        worker_init_fn(worker_id)

    ring = ShmRing(ring_name)
    # Map-style only: don't run further ahead of the consumer's in-order
    # emit point than this — bounds the consumer's reorder backlog when one
    # worker is slow. (Iterable shards can be uneven, where a gap in the
    # batch-index sequence would make this gate stall spuriously.)
    window = None if isinstance(dataset, IterableDataset) else \
        max(4 * num_workers, ring.n_slots, 8)

    def put(batch, bidx):
        payload = serialize_batch(_to_numpy_tree(collate(batch)))
        waited = 0.0
        while window is not None and bidx - ring.progress() > window:
            time.sleep(0.002)
            waited += 0.002
            if waited > 600.0:
                raise RuntimeError(
                    f"worker {worker_id}: consumer made no progress for "
                    f"600s before batch {bidx}; aborting")
        # a full ring means the consumer stopped draining; failing loudly
        # beats silently dropping the batch
        if not ring.write(payload, meta=bidx, timeout_ms=600000):
            raise RuntimeError(
                f"worker {worker_id}: ring full for 600s writing batch "
                f"{bidx}; consumer appears stalled")

    try:
        if isinstance(dataset, IterableDataset):
            it = iter(dataset)
            batch: list = []
            bidx = worker_id  # interleave indices across workers
            for sample in it:
                batch.append(sample)
                if len(batch) == iterable_batch_size:
                    put(batch, bidx)
                    bidx += num_workers
                    batch = []
            if batch and not drop_last:
                put(batch, bidx)
        else:
            for bidx in range(worker_id, len(batch_indices), num_workers):
                put([dataset[i] for i in batch_indices[bidx]], bidx)
    except Exception:
        err = traceback.format_exc().encode()
        try:
            ring.write(struct.pack("<Q", len(err)) + err, meta=_META_ERROR,
                       timeout_ms=60000)
        except Exception:
            pass
    finally:
        ring.producer_done()
        ring.close()


class ShmBatchLoader:
    """Consumer side: spawns workers, reads the ring, reorders batches."""

    def __init__(self, dataset, batch_indices: Optional[List[List[int]]],
                 num_workers: int, collate_fn, worker_init_fn=None,
                 slot_bytes: int = 64 << 20, n_slots: Optional[int] = None,
                 seed: int = 0, iterable_batch_size: int = 1,
                 drop_last: bool = False,
                 timeout: Optional[float] = None):
        import multiprocessing as mp
        from paddle_tpu.core.native import ShmRing

        self.num_workers = num_workers
        self._n_batches = len(batch_indices) if batch_indices is not None \
            else None
        # None/0 = no deadline (the reference's timeout=0 semantics);
        # liveness of worker processes is still checked every second.
        self._timeout_ms = int(timeout * 1000) if timeout else None
        self._ring_name = f"/pt_dl_{os.getpid()}_{id(self) & 0xffffff:x}"
        n_slots = n_slots or max(2 * num_workers, 4)
        self._ring = ShmRing(self._ring_name, slot_bytes=slot_bytes,
                             n_slots=n_slots, create=True)
        method = os.environ.get("PADDLE_TPU_WORKER_START", "auto")
        if method == "auto":
            # fork is faster but deadlocks if XLA threads already exist
            from jax._src import xla_bridge as _xb
            method = "spawn" if _xb.backends_are_initialized() else "fork"
        ctx = mp.get_context(method)
        self._procs = [
            ctx.Process(
                target=_worker_main,
                args=(self._ring_name, dataset, batch_indices, w, num_workers,
                      collate_fn, worker_init_fn, seed, iterable_batch_size,
                      drop_last),
                daemon=True)
            for w in range(num_workers)
        ]
        for p in self._procs:
            p.start()

    def __iter__(self):
        pending: dict = {}
        next_idx = 0
        emitted = 0
        self._waited_ms = 0
        while True:
            if self._n_batches is not None and emitted >= self._n_batches:
                break
            # serve from the reorder buffer first
            if next_idx in pending:
                batch = pending.pop(next_idx)
                next_idx += 1
                emitted += 1
                self._ring.set_progress(next_idx)
                yield batch
                continue
            if self._ring.producers_done() >= self.num_workers and \
                    self._ring.pending() == 0:
                # all workers finished; flush whatever remains in order
                for k in sorted(pending):
                    emitted += 1
                    yield pending[k]
                pending.clear()
                if self._n_batches is not None and \
                        emitted < self._n_batches:
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader epoch ended short: {emitted}/"
                        f"{self._n_batches} batches produced — a worker "
                        f"likely died without reporting an error")
                break
            # read in short slices so dead workers are detected promptly
            # (a spawn-crashed worker never reaches producer_done)
            got = self._ring.read_view(timeout_ms=1000)
            if got is None:
                dead = [p for p in self._procs
                        if not p.is_alive() and p.exitcode not in (0, None)]
                if dead and self._ring.producers_done() < self.num_workers \
                        and self._ring.pending() == 0:
                    codes = [p.exitcode for p in dead]
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader shm worker(s) died with exit codes "
                        f"{codes} before producing. If you use spawn start "
                        f"(default once JAX is initialized), the main "
                        f"script must be importable (guard entry code with "
                        f"if __name__ == '__main__') and the dataset "
                        f"picklable.")
                self._waited_ms += 1000
                if self._ring.producers_done() >= self.num_workers:
                    continue  # re-check drain condition
                if self._timeout_ms is not None and \
                        self._waited_ms >= self._timeout_ms:
                    self.shutdown()
                    raise RuntimeError(
                        f"DataLoader shm workers timed out after "
                        f"{self._timeout_ms}ms (alive="
                        f"{[p.is_alive() for p in self._procs]})")
                continue
            self._waited_ms = 0
            view, meta, ticket = got
            if meta == _META_ERROR:
                (elen,) = struct.unpack_from("<Q", view, 0)
                msg = bytes(view[8:8 + elen]).decode(errors="replace")
                self._ring.release(ticket)
                self.shutdown()
                raise RuntimeError(f"DataLoader worker failed:\n{msg}")
            # deserialize straight out of the mapped pages (single copy),
            # then hand the slot back to the producers
            batch = deserialize_batch(view)
            self._ring.release(ticket)
            if meta == next_idx:
                next_idx += 1
                emitted += 1
                self._ring.set_progress(next_idx)
                yield batch
            else:
                pending[meta] = batch
        self.shutdown()

    def shutdown(self):
        for p in self._procs:
            if p.is_alive():
                p.terminate()
        for p in self._procs:
            p.join(timeout=5)
        self._procs = []
        if self._ring is not None:
            self._ring.close()
            self._ring = None

    def __del__(self):
        try:
            self.shutdown()
        except Exception:
            pass
