"""paddle.cost_model — measured per-op cost model over static Programs
(reference: /root/reference/python/paddle/cost_model/cost_model.py —
CostModel.profile_measure via the C++ core.CostModel profiler,
static_cost_data/get_static_op_time over a shipped GPU benchmark JSON).

TPU-native design: there is no shipped benchmark table — op times are
MEASURED on the attached device. profile_measure() walks a recorded
static Program node-by-node, jit-compiles each node's kernel closure
once, and times steady-state executions (min over repeats, first call
excluded as compile). The result feeds the same consumers the reference
table does (auto-tuner cost models, pipeline stage balancing) with
numbers from the actual chip rather than a calibration file.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

__all__ = ["CostModel"]


class CostModel:
    def __init__(self):
        self._profile: Optional[Dict[str, dict]] = None

    def build_program(self):
        """Tiny demo program (reference cost_model.py:build_program)."""
        import paddle_tpu as paddle
        from paddle_tpu import static

        paddle.enable_static()
        try:
            main = static.Program()
            startup = static.Program()
            with static.program_guard(main, startup):
                data = static.data("X", [10, 1], "float32")
                from paddle_tpu import nn
                hidden = nn.Linear(1, 10)(data)
                paddle.mean(hidden)
            return startup, main
        finally:
            paddle.disable_static()

    def profile_measure(self, startup_program, main_program,
                        device: str = "tpu",
                        fetch_cost_list: List[str] = ("time",),
                        feed: Optional[dict] = None,
                        repeats: int = 3) -> Dict[str, dict]:
        """Measure every node of ``main_program`` on the device.

        Returns {op_name: {"op_time": ms_total, "calls": n,
        "per_call": [ms...]}} and caches it for get_static_op_time().
        Feed variables default to zeros of their declared shapes (dims
        <=0 become 1)."""
        import jax

        from ..framework.core import Tensor
        from ..static.program import Variable

        feed = dict(feed or {})
        env: Dict[int, object] = {}
        for name, var in main_program.feeds.items():
            if name in feed:
                val = np.asarray(feed[name], dtype=var.aval.dtype)
            else:
                shape = tuple(d if d and d > 0 else 1
                              for d in (getattr(var, "_declared_shape",
                                                None) or var.aval.shape))
                val = np.zeros(shape, var.aval.dtype)
            env[id(var)] = jax.numpy.asarray(val)

        def value_of(x):
            if isinstance(x, Variable):
                return env[id(x)]
            if isinstance(x, Tensor):
                return x._value
            return x

        from ..jit.partial_capture import _fp_const, _fp_fn
        from ..static.executor import resolve_node
        from ..utils.timing import timed_dispatch_diff

        # instance-level so repeated profile_measure calls (and repeated
        # nodes within one) reuse compiled kernels instead of minting a
        # fresh jit callable per visit (flightcheck FC202)
        jit_cache: Dict[tuple, object] = \
            self.__dict__.setdefault("_jit_cache", {})
        profile: Dict[str, dict] = {}
        for node in main_program.nodes:
            fn, vals = resolve_node(main_program, node, value_of)
            # reuse the compiled kernel across structurally identical
            # nodes (same closure code + captured constants + shapes) —
            # a Program with N identical layers compiles once, not N
            # times. Unfingerprintable closures fall back to their own
            # jit (jax caches by fn identity).
            fp = _fp_fn(fn)
            kw_fp = _fp_const(node.kwargs)
            key = None
            if fp is not None and kw_fp is not None:
                key = (fp, kw_fp, tuple(
                    (getattr(v, "shape", None), str(getattr(v, "dtype",
                                                            None)))
                    for v in vals))
            if key is None:
                # unfingerprintable closure: fall back to identity of
                # (node, resolved fn) — still one compile per node per
                # kernel instead of a fresh jit callable (and recompile)
                # per profile run (flightcheck FC202). Both OBJECTS are
                # the key (identity hash, kept alive by the entry), so
                # a recycled id() can never alias a dead node's kernel,
                # and a decomposition override installing a NEW fn for
                # the same node misses the cache instead of serving the
                # stale pre-override kernel.
                key = ("node", node, fn)
            jfn = jit_cache.get(key)
            if jfn is None:
                if len(jit_cache) > 512:
                    # bound the instance-level cache: profiling many
                    # distinct programs must not pin every dead
                    # program's nodes/executables forever
                    jit_cache.clear()
                jfn = jax.jit(lambda *xs, _fn=fn, _kw=node.kwargs:
                              _fn(*xs, **_kw))
                jit_cache[key] = jfn
            out = jfn(*vals)        # lazy env values for downstream
            # fetch-forced dispatch-count differencing with min-over-
            # repeats and a positive floor — the one timing recipe
            # (utils/timing.py; its own warm call proves compile)
            best = timed_dispatch_diff(
                jfn, tuple(vals), calls=(1, 1 + max(1, repeats)),
                repeats=2)
            outs = list(out) if isinstance(out, (tuple, list)) else [out]
            for v, o in zip(node.out_vars, outs):
                env[id(v)] = o
            rec = profile.setdefault(
                node.op_name, {"op_time": 0.0, "calls": 0,
                               "per_call": []})
            rec["op_time"] += best * 1e3
            rec["calls"] += 1
            rec["per_call"].append(round(best * 1e3, 6))
        for rec in profile.values():
            rec["op_time"] = round(rec["op_time"], 6)
        self._profile = profile
        return profile

    def static_cost_data(self):
        """The measured table (reference loads a pre-benchmarked JSON;
        here the data comes from the last profile_measure run)."""
        if self._profile is None:
            raise RuntimeError(
                "no cost data measured yet — run profile_measure() "
                "first (this build measures the real device instead of "
                "shipping a GPU calibration file)")
        return self._profile

    def get_static_op_time(self, op_name: str, forward: bool = True,
                           dtype: str = "float32") -> dict:
        if op_name is None or op_name == "":
            raise ValueError(
                "op_name should not be empty when you want to get "
                "static op time")
        data = self.static_cost_data()
        if op_name not in data:
            return {}
        rec = data[op_name]
        return {"op_time": rec["op_time"] / max(rec["calls"], 1),
                "config": {"dtype": dtype, "calls": rec["calls"]}}
