"""Distribution base (parity:
/root/reference/python/paddle/distribution/distribution.py).

Samples are returned as framework Tensors with shape
``sample_shape + batch_shape + event_shape``; log_prob/entropy are pure
jnp computations so they trace/fuse under jit.
"""
from __future__ import annotations

from typing import Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, default_generator


def _as_jnp(x, dtype=None):
    """Coerce Tensor / python number / ndarray to a jnp array."""
    if isinstance(x, Tensor):
        v = x._value
    else:
        v = x
    arr = jnp.asarray(v)
    if dtype is not None:
        arr = arr.astype(dtype)
    elif not jnp.issubdtype(arr.dtype, jnp.floating):
        arr = arr.astype(jnp.float32)
    return arr


def _sample_shape(shape) -> Tuple[int, ...]:
    if shape is None:
        return ()
    if isinstance(shape, (int, np.integer)):
        return (int(shape),)
    return tuple(int(s) for s in shape)


def _next_key():
    return default_generator.next_key()


class Distribution:
    def __init__(self, batch_shape: Sequence[int] = (),
                 event_shape: Sequence[int] = ()):
        self._batch_shape = tuple(int(s) for s in batch_shape)
        self._event_shape = tuple(int(s) for s in event_shape)

    @property
    def batch_shape(self) -> Tuple[int, ...]:
        return self._batch_shape

    @property
    def event_shape(self) -> Tuple[int, ...]:
        return self._event_shape

    @property
    def mean(self):
        raise NotImplementedError

    @property
    def variance(self):
        raise NotImplementedError

    @property
    def stddev(self):
        return Tensor(jnp.sqrt(_as_jnp(self.variance)))

    def sample(self, shape=()):
        raise NotImplementedError

    def rsample(self, shape=()):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def prob(self, value):
        return Tensor(jnp.exp(_as_jnp(self.log_prob(value))))

    # paddle's Bernoulli/Categorical expose probs() as pmf evaluation
    def probs(self, value):
        return self.prob(value)

    def entropy(self):
        raise NotImplementedError

    def cdf(self, value):
        raise NotImplementedError

    def icdf(self, value):
        raise NotImplementedError

    def kl_divergence(self, other: "Distribution"):
        from .kl import kl_divergence
        return kl_divergence(self, other)

    def _extend_shape(self, sample_shape) -> Tuple[int, ...]:
        return (_sample_shape(sample_shape) + self.batch_shape
                + self.event_shape)
