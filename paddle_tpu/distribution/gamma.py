"""Gamma (parity: /root/reference/python/paddle/distribution/gamma.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammainc, gammaln

from ..framework.core import Tensor
from .distribution import _as_jnp, _next_key, _sample_shape
from .exponential_family import ExponentialFamily


class Gamma(ExponentialFamily):
    def __init__(self, concentration, rate):
        self.concentration = _as_jnp(concentration)
        self.rate = _as_jnp(rate)
        self.concentration, self.rate = jnp.broadcast_arrays(
            self.concentration, self.rate)
        super().__init__(batch_shape=self.concentration.shape)

    @property
    def mean(self):
        return Tensor(self.concentration / self.rate)

    @property
    def variance(self):
        return Tensor(self.concentration / jnp.square(self.rate))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        g = jax.random.gamma(_next_key(), self.concentration, shp)
        return Tensor(g / self.rate)

    def log_prob(self, value):
        v = _as_jnp(value)
        a, b = self.concentration, self.rate
        return Tensor(a * jnp.log(b) + (a - 1) * jnp.log(v) - b * v
                      - gammaln(a))

    def entropy(self):
        a, b = self.concentration, self.rate
        return Tensor(a - jnp.log(b) + gammaln(a) + (1 - a) * digamma(a))

    def cdf(self, value):
        v = _as_jnp(value)
        return Tensor(gammainc(self.concentration, self.rate * v))
