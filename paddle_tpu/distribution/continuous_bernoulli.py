"""ContinuousBernoulli (parity:
/root/reference/python/paddle/distribution/continuous_bernoulli.py).

pdf(x; λ) = C(λ) λ^x (1-λ)^(1-x) on [0, 1], with normalizer
C(λ) = 2 atanh(1-2λ) / (1-2λ) for λ ≠ 0.5, = 2 for λ = 0.5.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class ContinuousBernoulli(Distribution):
    def __init__(self, probs, lims=(0.499, 0.501)):
        self.probs_ = jnp.clip(_as_jnp(probs), 1e-6, 1 - 1e-6)
        self._lims = lims
        super().__init__(batch_shape=self.probs_.shape)

    def _outside_unstable(self):
        return (self.probs_ < self._lims[0]) | (self.probs_ > self._lims[1])

    def _cut_probs(self):
        # pin near-0.5 λ to the stable region; Taylor used there instead
        return jnp.where(self._outside_unstable(), self.probs_,
                         jnp.full_like(self.probs_, self._lims[0]))

    def _log_norm(self):
        lam = self._cut_probs()
        log_norm = jnp.log(jnp.abs(2 * jnp.arctanh(1 - 2 * lam))) \
            - jnp.log(jnp.abs(1 - 2 * lam))
        x = self.probs_ - 0.5
        taylor = jnp.log(2.0) + (4.0 / 3.0 + 104.0 / 45.0 * x * x) * x * x
        return jnp.where(self._outside_unstable(), log_norm, taylor)

    @property
    def mean(self):
        lam = self._cut_probs()
        m = lam / (2 * lam - 1) + 1 / (2 * jnp.arctanh(1 - 2 * lam))
        x = self.probs_ - 0.5
        taylor = 0.5 + (1.0 / 3.0 + 16.0 / 45.0 * x * x) * x
        return Tensor(jnp.where(self._outside_unstable(), m, taylor))

    @property
    def variance(self):
        lam = self._cut_probs()
        t = jnp.arctanh(1 - 2 * lam)
        v = lam * (lam - 1) / jnp.square(1 - 2 * lam) + 1 / (4 * t * t)
        x = self.probs_ - 0.5
        taylor = 1.0 / 12.0 + (1.0 / 15.0 - 128.0 / 945.0 * x * x) * x * x
        return Tensor(jnp.where(self._outside_unstable(), v, taylor))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shp, self.probs_.dtype,
                               minval=1e-6, maxval=1 - 1e-6)
        return self.icdf(Tensor(u))

    def log_prob(self, value):
        v = _as_jnp(value)
        lam = self.probs_
        return Tensor(v * jnp.log(lam) + (1 - v) * jnp.log1p(-lam)
                      + self._log_norm())

    def entropy(self):
        lam = self.probs_
        m = _as_jnp(self.mean)
        return Tensor(-(m * jnp.log(lam) + (1 - m) * jnp.log1p(-lam)
                        + self._log_norm()))

    def cdf(self, value):
        v = _as_jnp(value)
        lam = self._cut_probs()
        num = jnp.power(lam, v) * jnp.power(1 - lam, 1 - v) + lam - 1
        c = num / (2 * lam - 1)
        out = jnp.where(self._outside_unstable(), c, v)
        return Tensor(jnp.clip(out, 0.0, 1.0))

    def icdf(self, value):
        u = _as_jnp(value)
        lam = self._cut_probs()
        x = (jnp.log1p(u * (2 * lam - 1) / (1 - lam))
             / (jnp.log(lam) - jnp.log1p(-lam)))
        return Tensor(jnp.where(self._outside_unstable(), x, u))
