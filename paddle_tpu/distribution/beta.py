"""Beta (parity: /root/reference/python/paddle/distribution/beta.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import betaln, digamma

from ..framework.core import Tensor
from .dirichlet import Dirichlet
from .distribution import _as_jnp, _next_key, _sample_shape
from .exponential_family import ExponentialFamily


class Beta(ExponentialFamily):
    def __init__(self, alpha, beta):
        self.alpha = _as_jnp(alpha)
        self.beta = _as_jnp(beta)
        self.alpha, self.beta = jnp.broadcast_arrays(self.alpha, self.beta)
        super().__init__(batch_shape=self.alpha.shape)

    @property
    def mean(self):
        return Tensor(self.alpha / (self.alpha + self.beta))

    @property
    def variance(self):
        s = self.alpha + self.beta
        return Tensor(self.alpha * self.beta / (s * s * (s + 1)))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        return Tensor(jax.random.beta(_next_key(), self.alpha, self.beta,
                                      shp))

    def log_prob(self, value):
        v = _as_jnp(value)
        return Tensor((self.alpha - 1) * jnp.log(v)
                      + (self.beta - 1) * jnp.log1p(-v)
                      - betaln(self.alpha, self.beta))

    def entropy(self):
        a, b = self.alpha, self.beta
        return Tensor(betaln(a, b) - (a - 1) * digamma(a)
                      - (b - 1) * digamma(b)
                      + (a + b - 2) * digamma(a + b))
