"""Categorical (parity:
/root/reference/python/paddle/distribution/categorical.py).

Paddle's Categorical takes unnormalized non-negative ``logits`` that are
interpreted as relative weights (it normalizes by the sum, not softmax).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class Categorical(Distribution):
    def __init__(self, logits, name=None):
        self.logits = _as_jnp(logits)
        self._p = self.logits / jnp.sum(self.logits, -1, keepdims=True)
        super().__init__(batch_shape=self.logits.shape[:-1])

    @property
    def probs_param(self):
        return Tensor(self._p)

    def sample(self, shape=()):
        shp = _sample_shape(shape)
        logp = jnp.log(jnp.clip(self._p, 1e-38))
        out = jax.random.categorical(
            _next_key(), logp, axis=-1,
            shape=shp + self.batch_shape)
        return Tensor(out.astype(jnp.int64) if jax.config.jax_enable_x64
                      else out)

    def log_prob(self, value):
        idx = _as_jnp(value, dtype=jnp.int32).astype(jnp.int32)
        if self.batch_shape == ():
            picked = self._p[idx]
        else:
            idx_b = jnp.broadcast_to(idx, self.batch_shape)
            picked = jnp.take_along_axis(
                self._p, idx_b[..., None], axis=-1)[..., 0]
        return Tensor(jnp.log(jnp.clip(picked, 1e-38)))

    def probs(self, value):
        return Tensor(jnp.exp(_as_jnp(self.log_prob(value))))

    def entropy(self):
        p = self._p
        return Tensor(-jnp.sum(p * jnp.log(jnp.clip(p, 1e-38)), -1))

    def kl_divergence(self, other: "Categorical"):
        from .kl import kl_divergence
        return kl_divergence(self, other)
