"""Bernoulli (parity:
/root/reference/python/paddle/distribution/bernoulli.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape
from .exponential_family import ExponentialFamily

_EPS = 1e-7


class Bernoulli(ExponentialFamily):
    def __init__(self, probs, name=None):
        self.probs_ = jnp.clip(_as_jnp(probs), _EPS, 1 - _EPS)
        self.logits = jnp.log(self.probs_) - jnp.log1p(-self.probs_)
        # paddle parity: .probs is the parameter tensor (instance attr
        # shadows the base class's pmf-evaluation method)
        self.probs = Tensor(self.probs_)
        super().__init__(batch_shape=self.probs_.shape)

    @property
    def mean(self):
        return Tensor(self.probs_)

    @property
    def variance(self):
        return Tensor(self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        return Tensor(jax.random.bernoulli(_next_key(), self.probs_, shp)
                      .astype(self.probs_.dtype))

    def rsample(self, shape=(), temperature=1.0):
        """Reparameterized relaxed sample (Gumbel-softmax / concrete)."""
        shp = _sample_shape(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shp, self.probs_.dtype,
                               minval=_EPS, maxval=1 - _EPS)
        logistic = jnp.log(u) - jnp.log1p(-u)
        return Tensor(jax.nn.sigmoid((self.logits + logistic) / temperature))

    def log_prob(self, value):
        v = _as_jnp(value)
        return Tensor(v * jnp.log(self.probs_)
                      + (1 - v) * jnp.log1p(-self.probs_))

    def entropy(self):
        p = self.probs_
        return Tensor(-(p * jnp.log(p) + (1 - p) * jnp.log1p(-p)))

    def cdf(self, value):
        v = _as_jnp(value)
        out = jnp.where(v < 0, 0.0, jnp.where(v < 1, 1 - self.probs_, 1.0))
        return Tensor(out)
