"""Independent (parity:
/root/reference/python/paddle/distribution/independent.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp


class Independent(Distribution):
    """Reinterprets the rightmost batch dims of ``base`` as event dims."""

    def __init__(self, base: Distribution, reinterpreted_batch_rank: int):
        if reinterpreted_batch_rank > len(base.batch_shape):
            raise ValueError("reinterpreted_batch_rank exceeds batch rank")
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        n = len(base.batch_shape) - self.reinterpreted_batch_rank
        super().__init__(
            batch_shape=base.batch_shape[:n],
            event_shape=base.batch_shape[n:] + base.event_shape)

    @property
    def mean(self):
        return self.base.mean

    @property
    def variance(self):
        return self.base.variance

    def sample(self, shape=()):
        return self.base.sample(shape)

    def rsample(self, shape=()):
        return self.base.rsample(shape)

    def _sum_rightmost(self, x):
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(x, axis=axes) if axes else x

    def log_prob(self, value):
        return Tensor(self._sum_rightmost(_as_jnp(self.base.log_prob(value))))

    def entropy(self):
        return Tensor(self._sum_rightmost(_as_jnp(self.base.entropy())))
