"""Multinomial (parity:
/root/reference/python/paddle/distribution/multinomial.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class Multinomial(Distribution):
    def __init__(self, total_count: int, probs):
        self.total_count = int(total_count)
        p = _as_jnp(probs)
        self.probs_ = p / jnp.sum(p, -1, keepdims=True)
        super().__init__(batch_shape=p.shape[:-1], event_shape=p.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        logp = jnp.log(jnp.clip(self.probs_, 1e-38))
        # draw total_count categoricals, histogram them (one-hot sum) —
        # static shapes, MXU/VPU friendly, no host loop
        draws = jax.random.categorical(
            _next_key(), logp, axis=-1,
            shape=(self.total_count,) + shp)
        onehot = jax.nn.one_hot(draws, self.probs_.shape[-1],
                                dtype=self.probs_.dtype)
        return Tensor(jnp.sum(onehot, axis=0))

    def log_prob(self, value):
        v = _as_jnp(value)
        logp = jnp.log(jnp.clip(self.probs_, 1e-38))
        return Tensor(gammaln(jnp.asarray(self.total_count + 1.0))
                      - jnp.sum(gammaln(v + 1), -1)
                      + jnp.sum(v * logp, -1))

    def entropy(self):
        # exact: H = -lgamma(n+1) + Σ_i E[lgamma(X_i+1)] - n Σ p log p
        # with X_i ~ Binomial(n, p_i); E[·] by summation over the static
        # support 0..n (total_count is a python int)
        n, p = self.total_count, jnp.clip(self.probs_, 1e-38)
        ks = jnp.arange(0, n + 1, dtype=p.dtype)
        ks = ks[(...,) + (None,) * p.ndim]
        logc = (gammaln(jnp.asarray(n + 1.0)) - gammaln(ks + 1)
                - gammaln(n - ks + 1))
        log_binom_pmf = logc + ks * jnp.log(p) + (n - ks) * jnp.log1p(-p)
        e_lgamma = jnp.sum(jnp.exp(log_binom_pmf) * gammaln(ks + 1), 0)
        ent1 = -jnp.sum(p * jnp.log(p), -1)
        return Tensor(-gammaln(jnp.asarray(n + 1.0))
                      + jnp.sum(e_lgamma, -1) + n * ent1)
