"""Binomial (parity:
/root/reference/python/paddle/distribution/binomial.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.scipy.special import gammaln

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class Binomial(Distribution):
    def __init__(self, total_count, probs):
        self.total_count = _as_jnp(total_count)
        self.probs_ = _as_jnp(probs)
        self.total_count, self.probs_ = jnp.broadcast_arrays(
            self.total_count, self.probs_)
        self.probs = Tensor(self.probs_)  # parameter tensor, paddle parity
        super().__init__(batch_shape=self.probs_.shape)

    @property
    def mean(self):
        return Tensor(self.total_count * self.probs_)

    @property
    def variance(self):
        return Tensor(self.total_count * self.probs_ * (1 - self.probs_))

    def sample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        n = jnp.broadcast_to(self.total_count, shp)
        p = jnp.broadcast_to(self.probs_, shp)
        out = jax.random.binomial(_next_key(), n, p, shape=shp)
        return Tensor(out.astype(self.probs_.dtype))

    def log_prob(self, value):
        k = _as_jnp(value)
        n, p = self.total_count, jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        logc = gammaln(n + 1) - gammaln(k + 1) - gammaln(n - k + 1)
        return Tensor(logc + k * jnp.log(p) + (n - k) * jnp.log1p(-p))

    _ENTROPY_EXACT_MAX = 1024

    def entropy(self):
        n, p = self.total_count, jnp.clip(self.probs_, 1e-7, 1 - 1e-7)
        try:
            nmax = int(np.max(np.asarray(n)))
        except Exception:
            nmax = None  # traced total_count: no static support bound
        if nmax is not None and nmax <= self._ENTROPY_EXACT_MAX:
            # exact by summation over support (static 1+max bound)
            ks = jnp.arange(0, nmax + 1, dtype=self.probs_.dtype)
            ks = ks[(...,) + (None,) * self.probs_.ndim]
            logc = gammaln(n + 1) - gammaln(ks + 1) - gammaln(n - ks + 1)
            logpmf = logc + ks * jnp.log(p) + (n - ks) * jnp.log1p(-p)
            logpmf = jnp.where(ks <= n, logpmf, -jnp.inf)
            pmf = jnp.exp(logpmf)
            return Tensor(-jnp.sum(pmf * jnp.where(jnp.isfinite(logpmf),
                                                   logpmf, 0.0), axis=0))
        # large or traced n: de Moivre–Laplace (Gaussian) approximation
        return Tensor(0.5 * jnp.log(2 * jnp.pi * jnp.e * n * p * (1 - p)))
