"""Exponential (parity:
/root/reference/python/paddle/distribution/exponential.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import _as_jnp, _next_key, _sample_shape
from .exponential_family import ExponentialFamily


class Exponential(ExponentialFamily):
    def __init__(self, rate):
        self.rate = _as_jnp(rate)
        super().__init__(batch_shape=self.rate.shape)

    @property
    def mean(self):
        return Tensor(1.0 / self.rate)

    @property
    def variance(self):
        return Tensor(1.0 / jnp.square(self.rate))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        e = jax.random.exponential(_next_key(), shp, self.rate.dtype)
        return Tensor(e / self.rate)

    def log_prob(self, value):
        v = _as_jnp(value)
        return Tensor(jnp.log(self.rate) - self.rate * v)

    def entropy(self):
        return Tensor(1.0 - jnp.log(self.rate))

    def cdf(self, value):
        v = _as_jnp(value)
        return Tensor(-jnp.expm1(-self.rate * v))

    def icdf(self, value):
        v = _as_jnp(value)
        return Tensor(-jnp.log1p(-v) / self.rate)
