"""Poisson (parity:
/root/reference/python/paddle/distribution/poisson.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import gammaln

from ..framework.core import Tensor
from .distribution import _as_jnp, _next_key, _sample_shape
from .exponential_family import ExponentialFamily


class Poisson(ExponentialFamily):
    def __init__(self, rate):
        self.rate = _as_jnp(rate)
        super().__init__(batch_shape=self.rate.shape)

    @property
    def mean(self):
        return Tensor(self.rate)

    @property
    def variance(self):
        return Tensor(self.rate)

    def sample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        out = jax.random.poisson(_next_key(), self.rate, shp)
        return Tensor(out.astype(self.rate.dtype))

    def log_prob(self, value):
        v = _as_jnp(value)
        return Tensor(v * jnp.log(jnp.clip(self.rate, 1e-38)) - self.rate
                      - gammaln(v + 1))

    def entropy(self):
        # series approximation (matches reference's truncated evaluation
        # for moderate rate): H ≈ 0.5 log(2πeλ) - corrections
        lam = self.rate
        h = (0.5 * jnp.log(2 * jnp.pi * jnp.e * lam)
             - 1 / (12 * lam) - 1 / (24 * lam ** 2) - 19 / (360 * lam ** 3))
        # exact for small λ by summation over k
        ks = jnp.arange(0, 32, dtype=lam.dtype)
        logpmf = (ks[(...,) + (None,) * lam.ndim] * jnp.log(
            jnp.clip(lam, 1e-38)) - lam - gammaln(
            ks[(...,) + (None,) * lam.ndim] + 1))
        pmf = jnp.exp(logpmf)
        h_exact = -jnp.sum(pmf * logpmf, axis=0)
        return Tensor(jnp.where(lam < 10.0, h_exact, h))
