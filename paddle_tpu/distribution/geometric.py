"""Geometric (parity:
/root/reference/python/paddle/distribution/geometric.py).

Paddle convention: support k = 0, 1, 2, ... (number of failures before
the first success); pmf(k) = (1-p)^k p.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape

_EPS = 1e-7


class Geometric(Distribution):
    def __init__(self, probs):
        self.probs_ = jnp.clip(_as_jnp(probs), _EPS, 1 - _EPS)
        self.probs = Tensor(self.probs_)  # parameter tensor, paddle parity
        super().__init__(batch_shape=self.probs_.shape)

    @property
    def mean(self):
        return Tensor((1 - self.probs_) / self.probs_)

    @property
    def variance(self):
        return Tensor((1 - self.probs_) / jnp.square(self.probs_))

    @property
    def stddev(self):
        return Tensor(jnp.sqrt(1 - self.probs_) / self.probs_)

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shp, self.probs_.dtype,
                               minval=_EPS, maxval=1.0)
        return Tensor(jnp.floor(jnp.log(u) / jnp.log1p(-self.probs_)))

    def log_prob(self, value):
        k = _as_jnp(value)
        return Tensor(k * jnp.log1p(-self.probs_) + jnp.log(self.probs_))

    def pmf(self, k):
        return Tensor(jnp.exp(_as_jnp(self.log_prob(k))))

    def log_pmf(self, k):
        return self.log_prob(k)

    def entropy(self):
        p = self.probs_
        q = 1 - p
        return Tensor(-(q * jnp.log(q) + p * jnp.log(p)) / p)

    def cdf(self, k):
        kk = _as_jnp(k)
        return Tensor(1 - jnp.power(1 - self.probs_, kk + 1))
