"""Dirichlet (parity:
/root/reference/python/paddle/distribution/dirichlet.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln

from ..framework.core import Tensor
from .distribution import _as_jnp, _next_key, _sample_shape
from .exponential_family import ExponentialFamily


class Dirichlet(ExponentialFamily):
    def __init__(self, concentration):
        self.concentration = _as_jnp(concentration)
        super().__init__(batch_shape=self.concentration.shape[:-1],
                         event_shape=self.concentration.shape[-1:])

    @property
    def mean(self):
        return Tensor(self.concentration
                      / jnp.sum(self.concentration, -1, keepdims=True))

    @property
    def variance(self):
        a0 = jnp.sum(self.concentration, -1, keepdims=True)
        m = self.concentration / a0
        return Tensor(m * (1 - m) / (a0 + 1))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        return Tensor(jax.random.dirichlet(_next_key(), self.concentration,
                                           shp))

    def log_prob(self, value):
        v = _as_jnp(value)
        a = self.concentration
        return Tensor(jnp.sum((a - 1) * jnp.log(v), -1)
                      + gammaln(jnp.sum(a, -1)) - jnp.sum(gammaln(a), -1))

    def entropy(self):
        a = self.concentration
        a0 = jnp.sum(a, -1)
        k = a.shape[-1]
        lnB = jnp.sum(gammaln(a), -1) - gammaln(a0)
        return Tensor(lnB + (a0 - k) * digamma(a0)
                      - jnp.sum((a - 1) * digamma(a), -1))
