"""MultivariateNormal (parity:
/root/reference/python/paddle/distribution/multivariate_normal.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class MultivariateNormal(Distribution):
    def __init__(self, loc, covariance_matrix=None, precision_matrix=None,
                 scale_tril=None):
        self.loc = _as_jnp(loc)
        if scale_tril is not None:
            self._scale_tril = _as_jnp(scale_tril)
        elif covariance_matrix is not None:
            self._scale_tril = jnp.linalg.cholesky(_as_jnp(covariance_matrix))
        elif precision_matrix is not None:
            prec = _as_jnp(precision_matrix)
            self._scale_tril = jnp.linalg.cholesky(jnp.linalg.inv(prec))
        else:
            raise ValueError(
                "one of covariance_matrix / precision_matrix / scale_tril "
                "must be specified")
        batch = jnp.broadcast_shapes(self.loc.shape[:-1],
                                     self._scale_tril.shape[:-2])
        super().__init__(batch_shape=batch,
                         event_shape=self.loc.shape[-1:])

    @property
    def scale_tril(self):
        return Tensor(self._scale_tril)

    @property
    def covariance_matrix(self):
        L = self._scale_tril
        return Tensor(L @ jnp.swapaxes(L, -1, -2))

    @property
    def precision_matrix(self):
        return Tensor(jnp.linalg.inv(_as_jnp(self.covariance_matrix)))

    @property
    def mean(self):
        return Tensor(jnp.broadcast_to(self.loc,
                                       self.batch_shape + self.event_shape))

    @property
    def variance(self):
        var = jnp.square(self._scale_tril).sum(-1)
        return Tensor(jnp.broadcast_to(var,
                                       self.batch_shape + self.event_shape))

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape + self.event_shape
        eps = jax.random.normal(_next_key(), shp, self.loc.dtype)
        return Tensor(self.loc + jnp.einsum('...ij,...j->...i',
                                            self._scale_tril, eps))

    def log_prob(self, value):
        v = _as_jnp(value)
        diff = v - self.loc
        L = jnp.broadcast_to(self._scale_tril,
                             diff.shape[:-1] + self._scale_tril.shape[-2:])
        # solve L y = diff  →  maha = |y|^2
        y = jax.scipy.linalg.solve_triangular(
            L, diff[..., None], lower=True)[..., 0]
        maha = jnp.sum(jnp.square(y), -1)
        half_logdet = jnp.sum(jnp.log(
            jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), -1)
        k = self.event_shape[0]
        return Tensor(-0.5 * (maha + k * math.log(2 * math.pi))
                      - half_logdet)

    def entropy(self):
        half_logdet = jnp.sum(jnp.log(
            jnp.diagonal(self._scale_tril, axis1=-2, axis2=-1)), -1)
        k = self.event_shape[0]
        out = 0.5 * k * (1 + math.log(2 * math.pi)) + half_logdet
        return Tensor(jnp.broadcast_to(out, self.batch_shape))
