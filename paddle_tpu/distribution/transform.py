"""Bijective transforms (parity:
/root/reference/python/paddle/distribution/transform.py).

All transforms are pure jnp functions of their input — composable, jit-
and vjp-friendly; log-det-Jacobians are closed form.
"""
from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import _as_jnp

__all__ = [
    'Transform', 'AbsTransform', 'AffineTransform', 'ChainTransform',
    'ExpTransform', 'IndependentTransform', 'PowerTransform',
    'ReshapeTransform', 'SigmoidTransform', 'SoftmaxTransform',
    'StackTransform', 'StickBreakingTransform', 'TanhTransform',
]


def _wrap(fn):
    def inner(self, x, *args):
        return Tensor(fn(self, _as_jnp(x), *(_as_jnp(a) for a in args)))
    return inner


class Transform:
    _event_rank = 0  # rank of the event this transform acts on

    def forward(self, x):
        return Tensor(self._forward(_as_jnp(x)))

    def inverse(self, y):
        return Tensor(self._inverse(_as_jnp(y)))

    def forward_log_det_jacobian(self, x):
        return Tensor(self._forward_log_det_jacobian(_as_jnp(x)))

    def inverse_log_det_jacobian(self, y):
        y = _as_jnp(y)
        return Tensor(-self._forward_log_det_jacobian(self._inverse(y)))

    def forward_shape(self, shape):
        return tuple(shape)

    def inverse_shape(self, shape):
        return tuple(shape)

    def __call__(self, x):
        return self.forward(x)

    # subclass hooks (jnp in / jnp out)
    def _forward(self, x):
        raise NotImplementedError

    def _inverse(self, y):
        raise NotImplementedError

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError


class AbsTransform(Transform):
    """y = |x| — not injective; inverse returns the positive branch."""

    def _forward(self, x):
        return jnp.abs(x)

    def _inverse(self, y):
        return y

    def _forward_log_det_jacobian(self, x):
        return jnp.zeros_like(x)


class AffineTransform(Transform):
    def __init__(self, loc, scale):
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)

    def _forward(self, x):
        return self.loc + self.scale * x

    def _inverse(self, y):
        return (y - self.loc) / self.scale

    def _forward_log_det_jacobian(self, x):
        return jnp.broadcast_to(jnp.log(jnp.abs(self.scale)), x.shape)


class ExpTransform(Transform):
    def _forward(self, x):
        return jnp.exp(x)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        return x


class PowerTransform(Transform):
    def __init__(self, power):
        self.power = _as_jnp(power)

    def _forward(self, x):
        return jnp.power(x, self.power)

    def _inverse(self, y):
        return jnp.power(y, 1.0 / self.power)

    def _forward_log_det_jacobian(self, x):
        return jnp.log(jnp.abs(self.power * jnp.power(x, self.power - 1)))


class SigmoidTransform(Transform):
    def _forward(self, x):
        return jax.nn.sigmoid(x)

    def _inverse(self, y):
        return jnp.log(y) - jnp.log1p(-y)

    def _forward_log_det_jacobian(self, x):
        return -jax.nn.softplus(-x) - jax.nn.softplus(x)


class TanhTransform(Transform):
    def _forward(self, x):
        return jnp.tanh(x)

    def _inverse(self, y):
        return jnp.arctanh(jnp.clip(y, -1 + 1e-7, 1 - 1e-7))

    def _forward_log_det_jacobian(self, x):
        # log(1 - tanh(x)^2) = 2 (log2 - x - softplus(-2x))
        return 2.0 * (jnp.log(2.0) - x - jax.nn.softplus(-2.0 * x))


class SoftmaxTransform(Transform):
    """Acts on the last axis; inverse is log (up to an additive const)."""
    _event_rank = 1

    def _forward(self, x):
        return jax.nn.softmax(x, axis=-1)

    def _inverse(self, y):
        return jnp.log(y)

    def _forward_log_det_jacobian(self, x):
        raise NotImplementedError(
            "SoftmaxTransform is not bijective; no log-det-Jacobian")


class StickBreakingTransform(Transform):
    """R^{K-1} → simplex^K via stick breaking."""
    _event_rank = 1

    def _forward(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        z = jax.nn.sigmoid(x - jnp.log(offset))
        zpad = jnp.concatenate([z, jnp.ones_like(z[..., :1])], -1)
        cum = jnp.concatenate(
            [jnp.ones_like(z[..., :1]), jnp.cumprod(1 - z, -1)], -1)
        return zpad * cum

    def _inverse(self, y):
        ycum = jnp.cumsum(y[..., :-1], -1)
        rest = 1 - jnp.concatenate(
            [jnp.zeros_like(y[..., :1]), ycum[..., :-1]], -1)
        z = y[..., :-1] / rest
        k = y.shape[-1] - 1
        offset = jnp.arange(k, 0, -1, dtype=y.dtype)
        return jnp.log(z) - jnp.log1p(-z) + jnp.log(offset)

    def _forward_log_det_jacobian(self, x):
        k = x.shape[-1]
        offset = jnp.arange(k, 0, -1, dtype=x.dtype)
        xs = x - jnp.log(offset)
        z = jax.nn.sigmoid(xs)
        cum = jnp.cumsum(jnp.log1p(-z), -1)
        cum = jnp.concatenate([jnp.zeros_like(cum[..., :1]),
                               cum[..., :-1]], -1)
        return jnp.sum(cum - jax.nn.softplus(-xs) - jax.nn.softplus(xs), -1)

    def forward_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] + 1,)

    def inverse_shape(self, shape):
        return tuple(shape[:-1]) + (shape[-1] - 1,)


class ChainTransform(Transform):
    def __init__(self, transforms: Sequence[Transform]):
        self.transforms = list(transforms)
        self._event_rank = max((t._event_rank for t in self.transforms),
                               default=0)

    def _forward(self, x):
        for t in self.transforms:
            x = t._forward(x)
        return x

    def _inverse(self, y):
        for t in reversed(self.transforms):
            y = t._inverse(y)
        return y

    def _forward_log_det_jacobian(self, x):
        total = 0.0
        for t in self.transforms:
            ldj = t._forward_log_det_jacobian(x)
            # an elementwise transform's ldj still carries the chain's
            # event dims — reduce them so terms add at batch rank
            extra = self._event_rank - t._event_rank
            if extra > 0 and getattr(ldj, 'ndim', 0) >= extra:
                ldj = jnp.sum(ldj, axis=tuple(range(-extra, 0)))
            total = total + ldj
            x = t._forward(x)
        return total

    def forward_shape(self, shape):
        for t in self.transforms:
            shape = t.forward_shape(shape)
        return tuple(shape)

    def inverse_shape(self, shape):
        for t in reversed(self.transforms):
            shape = t.inverse_shape(shape)
        return tuple(shape)


class IndependentTransform(Transform):
    """Reinterprets the rightmost ``reinterpreted_batch_rank`` dims as
    event dims: log-det sums over them."""

    def __init__(self, base: Transform, reinterpreted_batch_rank: int):
        self.base = base
        self.reinterpreted_batch_rank = int(reinterpreted_batch_rank)
        self._event_rank = base._event_rank + self.reinterpreted_batch_rank

    def _forward(self, x):
        return self.base._forward(x)

    def _inverse(self, y):
        return self.base._inverse(y)

    def _forward_log_det_jacobian(self, x):
        ldj = self.base._forward_log_det_jacobian(x)
        axes = tuple(range(-self.reinterpreted_batch_rank, 0))
        return jnp.sum(ldj, axis=axes) if axes else ldj


class ReshapeTransform(Transform):
    def __init__(self, in_event_shape, out_event_shape):
        self.in_event_shape = tuple(int(s) for s in in_event_shape)
        self.out_event_shape = tuple(int(s) for s in out_event_shape)
        self._event_rank = len(self.in_event_shape)

    def _forward(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.reshape(x, batch + self.out_event_shape)

    def _inverse(self, y):
        batch = y.shape[:y.ndim - len(self.out_event_shape)]
        return jnp.reshape(y, batch + self.in_event_shape)

    def _forward_log_det_jacobian(self, x):
        batch = x.shape[:x.ndim - len(self.in_event_shape)]
        return jnp.zeros(batch, x.dtype)

    def forward_shape(self, shape):
        n = len(self.in_event_shape)
        return tuple(shape[:len(shape) - n]) + self.out_event_shape

    def inverse_shape(self, shape):
        n = len(self.out_event_shape)
        return tuple(shape[:len(shape) - n]) + self.in_event_shape


class StackTransform(Transform):
    """Applies a list of transforms to slices along ``axis``."""

    def __init__(self, transforms: Sequence[Transform], axis: int = 0):
        self.transforms = list(transforms)
        self.axis = int(axis)

    def _map(self, fn_name, x):
        parts = [getattr(t, fn_name)(xi) for t, xi in
                 zip(self.transforms,
                     jnp.split(x, len(self.transforms), self.axis))]
        return jnp.concatenate(parts, self.axis)

    def _forward(self, x):
        return self._map('_forward', x)

    def _inverse(self, y):
        return self._map('_inverse', y)

    def _forward_log_det_jacobian(self, x):
        return self._map('_forward_log_det_jacobian', x)
