"""ExponentialFamily base (parity:
/root/reference/python/paddle/distribution/exponential_family.py).

The reference computes entropy generically via the Bregman divergence of
the log-normalizer; here subclasses provide closed-form entropy directly
(cheaper under XLA), and this base exists for API/isinstance parity.
"""
from __future__ import annotations

from .distribution import Distribution


class ExponentialFamily(Distribution):
    @property
    def _natural_parameters(self):
        raise NotImplementedError

    def _log_normalizer(self, *natural_parameters):
        raise NotImplementedError
