"""Normal (parity: /root/reference/python/paddle/distribution/normal.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.scipy.special import erf, erfinv

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape

_HALF_LOG_2PI = 0.5 * math.log(2.0 * math.pi)


class Normal(Distribution):
    def __init__(self, loc, scale, name=None):
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        self.loc, self.scale = jnp.broadcast_arrays(self.loc, self.scale)
        super().__init__(batch_shape=self.loc.shape)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(jnp.square(self.scale))

    @property
    def stddev(self):
        return Tensor(self.scale)

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        eps = jax.random.normal(_next_key(), shp, self.loc.dtype)
        return Tensor(self.loc + eps * self.scale)

    def log_prob(self, value):
        v = _as_jnp(value)
        z = (v - self.loc) / self.scale
        return Tensor(-0.5 * z * z - jnp.log(self.scale) - _HALF_LOG_2PI)

    def entropy(self):
        out = 0.5 + _HALF_LOG_2PI + jnp.log(self.scale)
        return Tensor(jnp.broadcast_to(out, self.batch_shape))

    def cdf(self, value):
        v = _as_jnp(value)
        return Tensor(0.5 * (1 + erf((v - self.loc)
                                     / (self.scale * math.sqrt(2.0)))))

    def icdf(self, value):
        v = _as_jnp(value)
        return Tensor(self.loc + self.scale * math.sqrt(2.0)
                      * erfinv(2 * v - 1))

    def kl_divergence(self, other: "Normal"):
        from .kl import kl_divergence
        return kl_divergence(self, other)
