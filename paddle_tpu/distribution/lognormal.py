"""LogNormal (parity:
/root/reference/python/paddle/distribution/lognormal.py)."""
from __future__ import annotations

import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import _as_jnp
from .normal import Normal
from .transform import ExpTransform
from .transformed_distribution import TransformedDistribution


class LogNormal(TransformedDistribution):
    def __init__(self, loc, scale):
        self._base = Normal(loc, scale)
        self.loc = self._base.loc
        self.scale = self._base.scale
        super().__init__(self._base, [ExpTransform()])

    @property
    def mean(self):
        return Tensor(jnp.exp(self.loc + jnp.square(self.scale) / 2))

    @property
    def variance(self):
        s2 = jnp.square(self.scale)
        return Tensor(jnp.expm1(s2) * jnp.exp(2 * self.loc + s2))

    def entropy(self):
        return Tensor(_as_jnp(self._base.entropy()) + self.loc)
