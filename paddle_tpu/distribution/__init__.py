"""paddle.distribution parity (reference:
/root/reference/python/paddle/distribution/__init__.py).

TPU-native: parameters live as jnp arrays, sampling draws threaded PRNG
keys from the global Generator (traceable under jit via
framework.core.with_rng_key), densities are pure jnp — everything fuses
under XLA.
"""
from __future__ import annotations

from . import transform  # noqa: F401
from .bernoulli import Bernoulli
from .beta import Beta
from .binomial import Binomial
from .categorical import Categorical
from .cauchy import Cauchy
from .continuous_bernoulli import ContinuousBernoulli
from .dirichlet import Dirichlet
from .distribution import Distribution
from .exponential import Exponential
from .exponential_family import ExponentialFamily
from .gamma import Gamma
from .geometric import Geometric
from .gumbel import Gumbel
from .independent import Independent
from .kl import kl_divergence, register_kl
from .laplace import Laplace
from .lognormal import LogNormal
from .multinomial import Multinomial
from .multivariate_normal import MultivariateNormal
from .normal import Normal
from .poisson import Poisson
from .transform import (  # noqa: F401
    AbsTransform,
    AffineTransform,
    ChainTransform,
    ExpTransform,
    IndependentTransform,
    PowerTransform,
    ReshapeTransform,
    SigmoidTransform,
    SoftmaxTransform,
    StackTransform,
    StickBreakingTransform,
    TanhTransform,
    Transform,
)
from .transformed_distribution import TransformedDistribution
from .uniform import Uniform

__all__ = [
    'Bernoulli', 'Beta', 'Binomial', 'Categorical', 'Cauchy',
    'ContinuousBernoulli', 'Dirichlet', 'Distribution', 'Exponential',
    'ExponentialFamily', 'Gamma', 'Geometric', 'Gumbel', 'Independent',
    'Laplace', 'LogNormal', 'Multinomial', 'MultivariateNormal', 'Normal',
    'Poisson', 'TransformedDistribution', 'Uniform',
    'kl_divergence', 'register_kl',
    'AbsTransform', 'AffineTransform', 'ChainTransform', 'ExpTransform',
    'IndependentTransform', 'PowerTransform', 'ReshapeTransform',
    'SigmoidTransform', 'SoftmaxTransform', 'StackTransform',
    'StickBreakingTransform', 'TanhTransform', 'Transform',
]
