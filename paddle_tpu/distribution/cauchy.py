"""Cauchy (parity: /root/reference/python/paddle/distribution/cauchy.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class Cauchy(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        self.loc, self.scale = jnp.broadcast_arrays(self.loc, self.scale)
        super().__init__(batch_shape=self.loc.shape)

    @property
    def mean(self):
        raise ValueError("Cauchy distribution has no mean")

    @property
    def variance(self):
        raise ValueError("Cauchy distribution has no variance")

    @property
    def stddev(self):
        raise ValueError("Cauchy distribution has no stddev")

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        c = jax.random.cauchy(_next_key(), shp, self.loc.dtype)
        return Tensor(self.loc + self.scale * c)

    def log_prob(self, value):
        v = _as_jnp(value)
        z = (v - self.loc) / self.scale
        return Tensor(-math.log(math.pi) - jnp.log(self.scale)
                      - jnp.log1p(z * z))

    def entropy(self):
        return Tensor(jnp.log(4 * math.pi * self.scale))

    def cdf(self, value):
        v = _as_jnp(value)
        return Tensor(jnp.arctan((v - self.loc) / self.scale) / math.pi
                      + 0.5)

    def icdf(self, value):
        v = _as_jnp(value)
        return Tensor(self.loc + self.scale
                      * jnp.tan(math.pi * (v - 0.5)))
