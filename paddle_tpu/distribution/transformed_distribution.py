"""TransformedDistribution (parity:
/root/reference/python/paddle/distribution/transformed_distribution.py)."""
from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp
from .transform import ChainTransform, Transform


class TransformedDistribution(Distribution):
    def __init__(self, base: Distribution, transforms: Sequence[Transform]):
        self.base = base
        self.transforms = list(transforms)
        self._chain = ChainTransform(self.transforms)
        shape = base.batch_shape + base.event_shape
        out_shape = self._chain.forward_shape(shape)
        ev_rank = max(self._chain._event_rank, len(base.event_shape))
        n = len(out_shape) - ev_rank
        super().__init__(batch_shape=out_shape[:n],
                         event_shape=out_shape[n:])

    def sample(self, shape=()):
        x = self.base.sample(shape)
        return self._chain.forward(x)

    def rsample(self, shape=()):
        x = self.base.rsample(shape)
        return self._chain.forward(x)

    def log_prob(self, value):
        y = _as_jnp(value)
        base_ev_rank = len(self.base.event_shape)
        lp = 0.0
        for t in reversed(self.transforms):
            x = t._inverse(y)
            ldj = t._forward_log_det_jacobian(x)
            # an elementwise transform's ldj still carries the base's
            # event dims — reduce them so lp is per batch element
            reduce_rank = base_ev_rank - t._event_rank
            if reduce_rank > 0 and hasattr(ldj, 'ndim') and ldj.ndim > 0:
                ldj = jnp.sum(ldj, axis=tuple(range(-reduce_rank, 0)))
            lp = lp - ldj
            y = x
        base_lp = _as_jnp(self.base.log_prob(Tensor(y)))
        return Tensor(base_lp + lp)
