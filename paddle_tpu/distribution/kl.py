"""KL divergence registry (parity:
/root/reference/python/paddle/distribution/kl.py — kl_divergence,
register_kl with MRO-based dispatch)."""
from __future__ import annotations

import functools

import jax.numpy as jnp
from jax.scipy.special import betaln, digamma, gammaln

from ..framework.core import Tensor
from .bernoulli import Bernoulli
from .beta import Beta
from .categorical import Categorical
from .dirichlet import Dirichlet
from .distribution import Distribution, _as_jnp
from .exponential import Exponential
from .gamma import Gamma
from .geometric import Geometric
from .gumbel import Gumbel
from .laplace import Laplace
from .lognormal import LogNormal
from .normal import Normal
from .poisson import Poisson
from .uniform import Uniform

__all__ = ["kl_divergence", "register_kl"]

_REGISTRY = {}


def register_kl(cls_p, cls_q):
    def decorator(fn):
        _REGISTRY[(cls_p, cls_q)] = fn
        return fn
    return decorator


def _dispatch(type_p, type_q):
    matches = [(p, q) for (p, q) in _REGISTRY
               if issubclass(type_p, p) and issubclass(type_q, q)]
    if not matches:
        raise NotImplementedError(
            f"no KL registered for ({type_p.__name__}, {type_q.__name__})")

    def specificity(pair):
        # fewer registered classes are subclasses of a *specific* class,
        # so minimize to prefer the most-derived match
        p, q = pair
        return (sum(issubclass(p2, p) for p2, _ in matches),
                sum(issubclass(q2, q) for _, q2 in matches))
    best = min(matches, key=specificity)
    return _REGISTRY[best]


def kl_divergence(p: Distribution, q: Distribution):
    return _dispatch(type(p), type(q))(p, q)


@register_kl(Normal, Normal)
def _kl_normal_normal(p, q):
    var_ratio = jnp.square(p.scale / q.scale)
    t1 = jnp.square((p.loc - q.loc) / q.scale)
    return Tensor(0.5 * (var_ratio + t1 - 1 - jnp.log(var_ratio)))


@register_kl(Uniform, Uniform)
def _kl_uniform_uniform(p, q):
    lo = p.low >= q.low
    hi = p.high <= q.high
    kl = jnp.log((q.high - q.low) / (p.high - p.low))
    return Tensor(jnp.where(lo & hi, kl, jnp.inf))


@register_kl(Bernoulli, Bernoulli)
def _kl_bernoulli_bernoulli(p, q):
    a, b = p.probs_, q.probs_
    return Tensor(a * (jnp.log(a) - jnp.log(b))
                  + (1 - a) * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(Categorical, Categorical)
def _kl_categorical_categorical(p, q):
    pp, qq = p._p, q._p
    return Tensor(jnp.sum(pp * (jnp.log(jnp.clip(pp, 1e-38))
                                - jnp.log(jnp.clip(qq, 1e-38))), -1))


@register_kl(Beta, Beta)
def _kl_beta_beta(p, q):
    a1, b1, a2, b2 = p.alpha, p.beta, q.alpha, q.beta
    return Tensor(betaln(a2, b2) - betaln(a1, b1)
                  + (a1 - a2) * digamma(a1) + (b1 - b2) * digamma(b1)
                  + (a2 - a1 + b2 - b1) * digamma(a1 + b1))


@register_kl(Dirichlet, Dirichlet)
def _kl_dirichlet_dirichlet(p, q):
    a, b = p.concentration, q.concentration
    a0 = jnp.sum(a, -1, keepdims=True)
    return Tensor(gammaln(jnp.sum(a, -1)) - gammaln(jnp.sum(b, -1))
                  - jnp.sum(gammaln(a) - gammaln(b), -1)
                  + jnp.sum((a - b) * (digamma(a) - digamma(a0)), -1))


@register_kl(Gamma, Gamma)
def _kl_gamma_gamma(p, q):
    a1, b1, a2, b2 = p.concentration, p.rate, q.concentration, q.rate
    return Tensor((a1 - a2) * digamma(a1) - gammaln(a1) + gammaln(a2)
                  + a2 * (jnp.log(b1) - jnp.log(b2))
                  + a1 * (b2 / b1 - 1))


@register_kl(Exponential, Exponential)
def _kl_exponential_exponential(p, q):
    r = q.rate / p.rate
    return Tensor(r - jnp.log(r) - 1)


@register_kl(Laplace, Laplace)
def _kl_laplace_laplace(p, q):
    scale_ratio = p.scale / q.scale
    loc_diff = jnp.abs(p.loc - q.loc) / q.scale
    return Tensor(-jnp.log(scale_ratio) - 1
                  + scale_ratio * jnp.exp(-loc_diff / scale_ratio)
                  + loc_diff)


@register_kl(Poisson, Poisson)
def _kl_poisson_poisson(p, q):
    return Tensor(p.rate * (jnp.log(p.rate) - jnp.log(q.rate))
                  - p.rate + q.rate)


@register_kl(Geometric, Geometric)
def _kl_geometric_geometric(p, q):
    a, b = p.probs_, q.probs_
    return Tensor((jnp.log(a) - jnp.log(b)) + (1 - a) / a
                  * (jnp.log1p(-a) - jnp.log1p(-b)))


@register_kl(LogNormal, LogNormal)
def _kl_lognormal_lognormal(p, q):
    return _kl_normal_normal(p._base, q._base)


@register_kl(Gumbel, Gumbel)
def _kl_gumbel_gumbel(p, q):
    # KL(Gumbel(m1,b1) || Gumbel(m2,b2)); Euler–Mascheroni γ
    g = 0.57721566490153286060
    b1, b2, m1, m2 = p.scale, q.scale, p.loc, q.loc
    return Tensor(jnp.log(b2) - jnp.log(b1)
                  + g * (b1 / b2 - 1)
                  + jnp.exp((m2 - m1) / b2
                            + gammaln(1 + b1 / b2)
                            - gammaln(jnp.ones_like(b1))) - 1
                  + (m1 - m2) / b2)
