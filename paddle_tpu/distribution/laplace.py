"""Laplace (parity:
/root/reference/python/paddle/distribution/laplace.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class Laplace(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        self.loc, self.scale = jnp.broadcast_arrays(self.loc, self.scale)
        super().__init__(batch_shape=self.loc.shape)

    @property
    def mean(self):
        return Tensor(self.loc)

    @property
    def variance(self):
        return Tensor(2 * jnp.square(self.scale))

    @property
    def stddev(self):
        return Tensor(math.sqrt(2.0) * self.scale)

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shp, self.loc.dtype,
                               minval=-0.5 + 1e-7, maxval=0.5)
        return Tensor(self.loc - self.scale * jnp.sign(u)
                      * jnp.log1p(-2 * jnp.abs(u)))

    def log_prob(self, value):
        v = _as_jnp(value)
        return Tensor(-jnp.abs(v - self.loc) / self.scale
                      - jnp.log(2 * self.scale))

    def entropy(self):
        return Tensor(1 + jnp.log(2 * self.scale))

    def cdf(self, value):
        v = _as_jnp(value)
        z = (v - self.loc) / self.scale
        return Tensor(0.5 - 0.5 * jnp.sign(z) * jnp.expm1(-jnp.abs(z)))

    def icdf(self, value):
        v = _as_jnp(value)
        t = v - 0.5
        return Tensor(self.loc - self.scale * jnp.sign(t)
                      * jnp.log1p(-2 * jnp.abs(t)))
