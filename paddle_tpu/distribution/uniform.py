"""Uniform (parity: /root/reference/python/paddle/distribution/uniform.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape


class Uniform(Distribution):
    def __init__(self, low, high, name=None):
        self.low = _as_jnp(low)
        self.high = _as_jnp(high)
        self.low, self.high = jnp.broadcast_arrays(self.low, self.high)
        super().__init__(batch_shape=self.low.shape)

    @property
    def mean(self):
        return Tensor((self.low + self.high) / 2)

    @property
    def variance(self):
        return Tensor(jnp.square(self.high - self.low) / 12)

    def sample(self, shape=(), seed=0):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        u = jax.random.uniform(_next_key(), shp, self.low.dtype)
        return Tensor(self.low + u * (self.high - self.low))

    def log_prob(self, value):
        v = _as_jnp(value)
        inside = (v >= self.low) & (v < self.high)
        lp = -jnp.log(self.high - self.low)
        return Tensor(jnp.where(inside, lp, -jnp.inf))

    def entropy(self):
        return Tensor(jnp.log(self.high - self.low))

    def cdf(self, value):
        v = _as_jnp(value)
        return Tensor(jnp.clip((v - self.low) / (self.high - self.low), 0, 1))

    def icdf(self, value):
        v = _as_jnp(value)
        return Tensor(self.low + v * (self.high - self.low))
