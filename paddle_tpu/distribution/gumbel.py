"""Gumbel (parity: /root/reference/python/paddle/distribution/gumbel.py)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..framework.core import Tensor
from .distribution import Distribution, _as_jnp, _next_key, _sample_shape

_EULER = 0.57721566490153286060


class Gumbel(Distribution):
    def __init__(self, loc, scale):
        self.loc = _as_jnp(loc)
        self.scale = _as_jnp(scale)
        self.loc, self.scale = jnp.broadcast_arrays(self.loc, self.scale)
        super().__init__(batch_shape=self.loc.shape)

    @property
    def mean(self):
        return Tensor(self.loc + self.scale * _EULER)

    @property
    def variance(self):
        return Tensor(jnp.square(math.pi * self.scale) / 6)

    @property
    def stddev(self):
        return Tensor(math.pi / math.sqrt(6.0) * self.scale)

    def sample(self, shape=()):
        return self.rsample(shape)

    def rsample(self, shape=()):
        shp = _sample_shape(shape) + self.batch_shape
        g = jax.random.gumbel(_next_key(), shp, self.loc.dtype)
        return Tensor(self.loc + self.scale * g)

    def log_prob(self, value):
        v = _as_jnp(value)
        z = (v - self.loc) / self.scale
        return Tensor(-(z + jnp.exp(-z)) - jnp.log(self.scale))

    def entropy(self):
        return Tensor(jnp.log(self.scale) + 1 + _EULER)

    def cdf(self, value):
        v = _as_jnp(value)
        return Tensor(jnp.exp(-jnp.exp(-(v - self.loc) / self.scale)))
