"""paddle_tpu.geometric — graph-learning message passing.

Reference: /root/reference/python/paddle/geometric/ (segment ops in
math.py, message passing send_u_recv/send_ue_recv/send_uv in
message_passing/, sampling). TPU-native: every op is a jax segment_sum /
gather composition — XLA lowers these to efficient sorted-scatter on
TPU; all are differentiable through the tape.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor, apply

__all__ = [
    "segment_sum", "segment_mean", "segment_max", "segment_min",
    "send_u_recv", "send_ue_recv", "send_uv", "reindex_graph",
    "sample_neighbors",
    "reindex_heter_graph", "weighted_sample_neighbors",
]


def _num_segments(segment_ids, count=None):
    if count is not None:
        return int(count)
    ids = segment_ids._value if isinstance(segment_ids, Tensor) \
        else segment_ids
    return int(np.asarray(jax.device_get(ids)).max()) + 1 if ids.size \
        else 0


def segment_sum(data, segment_ids, name=None):
    n = _num_segments(segment_ids)
    return apply("segment_sum",
                 lambda d, s: jax.ops.segment_sum(d, s, num_segments=n),
                 data, segment_ids)


def segment_mean(data, segment_ids, name=None):
    n = _num_segments(segment_ids)

    def f(d, s):
        tot = jax.ops.segment_sum(d, s, num_segments=n)
        cnt = jax.ops.segment_sum(jnp.ones((d.shape[0],), d.dtype), s,
                                  num_segments=n)
        return tot / jnp.maximum(cnt, 1)[(...,) + (None,) * (d.ndim - 1)]
    return apply("segment_mean", f, data, segment_ids)


def segment_max(data, segment_ids, name=None):
    n = _num_segments(segment_ids)
    return apply("segment_max",
                 lambda d, s: jax.ops.segment_max(d, s, num_segments=n),
                 data, segment_ids)


def segment_min(data, segment_ids, name=None):
    n = _num_segments(segment_ids)
    return apply("segment_min",
                 lambda d, s: jax.ops.segment_min(d, s, num_segments=n),
                 data, segment_ids)


_POOLS = {"sum": segment_sum, "mean": segment_mean, "max": segment_max,
          "min": segment_min}


def send_u_recv(x, src_index, dst_index, reduce_op: str = "sum",
                out_size=None, name=None):
    """Gather x[src] and segment-reduce onto dst (reference
    message_passing/send_recv.py send_u_recv)."""
    n = out_size or (x.shape[0] if hasattr(x, "shape") else None)
    pool = reduce_op.lower()
    if pool not in _POOLS:
        raise ValueError(f"reduce_op must be one of {list(_POOLS)}")

    seg = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}

    def f(xa, si, di):
        msgs = xa[si]
        if pool == "mean":
            tot = jax.ops.segment_sum(msgs, di, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0],), xa.dtype), di, num_segments=n)
            return tot / jnp.maximum(cnt, 1)[
                (...,) + (None,) * (msgs.ndim - 1)]
        return seg[pool](msgs, di, num_segments=n)

    return apply("send_u_recv", f, x, src_index, dst_index)


def send_ue_recv(x, y, src_index, dst_index, message_op: str = "add",
                 reduce_op: str = "sum", out_size=None, name=None):
    """Message = x[src] (op) edge_feature, then reduce onto dst."""
    n = out_size or x.shape[0]
    mop = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}[message_op.lower()]
    pool = reduce_op.lower()
    seg = {"sum": jax.ops.segment_sum, "max": jax.ops.segment_max,
           "min": jax.ops.segment_min}

    def f(xa, ya, si, di):
        msgs = mop(xa[si], ya)
        if pool == "mean":
            tot = jax.ops.segment_sum(msgs, di, num_segments=n)
            cnt = jax.ops.segment_sum(
                jnp.ones((msgs.shape[0],), msgs.dtype), di, num_segments=n)
            return tot / jnp.maximum(cnt, 1)[
                (...,) + (None,) * (msgs.ndim - 1)]
        return seg[pool](msgs, di, num_segments=n)

    return apply("send_ue_recv", f, x, y, src_index, dst_index)


def send_uv(x, y, src_index, dst_index, message_op: str = "add",
            name=None):
    """Per-edge message x[src] (op) y[dst] (no reduction)."""
    mop = {"add": jnp.add, "sub": jnp.subtract, "mul": jnp.multiply,
           "div": jnp.divide}[message_op.lower()]
    return apply("send_uv",
                 lambda xa, ya, si, di: mop(xa[si], ya[di]),
                 x, y, src_index, dst_index)


def reindex_graph(x, neighbors, count, value_buffer=None, index_buffer=None,
                  name=None):
    """Compact node ids to a contiguous range (reference
    sampling/neighbors.py reindex_graph)."""
    xa = np.asarray(x._value if isinstance(x, Tensor) else x)
    nb = np.asarray(neighbors._value if isinstance(neighbors, Tensor)
                    else neighbors)
    cnt = np.asarray(count._value if isinstance(count, Tensor) else count)
    uniq = list(dict.fromkeys(xa.tolist()))
    mapping = {v: i for i, v in enumerate(uniq)}
    out_nodes = list(uniq)
    reindexed = []
    for v in nb.tolist():
        if v not in mapping:
            mapping[v] = len(out_nodes)
            out_nodes.append(v)
        reindexed.append(mapping[v])
    return (Tensor(jnp.asarray(reindexed, jnp.int64)),
            Tensor(jnp.asarray(out_nodes, xa.dtype)),
            Tensor(jnp.asarray(cnt)))


def sample_neighbors(row, colptr, input_nodes, sample_size=-1,
                     eids=None, return_eids=False, perm_buffer=None,
                     name=None):
    """Uniform neighbor sampling on CSC (reference
    sampling/neighbors.py). Host-side (data loading path, not jitted)."""
    r = np.asarray(row._value if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr._value if isinstance(colptr, Tensor)
                    else colptr)
    nodes = np.asarray(input_nodes._value
                       if isinstance(input_nodes, Tensor) else input_nodes)
    rng = np.random.default_rng()
    out_n, out_count = [], []
    for v in nodes.tolist():
        nbrs = r[cp[v]:cp[v + 1]]
        if 0 <= sample_size < len(nbrs):
            nbrs = rng.choice(nbrs, size=sample_size, replace=False)
        out_n.extend(nbrs.tolist())
        out_count.append(len(nbrs))
    return (Tensor(jnp.asarray(out_n, jnp.int64)),
            Tensor(jnp.asarray(out_count, jnp.int64)))


def reindex_heter_graph(x, neighbors, count, value_buffer=None,
                        index_buffer=None, name=None):
    """Heterogeneous-graph reindex (reference reindex_heter_graph):
    like reindex_graph but neighbors/count are per-edge-type lists
    sharing one id mapping."""
    xa = np.asarray(x._value if isinstance(x, Tensor) else x)
    uniq = list(dict.fromkeys(xa.tolist()))
    mapping = {v: i for i, v in enumerate(uniq)}
    out_nodes = list(uniq)
    re_all, cnt_all = [], []
    for nb, cnt in zip(neighbors, count):
        nba = np.asarray(nb._value if isinstance(nb, Tensor) else nb)
        ca = np.asarray(cnt._value if isinstance(cnt, Tensor) else cnt)
        re = []
        for v in nba.tolist():
            if v not in mapping:
                mapping[v] = len(out_nodes)
                out_nodes.append(v)
            re.append(mapping[v])
        re_all.append(re)
        cnt_all.append(ca)
    flat = [v for re in re_all for v in re]
    cnts = np.concatenate([np.asarray(c) for c in cnt_all])
    return (Tensor(jnp.asarray(flat, jnp.int64)),
            Tensor(jnp.asarray(out_nodes, xa.dtype)),
            Tensor(jnp.asarray(cnts)))


def weighted_sample_neighbors(row, colptr, edge_weight, input_nodes,
                              sample_size=-1, eids=None,
                              return_eids=False, name=None):
    """Weight-proportional neighbor sampling on CSC (reference
    weighted_sample_neighbors). Host-side (data-loading path)."""
    r = np.asarray(row._value if isinstance(row, Tensor) else row)
    cp = np.asarray(colptr._value if isinstance(colptr, Tensor)
                    else colptr)
    w = np.asarray(edge_weight._value
                   if isinstance(edge_weight, Tensor) else edge_weight)
    nodes = np.asarray(input_nodes._value
                       if isinstance(input_nodes, Tensor) else input_nodes)
    rng = np.random.default_rng()
    out_n, out_count = [], []
    for v in nodes.tolist():
        lo, hi = cp[v], cp[v + 1]
        nbrs, ws = r[lo:hi], w[lo:hi].astype(np.float64)
        if 0 <= sample_size < len(nbrs):
            p = ws / ws.sum() if ws.sum() > 0 else None
            nbrs = rng.choice(nbrs, size=sample_size, replace=False,
                              p=p)
        out_n.extend(nbrs.tolist())
        out_count.append(len(nbrs))
    return (Tensor(jnp.asarray(out_n, jnp.int64)),
            Tensor(jnp.asarray(out_count, jnp.int64)))
