"""paddle.linalg namespace parity."""
from .tensor.linalg import (  # noqa: F401
    cholesky, inv, pinv, det, slogdet, svd, qr, eigh, eigvalsh, solve,
    triangular_solve, lstsq, matrix_power, matrix_rank, cond, lu,
    householder_product, cov, corrcoef, norm, matmul, multi_dot,
    matrix_transpose, cholesky_solve, matrix_exp, eig, eigvals,
    lu_unpack,
)
from .tensor import pca_lowrank  # noqa: F401
