"""Partial-graph capture — the SOT analog (reference:
/root/reference/python/paddle/jit/sot/opcode_translator/executor/
opcode_executor.py; frame hook paddle/fluid/pybind/eval_frame.c used at
python/paddle/jit/sot/translate.py:99).

The reference simulates CPython bytecode to compile traceable subgraphs
and falls back to eager at graph breaks. The TPU-native equivalent needs
no frame hook: every op already flows through the framework's apply()
dispatch seam, so capture is a *lazy segment recorder* installed there:

- ops append nodes to an open segment; their outputs are Tensors whose
  values are symbolic placeholders (shape/dtype from jax.eval_shape —
  nothing executes);
- when Python demands a concrete value (bool() of a comparison, .item(),
  int()/float()/np conversion — exactly the constructs that kill whole-
  graph tracing), the open segment is CLOSED: compiled with jax.jit,
  executed through the normal taped apply() path (so autograd sees one
  node per segment, like whole-graph to_static), and the concrete arrays
  are grafted back into the placeholder Tensors. That is a *graph
  break*: the data-dependent Python code then runs eagerly on concrete
  values, and the next op opens a fresh segment;
- re-running the Python function each call replays control flow with
  fresh break values — the guard mechanism is the Python interpreter
  itself. Compiled segments are cached by an op-sequence signature
  (op names, shapes/dtypes, fingerprinted constants incl. closure
  cells); a signature miss recompiles, exactly like a SOT guard miss.
  Constants that cannot be fingerprinted (e.g. large captured arrays)
  make a segment uncacheable — it still runs correctly, just without
  the jit cache.
"""
from __future__ import annotations

import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from ..framework.core import (
    Tensor, apply, _set_capture_handler,
)
from ..framework import core as _core

__all__ = ["PartialProgram", "GraphBreak"]


# ---------------------------------------------------------------------------
# symbolic placeholder value
# ---------------------------------------------------------------------------

class _SymValue:
    """Stands in for Tensor._value inside an open segment. Carries only
    shape/dtype; any demand for the real array closes the segment (a
    graph break) and returns the concrete result."""

    __slots__ = ("_ctx", "aval", "_concrete", "__weakref__")

    def __init__(self, ctx, aval):
        object.__setattr__(self, "_ctx", ctx)
        object.__setattr__(self, "aval", aval)
        object.__setattr__(self, "_concrete", None)

    # cheap structural queries — no break
    @property
    def shape(self):
        return self.aval.shape

    @property
    def dtype(self):
        return self.aval.dtype

    @property
    def ndim(self):
        return len(self.aval.shape)

    @property
    def size(self):
        return int(np.prod(self.aval.shape)) if self.aval.shape else 1

    def _force(self):
        if self._concrete is None:
            self._ctx._materialize("concrete value demanded")
        if self._concrete is None:  # pragma: no cover — invariant
            raise RuntimeError("partial capture: materialization failed "
                               "to produce a value")
        return self._concrete

    def _pt_unwrap(self):
        """Transparent unwrap for code that stored this placeholder."""
        return self._concrete if self._concrete is not None else self

    # concretization points = graph breaks
    def __bool__(self):
        return bool(self._force())

    def __int__(self):
        return int(self._force())

    def __float__(self):
        return float(self._force())

    def __index__(self):
        return int(self._force())

    def __len__(self):
        if not self.aval.shape:
            raise TypeError("len() of a 0-d tensor")
        return self.aval.shape[0]

    def __array__(self, dtype=None, copy=None):
        a = np.asarray(self._force())
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        # direct jnp use of a symbolic value (an op that bypasses the
        # apply seam) breaks the graph rather than erroring
        return jnp.asarray(self._force())

    def __getattr__(self, name):
        # anything beyond shape/dtype metadata (item, tolist, devices,
        # sharding, ...) needs the real array
        return getattr(self._force(), name)

    # raw-array arithmetic on a placeholder (framework internals that
    # compute on ._value directly, e.g. BatchNorm running stats): break
    # and compute on the concrete array
    def __add__(self, o):
        return self._force() + o

    def __radd__(self, o):
        return o + self._force()

    def __sub__(self, o):
        return self._force() - o

    def __rsub__(self, o):
        return o - self._force()

    def __mul__(self, o):
        return self._force() * o

    def __rmul__(self, o):
        return o * self._force()

    def __truediv__(self, o):
        return self._force() / o

    def __rtruediv__(self, o):
        return o / self._force()

    def __matmul__(self, o):
        return self._force() @ o

    def __rmatmul__(self, o):
        return o @ self._force()

    def __neg__(self):
        return -self._force()

    def __pow__(self, o):
        return self._force() ** o

    def __getitem__(self, idx):
        return self._force()[idx]

    def __lt__(self, o):
        return self._force() < o

    def __le__(self, o):
        return self._force() <= o

    def __gt__(self, o):
        return self._force() > o

    def __ge__(self, o):
        return self._force() >= o

    def __repr__(self):
        state = "materialized" if self._concrete is not None else "open"
        return f"_SymValue(shape={self.aval.shape}, " \
               f"dtype={self.aval.dtype}, {state})"


class GraphBreak:
    """Telemetry record for one break."""

    def __init__(self, reason: str, n_ops: int):
        self.reason = reason
        self.n_ops = n_ops

    def __repr__(self):
        return f"GraphBreak({self.reason!r}, ops={self.n_ops})"


# ---------------------------------------------------------------------------
# constant fingerprinting (the guard condition for segment cache reuse)
# ---------------------------------------------------------------------------

_MAX_CONST_ELEMS = 64


def _fp_const(c) -> Optional[tuple]:
    """Hashable fingerprint of a captured constant, or None if the
    constant cannot be fingerprinted (→ segment uncacheable)."""
    if c is None or isinstance(c, (bool, int, float, str, bytes)):
        return ("py", c)
    if isinstance(c, (np.dtype, type)):
        return ("ty", str(c))
    if isinstance(c, np.generic):
        return ("np0", c.dtype.str, c.item())
    if isinstance(c, (np.ndarray, jnp.ndarray, jax.Array)):
        try:
            if c.size <= _MAX_CONST_ELEMS:
                return ("arr", str(c.dtype), tuple(c.shape),
                        np.asarray(c).tobytes())
        except Exception:
            return None
        return None
    if isinstance(c, (tuple, list)):
        parts = tuple(_fp_const(e) for e in c)
        if any(p is None for p in parts):
            return None
        return ("seq", type(c).__name__, parts)
    if isinstance(c, dict):
        try:
            items = sorted(c.items())
        except TypeError:
            return None
        parts = tuple((k, _fp_const(v)) for k, v in items)
        if any(p[1] is None for p in parts):
            return None
        return ("map", parts)
    if callable(c):
        return _fp_fn(c)
    return None


def _fp_fn(fn) -> Optional[tuple]:
    """Fingerprint a function by code identity + captured cells (two
    lambdas from the same source line with equal captures fingerprint
    equal — that is the point: per-call closures must hit the cache)."""
    import functools
    if isinstance(fn, functools.partial):
        parts = (_fp_fn(fn.func), _fp_const(fn.args),
                 _fp_const(fn.keywords))
        if any(p is None for p in parts):
            return None
        return ("partial",) + parts
    from ..decomposition.register import DecompAware, prim_enabled
    if isinstance(fn, DecompAware):
        # per-call wrapper: fingerprint by the wrapped kernel + attrs +
        # the prim flag (the flag changes which body the call runs)
        inner = _fp_fn(fn.fn)
        attrs = _fp_const(fn.attrs)
        if inner is None or attrs is None:
            return None
        return ("decomp", fn.op_name, inner, attrs, prim_enabled())
    bound = getattr(fn, "__self__", None)
    if bound is not None and hasattr(fn, "__func__"):
        inner = _fp_fn(fn.__func__)
        if inner is None:
            return None
        return ("method", inner, id(bound))
    code = getattr(fn, "__code__", None)
    if code is None:
        # module-level callables without code objects (jax custom_jvp /
        # custom_vjp wrappers, builtins, callable classes): long-lived
        # stateless objects — identity is a sound fingerprint. Transient
        # lambdas always have __code__ and never take this path.
        return ("objid", id(fn), type(fn).__name__)
    cells = []
    for cell in (fn.__closure__ or ()):
        try:
            fp = _fp_const(cell.cell_contents)
        except ValueError:  # empty cell
            fp = ("empty",)
        if fp is None:
            return None
        cells.append(fp)
    defaults = tuple(_fp_const(d) for d in (fn.__defaults__ or ()))
    if any(d is None for d in defaults):
        return None
    return ("fn", code.co_filename, code.co_firstlineno,
            hash(code.co_code), tuple(cells), defaults)


# ---------------------------------------------------------------------------
# segment recorder
# ---------------------------------------------------------------------------

class _Node:
    __slots__ = ("op_name", "fn", "arg_specs", "kwargs", "out_syms",
                 "multi")

    def __init__(self, op_name, fn, arg_specs, kwargs, out_syms, multi):
        self.op_name = op_name
        self.fn = fn
        # arg_specs: ("sym", _SymValue) | ("in", input_index) | ("const", value)
        self.arg_specs = arg_specs
        self.kwargs = kwargs
        self.out_syms = out_syms
        self.multi = multi


class _CaptureContext:
    def __init__(self, owner: "PartialProgram"):
        self.owner = owner
        self.nodes: List[_Node] = []
        self.inputs: List[Tensor] = []       # concrete segment inputs
        self._input_ids: Dict[int, int] = {}  # id(Tensor) → input index
        # every Tensor holding a live placeholder of the open segment
        self.sym_tensors: List[Tuple[weakref.ref, _SymValue]] = []
        self._sym_ids: Dict[int, int] = {}  # id(_SymValue) → index
        self.n_segments = 0
        self.breaks: List[GraphBreak] = []
        self._suspended = False
        self._cacheable = True
        self._sig_parts: List[tuple] = []

    # -- recording -----------------------------------------------------------
    def handle(self, op_name, fn, args, kwargs, diff):
        if self._suspended:
            return NotImplemented
        if _core._static_handler is not None:
            return NotImplemented  # static-graph mode wins
        if op_name != "cast":
            # AMP composes with capture by applying the same per-op cast
            # decision the eager hook makes (amp.cast_plan) at RECORD
            # time: each needed cast is itself recorded as a "cast" node
            # (a.astype re-enters this handler), so compiled segments
            # reproduce eager-AMP numerics exactly and bf16 training
            # still gets segment acceleration.
            from ..amp import cast_needed, cast_plan
            plan, tgt = cast_plan(op_name)
            if plan is not None:
                cast_args = []
                for a in args:
                    if isinstance(a, Tensor):
                        v = a._value
                        dt = v.aval.dtype if isinstance(v, _SymValue) \
                            else v.dtype
                        if cast_needed(plan, dt):
                            a = a.astype(tgt)
                    cast_args.append(a)
                args = tuple(cast_args)
        arg_specs = []
        sig_args = []
        eval_args = []
        for a in args:
            if isinstance(a, Tensor):
                v = a._value
                if isinstance(v, _SymValue) and v._concrete is None:
                    if v._ctx is not self:
                        # placeholder from a stale context: force it
                        v._force()
                        arg_specs.append(("in", self._input_index(a)))
                        eval_args.append(jax.ShapeDtypeStruct(
                            a._value.shape, a._value.dtype))
                        sig_args.append(("in", tuple(a._value.shape),
                                         str(a._value.dtype)))
                    else:
                        arg_specs.append(("sym", v))
                        eval_args.append(v.aval)
                        sig_args.append(("sym", self._sym_index(v),
                                         tuple(v.aval.shape),
                                         str(v.aval.dtype)))
                else:
                    vv = v._pt_unwrap() if isinstance(v, _SymValue) else v
                    a._value = vv
                    arg_specs.append(("in", self._input_index(a)))
                    eval_args.append(jax.ShapeDtypeStruct(vv.shape,
                                                          vv.dtype))
                    sig_args.append(("in", tuple(vv.shape), str(vv.dtype)))
            elif isinstance(a, (np.ndarray, jnp.ndarray, jax.Array)) and \
                    not isinstance(a, np.generic):
                # raw array positional arg: lift to an input (it may
                # change between calls — e.g. cu_seqlens)
                t = Tensor(jnp.asarray(a))
                arg_specs.append(("in", self._input_index(t)))
                eval_args.append(jax.ShapeDtypeStruct(t._value.shape,
                                                      t._value.dtype))
                sig_args.append(("in", tuple(t._value.shape),
                                 str(t._value.dtype)))
            else:
                arg_specs.append(("const", a))
                fp = _fp_const(a)
                if fp is None:
                    self._cacheable = False
                sig_args.append(("const", fp))
                eval_args.append(a)

        kw_fp = _fp_const(kwargs) if kwargs else ("map", ())
        fn_fp = _fp_fn(fn)
        if kw_fp is None or fn_fp is None:
            self._cacheable = False

        # constants are BOUND in the closure (not abstracted — reshape
        # dims, axis ints etc. must stay concrete Python values);
        # only array slots go through eval_shape
        array_slots = [i for i, (kind, _) in enumerate(arg_specs)
                       if kind != "const"]
        eval_arrays = [eval_args[i] for i in array_slots]

        def pure(*xs):
            full = [val if kind == "const" else None
                    for kind, val in arg_specs]
            for i, x in zip(array_slots, xs):
                full[i] = x
            return fn(*full, **kwargs)

        try:
            out_aval = jax.eval_shape(pure, *eval_arrays)
        except Exception:
            # the op itself is untraceable: break, then run it eagerly
            self._materialize(f"untraceable op {op_name}")
            return NotImplemented

        multi = isinstance(out_aval, (tuple, list))
        avals = list(out_aval) if multi else [out_aval]
        out_syms = [_SymValue(self, av) for av in avals]
        self.nodes.append(_Node(op_name, fn, arg_specs, kwargs, out_syms,
                                multi))
        self._sig_parts.append((op_name, tuple(sig_args), kw_fp, fn_fp,
                                len(avals)))

        need_grad = (diff and _core._grad_state.enabled
                     and any(isinstance(a, Tensor) and not a.stop_gradient
                             for a in args))
        outs = []
        for sv in out_syms:
            t = Tensor(sv, stop_gradient=not need_grad)
            self._sym_ids[id(sv)] = len(self.sym_tensors)
            self.sym_tensors.append((weakref.ref(t), sv))
            outs.append(t)
        return tuple(outs) if multi else outs[0]

    def _input_index(self, t: Tensor) -> int:
        idx = self._input_ids.get(id(t))
        if idx is None:
            idx = len(self.inputs)
            self._input_ids[id(t)] = idx
            self.inputs.append(t)
        return idx

    def _sym_index(self, sv: _SymValue) -> int:
        # stable per-segment index: position in creation order
        return self._sym_ids.get(id(sv), -1)

    # -- materialization (segment close = graph break) -----------------------
    def _materialize(self, reason: str):
        if not self.nodes:
            return
        nodes, self.nodes = self.nodes, []
        inputs, self.inputs = self.inputs, []
        self._input_ids = {}
        sym_entries, self.sym_tensors = self.sym_tensors, []
        self._sym_ids = {}
        sig = (tuple(self._sig_parts), len(inputs))
        self._sig_parts = []
        cacheable, self._cacheable = self._cacheable, True

        # outputs worth computing: placeholders whose Tensor is alive
        live = [(wr, sv) for wr, sv in sym_entries if wr() is not None]
        if not live:
            return  # fully dead segment: drop (ops are pure)
        out_syms = [sv for _, sv in live]

        def seg_fn(*in_arrays):
            env: Dict[int, Any] = {}
            for node in nodes:
                xs = []
                for kind, val in node.arg_specs:
                    if kind == "sym":
                        xs.append(env[id(val)])
                    elif kind == "in":
                        xs.append(in_arrays[val])
                    else:
                        xs.append(val)
                out = node.fn(*xs, **node.kwargs)
                outs = list(out) if node.multi else [out]
                for sv, o in zip(node.out_syms, outs):
                    env[id(sv)] = o
            return tuple(env[id(sv)] for sv in out_syms)

        if cacheable:
            cache = self.owner._seg_cache
            cached = cache.get(sig)
            if cached is None:
                cached = jax.jit(seg_fn)
                cache[sig] = cached
                # bound the cache: volatile constants (e.g. a per-call
                # RNG key captured in a closure that the op layer didn't
                # lift into an arg) would otherwise grow it per call
                while len(cache) > self.owner.max_cached_segments:
                    cache.pop(next(iter(cache)))
            else:
                cache[sig] = cache.pop(sig)  # LRU touch
            runner = cached
        else:
            runner = seg_fn  # correct but uncached (op-by-op dispatch)

        self._suspended = True
        try:
            results = apply(f"subgraph[{len(nodes)}ops]", runner, *inputs)
        finally:
            self._suspended = False
        if not isinstance(results, tuple):
            results = (results,)
        # graft concrete values (and tape linkage) back into the
        # original Tensor objects the user's code is holding
        for (wr, sv), rt in zip(live, results):
            t = wr()
            object.__setattr__(sv, "_concrete", rt._value)
            if t is not None:
                t._value = rt._value
                t._node = rt._node
                t._out_idx = rt._out_idx
                t.stop_gradient = rt.stop_gradient
        self.n_segments += 1
        self.breaks.append(GraphBreak(reason, len(nodes)))


# ---------------------------------------------------------------------------
# public driver
# ---------------------------------------------------------------------------

class PartialProgram:
    """Run ``fn`` under partial-graph capture.

    Each call re-executes the Python function (control flow replays with
    fresh break values — implicit guards); tensor ops accumulate into
    compiled segments cached across calls by op-sequence signature.

    Telemetry: ``graph_break_count`` (breaks before function end, i.e.
    concretization demands), ``num_subgraphs`` (compiled segments run on
    the last call), ``last_breaks`` (reasons)."""

    max_cached_segments = 64  # LRU bound (volatile closure constants)

    def __init__(self, fn: Callable, name: str = ""):
        self.fn = fn
        self.name = name or getattr(fn, "__name__", "fn")
        self._seg_cache: Dict[Any, Callable] = {}
        self.graph_break_count = 0
        self.num_subgraphs = 0
        self.last_breaks: List[GraphBreak] = []
        self.call_count = 0

    def __call__(self, *args, **kwargs):
        if _core._capture_handler is not None:
            # no nesting: inner partial programs run inside the outer one
            return self.fn(*args, **kwargs)
        ctx = _CaptureContext(self)
        _set_capture_handler(ctx.handle)
        try:
            out = self.fn(*args, **kwargs)
        finally:
            _set_capture_handler(None)
        n_breaks = ctx.n_segments  # segments closed before function end
        ctx._materialize("function end")
        self.call_count += 1
        self.graph_break_count += n_breaks
        self.num_subgraphs = ctx.n_segments
        self.last_breaks = ctx.breaks
        return out
