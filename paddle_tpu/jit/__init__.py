"""paddle_tpu.jit — to_static / compiled train steps.

Replaces the reference's entire graph-capture stack — dy2static AST
transforms (/root/reference/python/paddle/jit/dy2static/), the SOT bytecode
JIT (/root/reference/python/paddle/jit/sot/) and its C eval-frame hook
(/root/reference/paddle/fluid/pybind/eval_frame.c) — with jax.jit tracing:
the eager Tensor ops run unchanged on tracers, so "graph capture" is just
calling the model inside a trace. Guards (SOT's retrace conditions) become
XLA's shape/dtype cache keys.

Key pieces:
- ``functional_call``: run a Layer with swapped-in parameter/buffer arrays
  (torch.func-style), returning outputs + updated buffers. This is what
  makes the mutable Layer API compose with functional transforms.
- ``to_static``: paddle.jit.to_static parity. Compiled forward whose
  backward is a single taped VJP of the whole compiled graph.
- ``TrainStep``: whole-training-step compilation (fwd+bwd+optimizer) with
  buffer donation — the intended high-performance path on TPU.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import (
    Parameter, Tensor, apply, no_grad, with_rng_key, default_generator,
)

__all__ = ["functional_call", "to_static", "TrainStep", "save", "load",
           "not_to_static", "ignore_module"]


# ---------------------------------------------------------------------------
# functional_call
# ---------------------------------------------------------------------------

def _collect(layer):
    params = list(layer.named_parameters())
    buffers = [(n, b) for n, b in layer.named_buffers() if b is not None]
    return params, buffers


class _SwapGuard:
    """Temporarily replace Tensor._value on params/buffers with provided
    (possibly traced) arrays; restore originals on exit and capture the
    post-call buffer values (BatchNorm running stats etc.)."""

    def __init__(self, tensors: List[Tensor], arrays: List[jax.Array]):
        self.tensors = tensors
        self.arrays = arrays
        self.saved = None

    def __enter__(self):
        self.saved = [t._value for t in self.tensors]
        for t, a in zip(self.tensors, self.arrays):
            t._value = a
        return self

    def read_current(self):
        return [t._value for t in self.tensors]

    def __exit__(self, *exc):
        for t, v in zip(self.tensors, self.saved):
            t._value = v
        return False


def _unwrap_tree(x):
    if isinstance(x, Tensor):
        return x._value
    if isinstance(x, (list, tuple)):
        return type(x)(_unwrap_tree(e) for e in x)
    if isinstance(x, dict):
        return {k: _unwrap_tree(v) for k, v in x.items()}
    return x


def _wrap_tree(x, stop_gradient=True):
    if isinstance(x, (jnp.ndarray, jax.Array)) or hasattr(x, "dtype"):
        return Tensor(x, stop_gradient=stop_gradient)
    if isinstance(x, (list, tuple)):
        return type(x)(_wrap_tree(e, stop_gradient) for e in x)
    if isinstance(x, dict):
        return {k: _wrap_tree(v, stop_gradient) for k, v in x.items()}
    return x


def functional_call(layer, param_arrays: Sequence[jax.Array],
                    buffer_arrays: Sequence[jax.Array], args: tuple,
                    kwargs: Optional[dict] = None):
    """Run ``layer(*args)`` with parameters/buffers replaced by the given
    arrays. args are raw arrays or Tensors. Returns
    (output_pytree_of_arrays, new_buffer_arrays)."""
    kwargs = kwargs or {}
    params, buffers = _collect(layer)
    p_tensors = [p for _, p in params]
    b_tensors = [b for _, b in buffers]
    targs = tuple(a if isinstance(a, Tensor) else Tensor(a) for a in args)
    with _SwapGuard(p_tensors, list(param_arrays)), \
         _SwapGuard(b_tensors, list(buffer_arrays)) as bguard:
        with no_grad():
            out = layer(*targs, **kwargs)
        new_buffers = bguard.read_current()
    return _unwrap_tree(out), new_buffers


# ---------------------------------------------------------------------------
# to_static
# ---------------------------------------------------------------------------

_RETRACE_WARN_THRESHOLD = 8


def _trace_error(exc, fn_name):
    """Rewrap jax tracing failures with actionable paddle-level guidance
    (the SOT-guard analog: reference jit/sot/translate.py:31 falls back on
    graph breaks; here we say exactly what to change or offer
    full_graph=False eager fallback)."""
    import jax.errors as jerr
    msg = None
    if isinstance(exc, jerr.TracerBoolConversionError) or \
            "TracerBoolConversionError" in type(exc).__name__:
        msg = ("data-dependent Python control flow (if/while on a traced "
               "Tensor value). Use paddle_tpu.static.nn.cond / "
               "while_loop / switch_case, move the branch out of the "
               "compiled function, or pass full_graph=False to run this "
               "function eagerly")
    elif isinstance(exc, jerr.ConcretizationTypeError):
        msg = ("a traced Tensor was used where a concrete Python value is "
               "required (e.g. int(x), x.item(), shape-dependent Python "
               "logic). Hoist the value out of the compiled function or "
               "pass full_graph=False")
    elif isinstance(exc, jerr.TracerArrayConversionError):
        msg = ("a traced Tensor was converted to numpy (np.asarray/"
               ".numpy()) inside the compiled region. Keep the "
               "computation in paddle/jax ops, or pass full_graph=False")
    if msg is None:
        return None
    return RuntimeError(
        f"to_static({fn_name}): cannot compile — {msg}.\n"
        f"Original error: {type(exc).__name__}: {exc}")


def _prim() -> bool:
    from ..decomposition.register import prim_enabled
    return prim_enabled()


def _snapshot_lower(p_arrays, b_arrays, key, training, args):
    """Aval-only snapshot for concrete_program (live arrays would pin
    the batch + params in HBM)."""
    sds = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
    return ([sds(p) for p in p_arrays], [sds(b) for b in b_arrays],
            key, training,
            tuple(sds(a._value) if isinstance(a, Tensor) else a
                  for a in args))


class StaticFunction:
    """Compiled callable over a Layer or plain function of Tensors.

    Forward runs under jax.jit; backward through the result is ONE taped
    node whose VJP is the XLA-compiled cotangent program (the analog of the
    reference's whole-program backward in partial_program.py).

    Robustness (reference SOT parity, jit/sot/):
    - untraceable constructs raise actionable errors naming the fix;
    - full_graph=False falls back to EAGER execution when tracing fails
      (the graph-break analog: correctness first, speed when possible);
    - every retrace is counted and the triggering signature recorded
      (`retrace_count` / `trace_signatures`); crossing
      _RETRACE_WARN_THRESHOLD logs a cache-churn warning.
    """

    def __init__(self, fn_or_layer, input_spec=None, build_strategy=None,
                 full_graph=True):
        self._layer = fn_or_layer if hasattr(fn_or_layer, "forward") else None
        self._fn = fn_or_layer if self._layer is None else None
        self._compiled = None
        self._input_spec = input_spec
        self._full_graph = full_graph
        self._partial = None        # PartialProgram after a graph break
        self.retrace_count = 0
        self.trace_signatures = []

    def _note_trace(self, in_arrays):
        if getattr(self, "_suppress_note", False):
            return  # introspective lowering is not a retrace
        self.retrace_count += 1
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in in_arrays)
        self.trace_signatures.append(sig)
        if len(self.trace_signatures) > 16:   # telemetry, not a log
            del self.trace_signatures[:-16]
        if self.retrace_count == _RETRACE_WARN_THRESHOLD:
            import warnings
            warnings.warn(
                f"to_static({self._name()}) retraced "
                f"{self.retrace_count} times — every new input "
                f"shape/dtype compiles a new program. Recent signatures: "
                f"{self.trace_signatures[-4:]}. Pad inputs to fixed "
                f"shapes or bucket them.", RuntimeWarning)

    def _name(self):
        target = self._layer if self._layer is not None else self._fn
        return getattr(target, "__name__",
                       type(target).__name__ if target is not None else "?")

    # the pure array function
    def _build(self):
        layer = self._layer
        note = self._note_trace

        if layer is not None:
            # `mode` is the static cache token: (training, prim_enabled).
            # The prim flag only forces a retrace when toggled — the new
            # trace then reads the live flag through each DecompAware
            def pure(param_arrays, buffer_arrays, rng_key, mode, *in_arrays):
                note(in_arrays)
                training = mode[0] if isinstance(mode, tuple) else mode
                layer.training = training
                with with_rng_key(rng_key):
                    out, new_bufs = functional_call(
                        layer, param_arrays, buffer_arrays, in_arrays)
                return out, new_bufs
        else:
            fn = self._fn

            def pure(param_arrays, buffer_arrays, rng_key, mode, *in_arrays):
                note(in_arrays)
                targs = tuple(Tensor(a) for a in in_arrays)
                from ..framework.core import _watch_mutations
                with with_rng_key(rng_key), no_grad(), \
                        _watch_mutations() as (mutated, created):
                    out = fn(*targs)
                arg_ids = {id(t) for t in targs}
                leaked = [t for i, t in mutated.items()
                          if i not in created and i not in arg_ids]
                if leaked:
                    raise RuntimeError(
                        f"to_static({fn.__name__}): the function mutates "
                        f"{len(leaked)} Tensor(s) it does not own (buffer/"
                        f"global state writes). Tracing would silently "
                        f"drop these updates. Wrap the owning Layer with "
                        f"to_static instead (its buffers are threaded "
                        f"through the compiled program), or return the "
                        f"updated values explicitly.")
                return _unwrap_tree(out), []

        return jax.jit(pure, static_argnums=(3,))

    def __call__(self, *args, **kwargs):
        if not _to_static_enabled:
            # paddle.jit.enable_to_static(False): run the target eagerly
            target = self._layer if self._layer is not None else self._fn
            return target(*args, **kwargs)
        if self._partial is not None:
            return self._partial(*args, **kwargs)
        if self._compiled is None:
            self._compiled = self._build()
        try:
            return self._call_compiled(args, kwargs)
        except Exception as e:
            wrapped = _trace_error(e, self._name())
            if wrapped is None:
                raise
            if not self._full_graph:
                # graph break (SOT parity, reference jit/sot/translate.py):
                # compile the traceable segments, run the breaking
                # constructs eagerly between them
                return self._enter_partial(e, args, kwargs)
            raise wrapped from e

    def _enter_partial(self, cause, args, kwargs):
        import warnings
        from .partial_capture import PartialProgram
        # warn BEFORE executing anything: under warnings-as-errors this
        # must raise while state is still clean (no segments run)
        warnings.warn(
            f"to_static({self._name()}): whole-graph tracing failed "
            f"({type(cause).__name__}); switching to partial-graph "
            f"capture (compiled subgraphs around the breaking "
            f"constructs).", RuntimeWarning)
        target = (self._layer if self._layer is not None else self._fn)
        self._partial = PartialProgram(target, name=self._name())
        try:
            return self._partial(*args, **kwargs)
        except Exception:
            # Do NOT re-run eagerly: segments already executed with real
            # side effects (buffer updates, RNG draws) — a rerun would
            # double-apply them. Propagate; the next call retries
            # (whole-graph first, then partial) from clean state.
            self._partial = None
            raise

    # partial-capture telemetry (SOT parity surface)
    @property
    def graph_break_count(self):
        return self._partial.graph_break_count if self._partial else 0

    @property
    def num_subgraphs(self):
        return self._partial.num_subgraphs if self._partial else 0


    def _call_compiled(self, args, kwargs):
        if kwargs:
            raise NotImplementedError(
                f"to_static({self._name()}): keyword arguments "
                f"{sorted(kwargs)} are not supported by the compiled "
                "call signature — pass them positionally (silently "
                "running with defaults would be wrong)")
        layer = self._layer
        if layer is not None:
            params, buffers = _collect(layer)
            p_tensors = [p for _, p in params]
            b_tensors = [b for _, b in buffers]
            b_arrays = [b._value for b in b_tensors]
            key = default_generator.next_key()

            compiled = self._compiled
            training = layer.training
            n_params = len(p_tensors)

            # the per-call key rides as a positional arg, not a closure
            # cell: an outer capture context fingerprints closures by
            # cell content, so a captured fresh key would miss the
            # segment cache every call (FC203)
            def whole_graph(k, *arrs):
                pa = arrs[:n_params]
                ia = arrs[n_params:]
                out, new_bufs = compiled(list(pa), b_arrays, k,
                                         (training, _prim()), *ia)
                flat_out, treedef = jax.tree_util.tree_flatten(out)
                self._last_treedef = treedef
                self._last_n_out = len(flat_out)
                return tuple(flat_out) + tuple(new_bufs)

            results = apply("to_static", whole_graph, key, *p_tensors,
                            *args)
            if getattr(self, "_lower_trace_count", -1) != \
                    self.retrace_count:
                # aval-only snapshot for concrete_program, refreshed per
                # retrace (not per call): ShapeDtypeStructs, ALL args
                self._lower_args = _snapshot_lower(
                    [p._value for p in p_tensors], b_arrays, key,
                    (training, _prim()), args)
                self._lower_trace_count = self.retrace_count
            if not isinstance(results, tuple):
                results = (results,)
            n_out = self._last_n_out
            out_tensors = list(results[:n_out])
            new_buf_tensors = results[n_out:]
            for bt, nb in zip(b_tensors, new_buf_tensors):
                bt._replace(nb._value)
            out = jax.tree_util.tree_unflatten(
                self._last_treedef, out_tensors)
            return out
        # plain function
        key = default_generator.next_key()
        compiled = self._compiled

        def whole_graph(k, *arrs):
            out, _ = compiled([], [], k, (True, _prim()), *arrs)
            flat_out, treedef = jax.tree_util.tree_flatten(out)
            self._last_treedef = treedef
            return tuple(flat_out) if len(flat_out) > 1 else flat_out[0]

        results = apply("to_static", whole_graph, key, *args)
        if getattr(self, "_lower_trace_count", -1) != self.retrace_count:
            self._lower_args = _snapshot_lower([], [], key,
                                               (True, _prim()), args)
            self._lower_trace_count = self.retrace_count
        if isinstance(results, tuple):
            return jax.tree_util.tree_unflatten(self._last_treedef,
                                                list(results))
        return jax.tree_util.tree_unflatten(self._last_treedef, [results])

    # paddle API compat
    @property
    def forward(self):
        return self.__call__

    @property
    def concrete_program(self):
        """The traced program of the LAST call (reference
        ConcreteProgram, jit/dy2static/program_translator.py): inputs/
        outputs specs, parameters, and main_program — here the
        framework's IR is StableHLO, so main_program is the lowered
        StableHLO module text of the compiled forward."""
        if self._partial is not None:
            raise RuntimeError(
                "concrete_program: this function runs under PARTIAL "
                "graph capture (whole-graph tracing failed) — there is "
                "no single whole program to show; see num_subgraphs / "
                "graph_break_count for the capture telemetry")
        if self._compiled is None or \
                getattr(self, "_lower_args", None) is None:
            raise RuntimeError(
                "concrete_program: call the to_static function at least "
                "once (tracing is input-driven — shapes come from the "
                "first call)")
        return _ConcreteProgram(self)


class _ConcreteProgram:
    """Reference ConcreteProgram parity surface over the last trace:
    .inputs (specs), .parameters, .main_program — this framework's IR
    is StableHLO, so main_program is the lowered module text."""

    def __init__(self, static_fn: "StaticFunction"):
        self._sf = static_fn

    @property
    def inputs(self):
        # derived from the same snapshot main_program lowers — the two
        # views always describe the SAME program
        from ..static.program import InputSpec
        _, _, _, _, ia = self._sf._lower_args
        return [InputSpec(list(a.shape), a.dtype) for a in ia
                if hasattr(a, "shape")]

    @property
    def parameters(self):
        layer = self._sf._layer
        if layer is None:
            return []
        return [p for _, p in layer.named_parameters()]

    @property
    def main_program(self) -> str:
        sf = self._sf
        pa, ba, key, training, ia = sf._lower_args
        layer = sf._layer
        prev_training = getattr(layer, "training", None)
        sf._suppress_note = True     # tracing here is introspection,
        try:                         # not a retrace of the live model
            lowered = sf._compiled.lower(pa, ba, key, training, *ia)
        finally:
            sf._suppress_note = False
            if layer is not None and prev_training is not None:
                # pure() sets layer.training as a trace side effect —
                # introspection must not flip the live train/eval mode
                layer.training = prev_training
        return lowered.as_text()

    def __repr__(self):
        return (f"ConcreteProgram(inputs={self.inputs}, "
                f"n_params={len(self.parameters)}, ir=stablehlo)")


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=True):
    """paddle.jit.to_static parity (/root/reference/python/paddle/jit/api.py:171).

    full_graph=False enables the graph-break analog: if tracing fails on
    an untraceable construct, the function runs eagerly instead (with a
    one-time warning) rather than erroring."""
    def decorate(fn):
        if hasattr(fn, "forward"):  # Layer: wrap call while keeping layer API
            static = StaticFunction(fn, input_spec, build_strategy,
                                    full_graph=full_graph)
            return _StaticLayerProxy(fn, static)
        return StaticFunction(fn, input_spec, build_strategy,
                              full_graph=full_graph)

    if function is not None:
        return decorate(function)
    return decorate


class _StaticLayerProxy:
    """Layer wrapper whose __call__ is compiled but which forwards
    everything else (state_dict, parameters, train/eval) to the layer.
    Reports the wrapped layer's __class__, so isinstance(proxy, Layer)
    (and isinstance against the concrete model class) hold; the layer
    instance itself is never mutated."""

    def __init__(self, layer, static_fn):
        object.__setattr__(self, "_layer", layer)
        object.__setattr__(self, "_static_fn", static_fn)

    @property
    def __class__(self):
        return type(self._layer)

    def __call__(self, *args, **kwargs):
        return self._static_fn(*args, **kwargs)

    # to_static telemetry/introspection lives on the StaticFunction
    _STATIC_ATTRS = frozenset({
        "concrete_program", "retrace_count", "trace_signatures",
        "graph_break_count", "num_subgraphs",
    })

    def __getattr__(self, name):
        if name in _StaticLayerProxy._STATIC_ATTRS:
            return getattr(self._static_fn, name)
        return getattr(self._layer, name)

    def __setattr__(self, name, value):
        setattr(self._layer, name, value)


def not_to_static(fn):
    return fn


def ignore_module(modules):
    return None


# ---------------------------------------------------------------------------
# TrainStep: whole-step compilation (the TPU fast path)
# ---------------------------------------------------------------------------

class TrainStep:
    """Compile forward+backward+optimizer into one XLA program.

    Usage:
        step = TrainStep(model, loss_fn, optimizer)   # loss_fn(out, *labels)
        loss = step(x, y)                             # Tensors in, loss out

    The compiled program donates parameter/optimizer-state buffers, so
    updates are in-place in HBM (the analog of the reference interpreter's
    inplace pass + buffer GC, at zero runtime cost).
    """

    def __init__(self, model, loss_fn: Callable, optimizer,
                 donate: bool = True, mesh=None, in_shardings=None,
                 gradient_merge: int = 1, gradient_merge_avg: bool = True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        params, buffers = _collect(model)
        self._param_names = [n for n, _ in params]
        self._p_tensors = [p for _, p in params]
        self._b_tensors = [b for _, b in buffers]
        # optimizer must own the same params (paddle-style construction)
        opt_ids = {id(p) for p in optimizer._parameter_list}
        if not all(id(p) in opt_ids for p in self._p_tensors
                   if not p.stop_gradient):
            raise ValueError("optimizer parameters must come from the model")
        self._trainable_mask = [not p.stop_gradient for p in self._p_tensors]
        self._compiled = None
        self._donate = donate
        self._step_i = 0
        # gradient merge (k-step accumulation; parity:
        # /root/reference/python/paddle/distributed/fleet/meta_optimizers/
        # gradient_merge_optimizer.py:21): accumulate k micro-step grads
        # in f32, apply the optimizer every k-th call
        self._gm_k = int(gradient_merge)
        if self._gm_k < 1:
            raise ValueError(f"gradient_merge must be >= 1, got {gradient_merge}")
        self._gm_avg = bool(gradient_merge_avg)
        self._gm_accum = None
        self._gm_compiled = None

    def _make_loss_and_grads(self):
        """Closure computing (loss, new_buffers, per-param grads) — the
        shared forward+backward of both the plain and gradient-merge
        compiled programs."""
        model = self.model
        loss_fn = self.loss_fn
        trainable_mask = self._trainable_mask

        def loss_and_grads(param_arrays, buffer_arrays, key, inputs, labels):
            train_params = [a for a, m in zip(param_arrays, trainable_mask)
                            if m]
            frozen = [a for a, m in zip(param_arrays, trainable_mask)
                      if not m]

            def loss_f(tp):
                it_t, it_f = iter(tp), iter(frozen)
                full = [next(it_t) if m else next(it_f)
                        for m in trainable_mask]
                with with_rng_key(key):
                    out, new_bufs = functional_call(
                        model, full, buffer_arrays, inputs)
                with with_rng_key(jax.random.fold_in(key, 777)), no_grad():
                    out_t = _wrap_tree(out)
                    label_t = tuple(_wrap_tree(l) for l in labels)
                    loss_t = loss_fn(out_t, *label_t)
                return loss_t._value.astype(jnp.float32), new_bufs

            (loss, new_bufs), grads = jax.value_and_grad(
                loss_f, has_aux=True)(train_params)
            # re-expand grads to the full param list (None for frozen)
            gi = iter(grads)
            full_grads = [next(gi) if m else None for m in trainable_mask]
            return loss, new_bufs, full_grads

        return loss_and_grads

    def _make_opt_update(self):
        """Closure applying the optimizer to full-per-param grads and
        pinning output placements (shared by both compiled programs)."""
        optimizer = self.optimizer

        def opt_update(param_arrays, full_grads, opt_state, lr):
            # align: optimizer params are a subset (usually ==) of model params
            id2idx = {id(p): i for i, p in enumerate(self._p_tensors)}
            opt_grads = [full_grads[id2idx[id(p)]] if id(p) in id2idx else None
                         for p in optimizer._parameter_list]
            opt_in = [param_arrays[id2idx[id(p)]]
                      for p in optimizer._parameter_list]
            new_opt_params, new_opt_state = optimizer.update(
                opt_in, opt_grads, opt_state, lr)
            # write updates back into the full param list
            new_params = list(param_arrays)
            for p, np_ in zip(optimizer._parameter_list, new_opt_params):
                if np_ is not None:
                    new_params[id2idx[id(p)]] = np_
            # pin outputs to their INPUT shardings: placements must be
            # STABLE across steps (otherwise e.g. ZeRO-1's sharded
            # optimizer update makes XLA emit sharded params, silently
            # drifting stage 1 into stage 3 after the first step; the
            # same applies to the optimizer states in reverse)
            new_params = [
                jax.lax.with_sharding_constraint(a, s)
                if s is not None else a
                for a, s in zip(new_params, self._param_shardings())]
            # `opt_state` here is a tracer: reading `.sharding` off it
            # raises on jax>=0.9, so the pin must come from the LIVE
            # concrete state captured at trace time (tracing happens on
            # the first __call__, after optimizer state init).
            opt_shardings = self._opt_state_shardings()
            new_leaves, new_td = jax.tree_util.tree_flatten(new_opt_state)
            old_leaves = jax.tree_util.tree_leaves(opt_state)
            if len(new_leaves) == len(old_leaves) == len(opt_shardings):
                pinned = [
                    jax.lax.with_sharding_constraint(new, s)
                    if (s is not None and hasattr(new, "shape")
                        and getattr(old, "shape", None) == new.shape)
                    else new
                    for new, old, s in zip(new_leaves, old_leaves,
                                           opt_shardings)]
                new_opt_state = jax.tree_util.tree_unflatten(new_td, pinned)
            elif any(s is not None for s in opt_shardings):
                # an optimizer whose update() changes the state's leaf
                # count would silently lose the ZeRO placement pin —
                # fail loudly instead of drifting sharded state
                raise ValueError(
                    "optimizer.update() returned a state tree whose leaf "
                    f"count ({len(new_leaves)}) differs from init_state's "
                    f"({len(opt_shardings)}); the sharded optimizer-state "
                    "placement pin cannot be applied. Keep the state "
                    "structure stable across steps.")
            return new_params, new_opt_state

        return opt_update

    def _build(self):
        loss_and_grads = self._make_loss_and_grads()
        opt_update = self._make_opt_update()

        def step(param_arrays, buffer_arrays, opt_state, lr, key, inputs,
                 labels):
            loss, new_bufs, full_grads = loss_and_grads(
                param_arrays, buffer_arrays, key, inputs, labels)
            new_params, new_opt_state = opt_update(
                param_arrays, full_grads, opt_state, lr)
            return loss, new_params, new_bufs, new_opt_state

        donate = (0, 2) if self._donate else ()
        return jax.jit(step, donate_argnums=donate)

    def _build_gm(self):
        """Two compiled programs for gradient merge — an accumulate-only
        micro-step and an apply step — selected host-side by
        step_i % k (compile-static: no lax.cond over the optimizer)."""
        loss_and_grads = self._make_loss_and_grads()
        opt_update = self._make_opt_update()
        k, avg = self._gm_k, self._gm_avg
        mask = self._trainable_mask

        def accum_step(param_arrays, buffer_arrays, accum, key, inputs,
                       labels):
            loss, new_bufs, full_grads = loss_and_grads(
                param_arrays, buffer_arrays, key, inputs, labels)
            tg = [g for g, m in zip(full_grads, mask) if m]
            new_accum = [a + g.astype(jnp.float32)
                         for a, g in zip(accum, tg)]
            return loss, new_bufs, new_accum

        def apply_step(param_arrays, buffer_arrays, opt_state, lr, accum,
                       key, inputs, labels):
            loss, new_bufs, full_grads = loss_and_grads(
                param_arrays, buffer_arrays, key, inputs, labels)
            it = iter(accum)
            merged = []
            for g, m in zip(full_grads, mask):
                if not m:
                    merged.append(None)
                    continue
                tot = next(it) + g.astype(jnp.float32)
                if avg:
                    tot = tot / k
                # back to the native grad dtype so the optimizer update
                # behaves exactly like a plain step (keeps param dtype
                # stable for donation)
                merged.append(tot.astype(g.dtype))
            new_params, new_opt_state = opt_update(
                param_arrays, merged, opt_state, lr)
            zero_accum = [jnp.zeros_like(a) for a in accum]
            return loss, new_params, new_bufs, new_opt_state, zero_accum

        da = (2,) if self._donate else ()
        db = (0, 2, 4) if self._donate else ()
        return (jax.jit(accum_step, donate_argnums=da),
                jax.jit(apply_step, donate_argnums=db))

    def _init_gm_accum(self):
        out = []
        for p, m in zip(self._p_tensors, self._trainable_mask):
            if not m:
                continue
            z = jnp.zeros(p._value.shape, jnp.float32)
            s = getattr(p._value, "sharding", None)
            if isinstance(s, jax.sharding.NamedSharding):
                z = jax.device_put(z, s)
            out.append(z)
        return out

    def _param_shardings(self):
        out = []
        for p in self._p_tensors:
            s = getattr(p._value, "sharding", None)
            out.append(s if isinstance(s, jax.sharding.NamedSharding)
                       else None)
        return out

    def _opt_state_shardings(self):
        """Concrete per-leaf NamedShardings of the live optimizer state
        (flattened order), None where unsharded/non-array."""
        out = []
        for leaf in jax.tree_util.tree_leaves(self.optimizer._state):
            s = getattr(leaf, "sharding", None)
            out.append(s if isinstance(s, jax.sharding.NamedSharding)
                       else None)
        return out

    def __call__(self, inputs, labels):
        """inputs / labels: a Tensor or tuple of Tensors. Model is called as
        model(*inputs); loss as loss_fn(model_out, *labels)."""
        # DecompAware kernels read the prim flag at trace time: a toggle
        # must rebuild, not silently keep the other mode's trace (same
        # contract as to_static's (training, prim) mode token)
        if getattr(self, "_built_prim", None) is not None and \
                self._built_prim != _prim():
            self._compiled = None
            self._gm_compiled = None
            # a partial gradient-merge window would blend gradients
            # traced under both decomposition modes — drop it and
            # restart the window cleanly
            self._gm_accum = None
            self._step_i -= self._step_i % self._gm_k
        first = self._compiled is None and self._gm_compiled is None
        if first:
            self._built_prim = _prim()
            if self._gm_k > 1:
                self._gm_compiled = self._build_gm()
            else:
                self._compiled = self._build()
            import os as _os
            from ..utils.flags import FLAGS
            if getattr(FLAGS, "enable_watchdog", None) or \
                    _os.environ.get("FLAGS_enable_watchdog", "").lower() \
                    in ("1", "true"):
                from ..distributed.watchdog import enable_watchdog
                enable_watchdog()
        if self.optimizer._state is None:
            self.optimizer._state = self.optimizer.init_state(
                [p._value for p in self.optimizer._parameter_list])
        p_arrays = [p._value for p in self._p_tensors]
        b_arrays = [b._value for b in self._b_tensors]
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        key = jax.random.fold_in(default_generator._key, self._step_i)

        def _unwrap_batch(x):
            if isinstance(x, Tensor):
                return (x._value,)
            if isinstance(x, (tuple, list)):
                return tuple(e._value if isinstance(e, Tensor)
                             else jnp.asarray(e) for e in x)
            return (jnp.asarray(x),)

        in_arrays = _unwrap_batch(inputs)
        label_arrays = _unwrap_batch(labels)
        if self._gm_k > 1:
            loss = self._call_gm(p_arrays, b_arrays, lr, key, in_arrays,
                                 label_arrays)
        else:
            loss, new_params, new_bufs, new_state = self._compiled(
                p_arrays, b_arrays, self.optimizer._state, lr, key,
                in_arrays, label_arrays)
            for p, a in zip(self._p_tensors, new_params):
                p._replace(a)
            for b, a in zip(self._b_tensors, new_bufs):
                b._replace(a)
            self.optimizer._state = new_state
            self.optimizer._step_count += 1
        self._step_i += 1
        from ..distributed.watchdog import notify_step
        notify_step(self._step_i)
        return Tensor(loss)

    def _call_gm(self, p_arrays, b_arrays, lr, key, in_arrays,
                 label_arrays):
        """One gradient-merge micro-step: accumulate, or (every k-th
        call) merge + optimizer apply. The optimizer steps — and its
        step count / LR schedule advance — only on apply."""
        accum_fn, apply_fn = self._gm_compiled
        if self._gm_accum is None:
            self._gm_accum = self._init_gm_accum()
        is_apply = (self._step_i + 1) % self._gm_k == 0
        if not is_apply:
            loss, new_bufs, new_accum = accum_fn(
                p_arrays, b_arrays, self._gm_accum, key, in_arrays,
                label_arrays)
            for b, a in zip(self._b_tensors, new_bufs):
                b._replace(a)
            self._gm_accum = new_accum
            return loss
        loss, new_params, new_bufs, new_state, new_accum = apply_fn(
            p_arrays, b_arrays, self.optimizer._state, lr,
            self._gm_accum, key, in_arrays, label_arrays)
        for p, a in zip(self._p_tensors, new_params):
            p._replace(a)
        for b, a in zip(self._b_tensors, new_bufs):
            b._replace(a)
        self.optimizer._state = new_state
        self.optimizer._step_count += 1
        self._gm_accum = new_accum
        return loss


# ---------------------------------------------------------------------------
# jit.save / jit.load — AOT export parity
# (reference: paddle.jit.save → TranslatedLayer,
# /root/reference/python/paddle/jit/api.py + translated_layer.py). The
# artifact is serialized StableHLO (jax.export) + params npz — loadable
# without the Python model class, like the reference's program+params.
# ---------------------------------------------------------------------------

def save(layer, path, input_spec=None, **configs):
    """Trace layer.forward over input_spec and write <path>.pdmodel
    (StableHLO + metadata) and <path>.pdiparams.npz. Also writes
    <path>.pdparams (state_dict) so paddle.load works on the same
    prefix."""
    import os
    import pickle

    from ..framework.io import save as _save
    from ..static.program import InputSpec

    if input_spec is None:
        raise ValueError(
            "jit.save needs input_spec=[InputSpec(shape, dtype), ...] "
            "to trace the forward (dynamic dims as 1)")
    specs = [s if isinstance(s, InputSpec) else InputSpec(
        s.shape, s.dtype) for s in input_spec]

    params, buffers = _collect(layer)
    p_arrays = [p._value for _, p in params]
    b_arrays = [b._value for _, b in buffers]
    was_training = getattr(layer, "training", False)
    layer.eval()

    def fn(in_arrays, param_arrays, buffer_arrays):
        out, _ = functional_call(layer, param_arrays, buffer_arrays,
                                 tuple(in_arrays))
        flat, _ = jax.tree_util.tree_flatten(out)
        return tuple(flat)

    in_avals = [jax.ShapeDtypeStruct(
        tuple(d if d and d > 0 else 1 for d in s.shape), s.dtype)
        for s in specs]
    p_avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in p_arrays]
    b_avals = [jax.ShapeDtypeStruct(a.shape, a.dtype) for a in b_arrays]
    try:
        exported = jax.export.export(jax.jit(fn))(in_avals, p_avals,
                                                  b_avals)
    finally:
        if was_training:
            layer.train()

    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path + ".pdmodel", "wb") as f:
        pickle.dump({
            "stablehlo": exported.serialize(),
            "feed_names": [s.name or f"x{i}"
                           for i, s in enumerate(specs)],
            "feed_shapes": [tuple(a.shape) for a in in_avals],
            "feed_dtypes": [str(a.dtype) for a in in_avals],
            "fetch_names": [f"out{i}"
                            for i in range(len(exported.out_avals))],
            "kind": "jit.save",
            "n_params": len(p_arrays),
        }, f)
    np.savez(path + ".pdiparams",
             **{f"p{i}": np.asarray(a)
                for i, a in enumerate(list(p_arrays) + list(b_arrays))})
    _save({"state_dict": layer.state_dict()}, path + ".pdparams")
    return path


class TranslatedLayer:
    """Callable rebuilt from a jit.save artifact (reference
    TranslatedLayer, jit/translated_layer.py) — runs the compiled
    StableHLO, no Python model code needed."""

    def __init__(self, path: str):
        import pickle
        with open(path + ".pdmodel", "rb") as f:
            meta = pickle.load(f)
        self._exported = jax.export.deserialize(meta["stablehlo"])
        z = np.load(path + ".pdiparams.npz")
        stored = [jnp.asarray(z[f"p{i}"]) for i in range(len(z.files))]
        n_p = meta["n_params"]
        self._params = stored[:n_p]
        self._buffers = stored[n_p:]
        self.feed_names = meta["feed_names"]

    def __call__(self, *args):
        in_arrays = [a._value if isinstance(a, Tensor) else jnp.asarray(a)
                     for a in args]
        out = self._exported.call(list(in_arrays), self._params,
                                  self._buffers)
        outs = [Tensor(o) for o in out]
        return outs[0] if len(outs) == 1 else tuple(outs)

    def eval(self):
        return self

    def train(self):
        raise RuntimeError("TranslatedLayer is inference-only")


def load(path, **configs) -> TranslatedLayer:
    return TranslatedLayer(path)


# --- telemetry/config parity (reference jit/api.py) ------------------------

_to_static_enabled = True


def enable_to_static(enable: bool = True):
    """Globally toggle to_static compilation (reference
    paddle.jit.enable_to_static). When disabled, StaticFunction runs
    its target eagerly."""
    global _to_static_enabled
    _to_static_enabled = bool(enable)


def set_code_level(level=100, also_to_stdout=False):
    """Reference sets dy2static transformed-code logging verbosity; the
    tracing pipeline here has no transformed source to print — the knob
    is accepted and recorded (telemetry lives on StaticFunction:
    retrace_count / trace_signatures / graph_break_count)."""
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if also_to_stdout else logging.INFO)


def set_verbosity(level=0, also_to_stdout=False):
    import logging
    logging.getLogger("paddle_tpu.jit").setLevel(
        logging.DEBUG if level > 0 else logging.WARNING)


__all__ += ["enable_to_static", "set_code_level", "set_verbosity"]
