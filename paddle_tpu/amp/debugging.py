"""amp.debugging — numerics sanitizer (nan/inf detection + op stats).

Reference: /root/reference/python/paddle/amp/debugging.py
(TensorCheckerConfig :157, enable_tensor_checker/disable_tensor_checker,
check_numerics, collect_operator_stats :459) backed by the C++ per-op
nan/inf scan (/root/reference/paddle/fluid/eager/nan_inf_utils.h,
FLAGS_check_nan_inf). TPU-native: the checker hooks the same op
dispatcher AMP uses — each checked op's outputs get a jnp isfinite
reduction (fused by XLA; one scalar readback only when debug_mode
demands a host-side raise).
"""
from __future__ import annotations

import contextlib
from enum import Enum
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["DebugMode", "TensorCheckerConfig", "enable_tensor_checker",
           "disable_tensor_checker", "check_numerics",
           "enable_operator_stats_collection",
           "disable_operator_stats_collection", "collect_operator_stats"]


class DebugMode(Enum):
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


class TensorCheckerConfig:
    """Parity with reference TensorCheckerConfig (amp/debugging.py:157):
    enable + debug_mode + op/dtype filters."""

    def __init__(self, enable: bool = True,
                 debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT,
                 output_dir: Optional[str] = None,
                 checked_op_list: Optional[List[str]] = None,
                 skipped_op_list: Optional[List[str]] = None,
                 debug_step: Optional[tuple] = None):
        self.enable = enable
        self.debug_mode = debug_mode
        self.output_dir = output_dir
        self.checked_op_list = set(checked_op_list or [])
        self.skipped_op_list = set(skipped_op_list or [])
        self.debug_step = debug_step
        self._step = 0

    def _should_check(self, op_name: str) -> bool:
        if not self.enable:
            return False
        if self.debug_step is not None:
            lo, hi = self.debug_step
            if not (lo <= self._step < hi):
                return False
        if self.checked_op_list and op_name not in self.checked_op_list:
            return False
        if op_name in self.skipped_op_list:
            return False
        return True

    def step(self):
        """Advance the training-step counter that debug_step windows are
        measured against. Called automatically by Optimizer.step()."""
        self._step += 1


_checker: Optional[TensorCheckerConfig] = None
_found: List[Dict] = []


def enable_tensor_checker(config: TensorCheckerConfig):
    """Install the per-op nan/inf hook (reference
    enable_tensor_checker). Starts a fresh findings list."""
    global _checker
    _checker = config
    _found.clear()
    _pending.clear()
    _dropped[0] = 0
    from ..framework import core as fcore
    fcore._set_check_hook(_check_outputs)


def disable_tensor_checker():
    global _checker
    _checker = None
    from ..framework import core as fcore
    fcore._set_check_hook(None)


def _check_outputs(op_name: str, arrays):
    """Called by the dispatcher with each op's output arrays (eager
    path). ABORT mode blocks on a scalar readback per op (debugging is
    the point there); record modes enqueue device-side flags and resolve
    them lazily in found_issues(), preserving async dispatch."""
    cfg = _checker
    if cfg is None or not cfg._should_check(op_name):
        return
    abort = cfg.debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT
    for i, a in enumerate(arrays):
        if not isinstance(a, jax.Array) or isinstance(a, jax.core.Tracer):
            continue  # traced values are checked by the jitted variant
        if not jnp.issubdtype(a.dtype, jnp.floating):
            continue
        if not abort:
            if len(_pending) < 10000:
                # keep only SCALAR device values (not the output array —
                # retaining it would pin activations in HBM); resolved
                # lazily in found_issues(). Counting runs on the native
                # dtype (an f32 cast would flag big finite f64 as inf).
                _pending.append((op_name, i, jnp.isnan(a).sum(),
                                 jnp.isinf(a).sum(), tuple(a.shape),
                                 str(a.dtype)))
            else:
                _dropped[0] += 1  # surface saturation, don't lie
            continue
        if bool(jnp.isfinite(a).all()):
            continue
        info = _describe(op_name, i, a)
        _found.append(info)
        raise FloatingPointError(
            f"nan/inf detected in output {i} of op {op_name!r}: "
            f"{info['num_nan']} NaN, {info['num_inf']} Inf "
            f"(shape {info['shape']}, dtype {info['dtype']})")


_pending: List[tuple] = []
_dropped = [0]


def _describe(op_name, i, a) -> Dict:
    arr = np.asarray(a)
    return {
        "op": op_name, "output_index": i,
        "num_nan": int(np.isnan(arr).sum()),
        "num_inf": int(np.isinf(arr).sum()),
        "shape": tuple(arr.shape), "dtype": str(arr.dtype),
    }


def found_issues() -> List[Dict]:
    """Findings so far; resolves the lazily-enqueued record-mode
    counters (the only point record mode synchronizes with the device).
    Raises if the pending queue saturated (checks were dropped)."""
    global _pending
    pending, _pending = _pending, []
    for op_name, i, nan_ct, inf_ct, shape, dtype in pending:
        num_nan, num_inf = int(nan_ct), int(inf_ct)
        if num_nan or num_inf:
            _found.append({"op": op_name, "output_index": i,
                           "num_nan": num_nan, "num_inf": num_inf,
                           "shape": shape, "dtype": dtype})
    if _dropped[0]:
        # resolve what WAS queued first (evidence preserved), then report
        # the saturation
        k, _dropped[0] = _dropped[0], 0
        raise RuntimeError(
            f"nan/inf record queue saturated: {k} op outputs were not "
            f"checked — call found_issues() periodically (e.g. once per "
            f"step) to drain it; findings so far remain available via "
            f"found_issues()")
    return list(_found)


def check_numerics(tensor, op_type: str = "", var_name: str = "",
                   debug_mode: DebugMode = DebugMode.CHECK_NAN_INF_AND_ABORT):
    """One-shot check (reference paddle.amp.debugging.check_numerics).
    Returns (num_nan, num_inf, num_zero) Tensors like the reference."""
    from ..framework.core import Tensor, apply_nodiff

    def f(a):
        af = a.astype(jnp.float32)
        return (jnp.isnan(af).sum(), jnp.isinf(af).sum(),
                (af == 0).sum())
    nan_ct, inf_ct, zero_ct = apply_nodiff("check_numerics", f, tensor)
    if debug_mode == DebugMode.CHECK_NAN_INF_AND_ABORT:
        n_nan, n_inf = int(nan_ct.numpy()), int(inf_ct.numpy())
        if n_nan or n_inf:
            raise FloatingPointError(
                f"check_numerics({op_type or 'tensor'} {var_name}): "
                f"{n_nan} NaN, {n_inf} Inf")
    return nan_ct, inf_ct, zero_ct


# ---------------------------------------------------------------------------
# operator stats collection (reference collect_operator_stats :459)
# ---------------------------------------------------------------------------

_op_stats: Optional[Dict[str, Dict[str, int]]] = None


def enable_operator_stats_collection():
    """Count op calls per dtype (reference op-stats table)."""
    global _op_stats
    _op_stats = {}
    from ..framework import core as fcore
    fcore._set_stats_hook(_record_stats)


def disable_operator_stats_collection():
    global _op_stats
    from ..framework import core as fcore
    fcore._set_stats_hook(None)
    stats = _op_stats
    _op_stats = None
    if stats:
        print(_format_stats(stats))
    return stats


def _record_stats(op_name: str, arrays):
    if _op_stats is None:
        return
    row = _op_stats.setdefault(op_name, {})
    for a in arrays:
        d = str(getattr(a, "dtype", "other"))
        row[d] = row.get(d, 0) + 1


def _format_stats(stats) -> str:
    dtypes = ["float32", "bfloat16", "float16", "other"]
    header = f"{'Op':<28}" + "".join(f"{d:>12}" for d in dtypes)
    lines = ["<------------- op list of amp running ------------->",
             header, "-" * len(header)]
    for op, row in sorted(stats.items()):
        counts = []
        for d in dtypes:
            c = row.get(d, 0) if d != "other" else sum(
                v for k, v in row.items() if k not in dtypes)
            counts.append(c)
        lines.append(f"{op:<28}" + "".join(f"{c:>12}" for c in counts))
    return "\n".join(lines)


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    try:
        yield
    finally:
        disable_operator_stats_collection()
