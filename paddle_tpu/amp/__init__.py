"""paddle_tpu.amp — bf16-first autocast + GradScaler.

Parity: /root/reference/python/paddle/amp/ (auto_cast.py:703, decorate:787,
amp_lists.py, grad_scaler.py:578). On TPU the native mixed-precision dtype
is bfloat16 (MXU matmul dtype); float16 is accepted for API compat. The
autocast hook installs into framework.core.apply — the same interception
point as the reference's generated AMP code in each ad_func
(/root/reference/paddle/fluid/eager/amp_auto_cast.h).
"""
from __future__ import annotations

import contextlib
from typing import Optional

import jax.numpy as jnp
import numpy as np

from ..framework import dtype as dtypes
from ..framework.core import Tensor, _set_amp_hook
from .grad_scaler import GradScaler  # noqa: F401

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler",
           "white_list", "black_list", "is_auto_cast_enabled"]

# Per-op lists (subset of /root/reference/python/paddle/amp/amp_lists.py:17-100)
WHITE_LIST = {
    "matmul", "bmm", "mm", "linear", "conv1d", "conv2d", "conv3d",
    "conv1d_transpose", "conv2d_transpose", "conv3d_transpose", "einsum",
    "flash_attention", "sdpa", "addmm", "mv",
}
BLACK_LIST = {
    "exp", "log", "log2", "log10", "log1p", "logsumexp", "mean", "sum",
    "softmax", "log_softmax", "cross_entropy", "nll_loss", "bce",
    "bce_logits", "mse_loss", "l1_loss", "kl_div", "layer_norm",
    "batch_norm", "bn_stats", "group_norm", "instance_norm", "rms_norm",
    "norm", "cumsum", "pow", "square", "reciprocal", "rsqrt", "sqrt",
    "sigmoid", "erf", "erfinv",
}


class _AmpState:
    enabled = False
    dtype = None          # np.dtype target (bfloat16/float16)
    level = "O1"
    custom_white = set()
    custom_black = set()


_state = _AmpState()


def is_auto_cast_enabled() -> bool:
    return _state.enabled


def white_list():
    return WHITE_LIST | _state.custom_white


def black_list():
    return (BLACK_LIST | _state.custom_black) - _state.custom_white


def cast_plan(op_name):
    """The autocast decision for one op under the current state:
    ('down'|'up'|None, target np.dtype). Shared by the eager hook below
    and the partial-capture recorder (jit/partial_capture.py), which
    applies the same casts to symbolic segment values at record time."""
    if not _state.enabled or op_name == "cast" or \
            op_name.startswith("subgraph["):
        # "cast" would recurse; a captured subgraph replay
        # (partial_capture._materialize) already has its casts recorded
        # inside — re-casting its inputs would corrupt the segment
        return None, None
    wl = op_name in WHITE_LIST or op_name in _state.custom_white
    # explicit custom white-list entries override the built-in black list
    bl = (op_name in BLACK_LIST or op_name in _state.custom_black) and \
        op_name not in _state.custom_white
    if (not bl) if _state.level == "O2" else (wl and not bl):
        return "down", _state.dtype
    if bl:
        return "up", np.float32
    return None, None


def cast_needed(plan, dtype):
    """Whether a tensor of `dtype` needs casting under `plan`."""
    if plan == "down":
        return dtype == np.float32
    if plan == "up":
        return dtype == np.dtype(_state.dtype)
    return False


def _amp_hook(op_name, tensors):
    plan, tgt = cast_plan(op_name)
    if plan is None:
        return tensors
    return [t.astype(tgt) if cast_needed(plan, t.dtype) else t
            for t in tensors]


_set_amp_hook(_amp_hook)


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None,
              custom_black_list=None, level: str = "O1",
              dtype: str = "bfloat16", use_promote: bool = True):
    """paddle.amp.auto_cast parity; dtype defaults to bfloat16 (TPU)."""
    prev = (_state.enabled, _state.dtype, _state.level,
            _state.custom_white, _state.custom_black)
    _state.enabled = enable
    _state.dtype = dtypes.convert_dtype(dtype)
    _state.level = level
    _state.custom_white = set(custom_white_list or ())
    _state.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_state.enabled, _state.dtype, _state.level,
         _state.custom_white, _state.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight=None, save_dtype=None):
    """paddle.amp.decorate parity: O2 casts model params to the AMP dtype
    (optimizers keep float32 master weights via multi_precision)."""
    d = dtypes.convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    if level == "O2":
        for m in model_list:
            m.to(dtype=d)
    if optimizers is None:
        return models
    return models, optimizers


def is_float16_supported(device=None):
    return True


def is_bfloat16_supported(device=None):
    return True
