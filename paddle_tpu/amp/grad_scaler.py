"""GradScaler (parity: /root/reference/python/paddle/amp/grad_scaler.py:578).

On TPU the default training dtype is bfloat16, whose dynamic range matches
float32 — loss scaling is numerically unnecessary. The scaler is therefore
API-complete but cheap: with bf16 it is an identity pass-through unless
float16 is explicitly in play (use_dynamic_loss_scaling still implemented
for fp16 parity)."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=2.0 ** 16,
                 incr_ratio=2.0, decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling) if enable else 1.0
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        self._unscaled = set()  # optimizers already unscaled this step

    def scale(self, var: Tensor) -> Tensor:
        if not self._enable:
            return var
        from ..tensor.math import multiply
        return multiply(var, self._scale)

    def unscale_(self, optimizer):
        if not self._enable or id(optimizer) in self._unscaled:
            return
        self._unscaled.add(id(optimizer))
        inv = 1.0 / self._scale
        found_inf = False
        for p in optimizer._parameter_list:
            if p.grad is None:
                continue
            g = p.grad._value.astype(jnp.float32) * inv
            if bool(jnp.any(~jnp.isfinite(g))):
                found_inf = True
            p.grad._replace(g.astype(p.grad._value.dtype))
        self._found_inf = found_inf

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self._unscaled.discard(id(optimizer))
        self.update()

    def minimize(self, optimizer, scaled_loss):
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_init_loss_scaling(self):
        return self._scale

    def set_init_loss_scaling(self, v):
        self._scale = float(v)

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio,
                "incr_every_n_steps": self._incr_every,
                "decr_every_n_nan_or_inf": self._decr_every,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)


AmpScaler = GradScaler
