"""Seq2seq decoding API (parity:
/root/reference/python/paddle/nn/decode.py — Decoder base,
BeamSearchDecoder:153, dynamic_decode:994).

The reference drives a cell step-by-step through a while_loop with beam
bookkeeping in-graph. TPU-native: the beam expansion math is the shared
jnp core (models.generation.beam_step — same code the causal-LM
beam_search uses); the cell steps run eagerly over Tensors (cells are
tiny — the compiled-decode fast path for LLM serving lives in
models.generation / inference.ServingEngine).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.core import Tensor

__all__ = ["Decoder", "BeamSearchDecoder", "dynamic_decode"]


class Decoder:
    """Abstract decoder interface. THIS driver's contract (which is
    narrower than the reference's — dynamic_decode here drives beam
    decoding only):

    - ``end_token`` attribute (int);
    - ``initialize(inits) -> (ids, states, scores, finished)`` with ids
      [batch*beam] int32, scores/finished [batch, beam];
    - ``step(time, ids, states, scores, finished, lengths, **kw) ->
      (tok_idx, beam_idx, scores, finished, lengths, next_ids,
      new_states)``;
    - ``finalize(predicted_ids, parent_idx, scores) -> [b, T, beam]``
      numpy token array (parent-pointer backtracking).
    """

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, ids, states, scores, finished, lengths,
             **kwargs):
        raise NotImplementedError

    def finalize(self, predicted_ids, parent_idx, scores):
        raise NotImplementedError


def _tile_beam(x, beam_size):
    """[batch, ...] -> [batch * beam, ...] (repeat each row)."""
    a = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    return Tensor(jnp.repeat(a, beam_size, axis=0))


class BeamSearchDecoder(Decoder):
    """Beam-search wrapper over an RNN cell (reference
    BeamSearchDecoder, decode.py:153).

    cell(input, states) -> (output, new_states); embedding_fn maps
    selected ids to the next input; output_fn maps cell output to
    vocab logits.
    """

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        """Tile a [batch, ...] tensor to [batch * beam, ...] (for
        encoder outputs used inside the cell)."""
        return _tile_beam(x, beam_size)

    # -- Decoder interface ---------------------------------------------------
    def initialize(self, initial_cell_states):
        nb = self.beam_size
        states = jax.tree_util.tree_map(
            lambda t: _tile_beam(t, nb), initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        leaves = jax.tree_util.tree_leaves(
            initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        b = leaves[0].shape[0]
        ids = jnp.full((b * nb,), self.start_token, jnp.int32)
        # beam 0 carries the live hypothesis; the rest start dead so the
        # first expansion doesn't pick duplicates
        scores = jnp.tile(
            jnp.asarray([0.0] + [-1e30] * (nb - 1), jnp.float32), (b, 1))
        finished = jnp.zeros((b, nb), bool)
        return ids, states, scores, finished

    def _logits(self, ids, states):
        inp = Tensor(ids)
        if self.embedding_fn is not None:
            inp = self.embedding_fn(inp)
        out, new_states = self.cell(inp, states)
        if self.output_fn is not None:
            out = self.output_fn(out)
        return out, new_states

    def step(self, time, ids, states, scores, finished, lengths,
             **kwargs):
        from ..models.generation import beam_step
        nb = self.beam_size
        out, new_states = self._logits(ids, states)
        logits = out._value.astype(jnp.float32)
        b = logits.shape[0] // nb
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(b, nb, -1)
        scores, beam_idx, tok_idx, finished, lengths = beam_step(
            scores, logp, finished, self.end_token, lengths)
        sel = (jnp.arange(b, dtype=jnp.int32)[:, None] * nb
               + beam_idx).reshape(b * nb)
        new_states = jax.tree_util.tree_map(
            lambda t: Tensor(t._value[sel]), new_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        return (tok_idx, beam_idx, scores, finished, lengths,
                tok_idx.reshape(b * nb), new_states)

    def finalize(self, predicted_ids, parent_idx, scores):
        """Backtrack parent pointers into full sequences
        [batch, time, beam] (reference gather_tree semantics)."""
        t_max = len(predicted_ids)
        b, nb = scores.shape
        seqs = np.zeros((b, t_max, nb), np.int32)
        # walk backwards following parents
        cur_parent = np.tile(np.arange(nb, dtype=np.int32), (b, 1))
        for t in range(t_max - 1, -1, -1):
            toks = np.asarray(predicted_ids[t])
            pars = np.asarray(parent_idx[t])
            seqs[:, t, :] = np.take_along_axis(toks, cur_parent, axis=1)
            cur_parent = np.take_along_axis(pars, cur_parent, axis=1)
        return seqs


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Drive decoder.step until every beam finishes or max_step_num
    (reference dynamic_decode, decode.py:994). Returns
    (predicted_ids Tensor [batch, time, beam] — or time-major — sorted
    best-first, final_states[, sequence_lengths]).

    is_test is accepted (a memory hint with no effect here — the eager
    loop already keeps only per-step ids). impute_finished is NOT
    implemented: states of finished beams keep evolving (their outputs
    are frozen to eos regardless); requesting it is rejected rather
    than silently ignored."""
    if impute_finished:
        raise NotImplementedError(
            "dynamic_decode(impute_finished=True): state imputation for "
            "finished beams is not implemented; final_states of "
            "finished beams reflect continued (discarded) steps")
    ids, states, scores, finished = decoder.initialize(inits)
    lengths0 = jnp.zeros_like(scores, dtype=jnp.int32)
    lengths = lengths0
    max_steps = max_step_num if max_step_num is not None else 256
    pred_steps = []
    parent_steps = []
    for t in range(int(max_steps)):
        (tok_idx, beam_idx, scores, finished, lengths, ids,
         states) = decoder.step(t, ids, states, scores, finished,
                                lengths, **kwargs)
        pred_steps.append(np.asarray(tok_idx))
        parent_steps.append(np.asarray(beam_idx))
        if bool(np.asarray(finished).all()):
            break
    seqs = decoder.finalize(pred_steps, parent_steps, scores)  # [b,T,nb]
    # order beams best-first by final score
    order = np.argsort(-np.asarray(scores), axis=1)
    seqs = np.take_along_axis(seqs, order[:, None, :], axis=2)
    end = decoder.end_token
    lengths = (seqs != end).cumprod(axis=1).sum(axis=1)  # pre-eos length
    out = seqs.transpose(1, 0, 2) if output_time_major else seqs
    result = (Tensor(jnp.asarray(out)), states)
    if return_length:
        result = result + (Tensor(jnp.asarray(lengths.astype(np.int64))),)
    return result
