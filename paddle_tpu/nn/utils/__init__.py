"""paddle.nn.utils parity (reference python/paddle/nn/utils/):
weight_norm / remove_weight_norm / spectral_norm reparameterizations,
parameters_to_vector / vector_to_parameters."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Parameter, Tensor, apply

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except(w, dim):
    axes = tuple(i for i in range(w.ndim) if i != dim)
    return jnp.sqrt(jnp.sum(jnp.square(w.astype(jnp.float32)), axis=axes,
                            keepdims=True))


def weight_norm(layer, name: str = "weight", dim: int = 0):
    """Reparameterize layer.<name> as g * v / ||v|| (reference
    nn.utils.weight_norm): trains g (per-dim magnitude) and v
    (direction); a forward-pre-hook recomputes the weight each call so
    gradients flow into g and v."""
    w = getattr(layer, name)
    if dim is None:
        dim = -1
    warr = w._value
    g0 = _norm_except(warr, dim % warr.ndim)
    g = Parameter(g0.astype(warr.dtype))
    v = Parameter(warr)
    setattr(layer, name + "_g", g)
    setattr(layer, name + "_v", v)
    # the original param stops being trainable state
    layer._parameters.pop(name, None)

    def _recompute(lyr, inputs):
        def f(gv, vv):
            axes = tuple(i for i in range(vv.ndim) if i != dim % vv.ndim)
            nrm = jnp.sqrt(jnp.sum(jnp.square(
                vv.astype(jnp.float32)), axis=axes, keepdims=True))
            return (gv.astype(jnp.float32) * vv.astype(jnp.float32)
                    / jnp.maximum(nrm, 1e-12)).astype(vv.dtype)
        object.__setattr__(lyr, name, apply("weight_norm", f, g, v))
        return None

    handle = layer.register_forward_pre_hook(_recompute)
    layer._weight_norm_hook = (name, handle)
    _recompute(layer, None)
    return layer


def remove_weight_norm(layer, name: str = "weight"):
    """Fold g*v/||v|| back into a plain parameter."""
    hook = getattr(layer, "_weight_norm_hook", None)
    if hook is None or hook[0] != name:
        raise ValueError(f"layer has no weight_norm on {name!r}")
    hook[1].remove()
    w = getattr(layer, name)
    setattr(layer, name, Parameter(w._value))
    for suffix in ("_g", "_v"):
        layer._parameters.pop(name + suffix, None)
        if hasattr(layer, name + suffix):
            object.__delattr__(layer, name + suffix)
    del layer._weight_norm_hook
    return layer


def spectral_norm(layer, name: str = "weight", n_power_iterations: int = 1,
                  eps: float = 1e-12, dim: int = 0):
    """Spectral normalization via power iteration (reference
    nn.utils.spectral_norm): weight / sigma_max recomputed per call,
    with the u vector persisted across calls so the estimate converges
    over training."""
    from ...framework.core import default_generator
    w = getattr(layer, name)
    warr = w._value
    mat = jnp.moveaxis(warr, dim, 0).reshape(warr.shape[dim], -1)
    key = default_generator.next_key()
    u0 = jax.random.normal(key, (mat.shape[0],), jnp.float32)
    layer._sn_u = u0 / jnp.linalg.norm(u0)
    v = Parameter(warr)
    setattr(layer, name + "_orig", v)
    layer._parameters.pop(name, None)

    def _power_iter(m, u, iters):
        for _ in range(iters):
            vvec = m.T @ u
            vvec = vvec / jnp.maximum(jnp.linalg.norm(vvec), eps)
            u = m @ vvec
            u = u / jnp.maximum(jnp.linalg.norm(u), eps)
        return u, vvec

    def _recompute(lyr, inputs):
        # persist the power-iteration vector across calls (the reference
        # updates the u buffer every forward, so the sigma estimate
        # converges over training even with n_power_iterations=1)
        if not isinstance(v._value, jax.core.Tracer):
            m_c = jnp.moveaxis(v._value.astype(jnp.float32), dim, 0) \
                .reshape(v._value.shape[dim], -1)
            u_new, _ = _power_iter(m_c, lyr._sn_u, n_power_iterations)
            object.__setattr__(lyr, "_sn_u", u_new)

        def f(vv):
            m = jnp.moveaxis(vv.astype(jnp.float32), dim, 0) \
                .reshape(vv.shape[dim], -1)
            u, vvec = _power_iter(m, lyr._sn_u, n_power_iterations)
            sigma = u @ (m @ vvec)
            return (vv.astype(jnp.float32) / jnp.maximum(sigma, eps)) \
                .astype(vv.dtype)
        object.__setattr__(lyr, name, apply("spectral_norm", f, v))
        return None

    layer.register_forward_pre_hook(_recompute)
    _recompute(layer, None)
    return layer


def parameters_to_vector(parameters, name=None):
    from ...tensor.manipulation import concat
    flats = [p.reshape([-1]) for p in parameters]
    return concat(flats, axis=0)


def vector_to_parameters(vec, parameters, name=None):
    off = 0
    for p in parameters:
        n = p.size
        chunk = vec[off:off + n].reshape(p.shape)
        p._replace(chunk._value if isinstance(chunk, Tensor) else chunk)
        off += n
