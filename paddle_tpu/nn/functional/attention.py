"""Attention functionals (parity:
/root/reference/python/paddle/nn/functional/flash_attention.py:146,441).
Layout matches paddle: [batch, seq, num_heads, head_dim].

Attention dropout (training): applied to the softmax probs on the dense
XLA path (the Pallas kernel only serves dropout=0; the gate is
dropout-aware). Keys come from the framework RNG stream, so compiled
TrainStep runs are deterministic per step key."""
from __future__ import annotations

from ...framework.core import Tensor, apply, default_generator
from ...ops import flash_attention as _fa

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "sdp_kernel"]


def _dropout_key(dropout, training):
    if dropout and float(dropout) != 0.0 and training:
        return float(dropout), default_generator.next_key()
    return 0.0, None


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    p, dkey = _dropout_key(dropout, training)
    if dkey is None:
        out = apply("flash_attention",
                    lambda q, k, v: _fa(q, k, v, causal=causal),
                    query, key, value)
    else:
        # key as a positional arg (not closure) — partial capture lifts
        # it to a segment input, keeping stochastic segments cacheable
        out = apply("flash_attention",
                    lambda q, k, v, dk: _fa(q, k, v, causal=causal,
                                            dropout=p, dropout_key=dk),
                    query, key, value, dkey)
    if return_softmax:
        return out, None
    return out, None  # paddle returns (out, softmax) tuple


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False,
                                 training=True, name=None):
    p, dkey = _dropout_key(dropout_p, training)
    if attn_mask is not None:
        if dkey is None:
            return apply("sdpa",
                         lambda q, k, v, m: _fa(q, k, v, attn_mask=m,
                                                causal=is_causal),
                         query, key, value, attn_mask)
        return apply("sdpa",
                     lambda q, k, v, m, dk: _fa(q, k, v, attn_mask=m,
                                                causal=is_causal,
                                                dropout=p, dropout_key=dk),
                     query, key, value, attn_mask, dkey)
    if dkey is None:
        return apply("sdpa",
                     lambda q, k, v: _fa(q, k, v, causal=is_causal),
                     query, key, value)
    return apply("sdpa",
                 lambda q, k, v, dk: _fa(q, k, v, causal=is_causal,
                                         dropout=p, dropout_key=dk),
                 query, key, value, dkey)


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen (packed-sequence) flash attention (parity:
    /root/reference/python/paddle/nn/functional/flash_attention.py:302).

    query/key/value: packed [total_tokens, num_heads, head_dim];
    cu_seqlens_*: [n_seqs+1] cumulative lengths. Returns (out, None) like
    the padded API. On TPU this runs the segment-ids Pallas kernel; the
    dense reference path is used on CPU/odd shapes."""
    if dropout and float(dropout) != 0.0 and training:
        raise NotImplementedError(
            "flash_attn_unpadded: attention dropout is not implemented "
            "on the packed varlen kernel; pass dropout=0.0 (or "
            "training=False). Silently training without the requested "
            "dropout would be wrong.")
    from ...ops.flash_attention import flash_attn_varlen

    def _raw(t):
        return t._value if isinstance(t, Tensor) else t

    cu_q = _raw(cu_seqlens_q)
    cu_k = _raw(cu_seqlens_k)
    out = apply("flash_attn_unpadded",
                lambda q, k, v: flash_attn_varlen(
                    q, k, v, cu_q, cu_k, max_seqlen_q, max_seqlen_k,
                    scale=scale, causal=causal),
                query, key, value)
    return out, None


class sdp_kernel:
    """Context manager API-compat shim (paddle.nn.functional.sdp_kernel)."""

    def __init__(self, enable_flash=True, enable_math=True,
                 enable_mem_efficient=True):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False
