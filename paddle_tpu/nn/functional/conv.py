"""Convolution functionals over lax.conv_general_dilated — the op XLA maps
onto the MXU. Parity: /root/reference/python/paddle/nn/functional/conv.py.
Weight layout matches paddle: [out_c, in_c/groups, *kernel]."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose",
           "conv2d_transpose", "conv3d_transpose"]


def _norm_tuple(v, n):
    if isinstance(v, int):
        return (v,) * n
    return tuple(int(e) for e in v)


def _norm_padding(padding, n):
    """Returns lax padding spec: 'SAME', 'VALID' or [(lo,hi)]*n."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n
    padding = list(padding)
    if len(padding) == n and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n)]
    if all(isinstance(p, (list, tuple)) for p in padding):
        # paddle full-form [[0,0],[0,0],[h0,h1],[w0,w1]] (NCHW)
        flat = [tuple(p) for p in padding]
        if len(flat) == n + 2:
            return flat[2:]
        return flat
    raise ValueError(f"bad padding {padding}")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, channel_last):
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    pad = _norm_padding(padding, n)

    spatial = "DHW"[3 - n:]
    if channel_last:
        lhs_spec = "N" + spatial + "C"
    else:
        lhs_spec = "NC" + spatial
    rhs_spec = "OI" + spatial
    out_spec = lhs_spec
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2),
                                        (lhs_spec, rhs_spec, out_spec))

    def f(a, w, *b):
        out = jax.lax.conv_general_dilated(
            a, w.astype(a.dtype), window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn, feature_group_count=groups,
            preferred_element_type=None)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[out.ndim - 1 if channel_last else 1] = -1
            out = out + b[0].astype(out.dtype).reshape(bias_shape)
        return out

    if bias is not None:
        return apply(f"conv{n}d", f, x, weight, bias)
    return apply(f"conv{n}d", f, x, weight)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1,
                 channel_last=data_format == "NLC")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2,
                 channel_last=data_format == "NHWC")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3,
                 channel_last=data_format == "NDHWC")


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, channel_last, output_size=None):
    strides = _norm_tuple(stride, n)
    dil = _norm_tuple(dilation, n)
    opad = _norm_tuple(output_padding, n)
    pad = _norm_padding(padding, n)

    spatial = "DHW"[3 - n:]
    lhs_spec = ("N" + spatial + "C") if channel_last else ("NC" + spatial)
    # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
    rhs_spec = "IO" + spatial
    dn = jax.lax.conv_dimension_numbers((1,) * (n + 2), (1,) * (n + 2),
                                        (lhs_spec, rhs_spec, lhs_spec))

    def f(a, w, *b):
        if isinstance(pad, str):
            padding_cfg = pad
        else:
            # convert forward-conv padding to transpose padding
            padding_cfg = []
            for i in range(n):
                k = (w.shape[2 + i] - 1) * dil[i] + 1
                lo = k - 1 - pad[i][0]
                hi = k - 1 - pad[i][1] + opad[i]
                padding_cfg.append((lo, hi))
        def one_group(a_g, w_g):
            w_t = jnp.swapaxes(w_g, 0, 1)  # -> [out_c, in_c, *k]
            w_t = jnp.flip(w_t, axis=tuple(range(2, 2 + n)))
            return jax.lax.conv_general_dilated(
                a_g, w_t.astype(a_g.dtype), window_strides=(1,) * n,
                padding=padding_cfg, lhs_dilation=strides,
                rhs_dilation=dil,
                dimension_numbers=jax.lax.conv_dimension_numbers(
                    (1,) * (n + 2), (1,) * (n + 2),
                    (lhs_spec, "OI" + spatial, lhs_spec)))

        if groups > 1:
            # grouped transpose conv: per-group slices of the input
            # channels and the [in_c, out_c/groups, *k] weight, outputs
            # concatenated on the channel axis (parity:
            # /root/reference/python/paddle/nn/functional/conv.py
            # conv2d_transpose groups semantics)
            ch_ax = (n + 1) if channel_last else 1
            icg = a.shape[ch_ax] // groups
            outs = [one_group(
                jax.lax.slice_in_dim(a, g * icg, (g + 1) * icg,
                                     axis=ch_ax),
                w[g * icg:(g + 1) * icg]) for g in range(groups)]
            out = jnp.concatenate(outs, axis=ch_ax)
        else:
            out = one_group(a, w)
        if b:
            bias_shape = [1] * out.ndim
            bias_shape[out.ndim - 1 if channel_last else 1] = -1
            out = out + b[0].astype(out.dtype).reshape(bias_shape)
        return out

    if bias is not None:
        return apply(f"conv{n}d_transpose", f, x, weight, bias)
    return apply(f"conv{n}d_transpose", f, x, weight)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCL", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, data_format == "NLC",
                           output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format == "NHWC",
                           output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     output_size=None, data_format="NCDHW", name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format == "NDHWC",
                           output_size)
