"""Common functionals: linear, dropout, embedding, one_hot, interpolate …
(parity: /root/reference/python/paddle/nn/functional/common.py,
input.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply, apply_nodiff, default_generator
from ...framework import dtype as dtypes

__all__ = [
    "linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
    "embedding", "one_hot", "interpolate", "upsample", "unfold", "fold",
    "cosine_similarity", "pixel_shuffle", "pixel_unshuffle",
    "channel_shuffle", "class_center_sample", "pad",
]

from .loss import cosine_similarity  # shared
from ...tensor.manipulation import pad  # shared


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b). Paddle weight layout: [in, out]."""
    if bias is not None:
        return apply("linear", lambda a, w, b: jnp.matmul(a, w.astype(a.dtype)) + b.astype(a.dtype),
                     x, weight, bias)
    return apply("linear", lambda a, w: jnp.matmul(a, w.astype(a.dtype)), x, weight)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train",
            name=None):
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return apply("dropout_scale", lambda a: a * (1 - p), x)
        return x
    key = default_generator.next_key()

    # key passes as a positional arg (not a closure cell) so partial
    # capture lifts it into a segment input — stochastic segments stay
    # cache-hittable across calls
    def f(a, k):
        if axis is None:
            shape = a.shape
        else:
            axes = (axis,) if isinstance(axis, int) else tuple(axis)
            shape = tuple(a.shape[i] if i in axes else 1 for i in range(a.ndim))
        keep = jax.random.bernoulli(k, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, a / (1.0 - p), jnp.zeros_like(a)).astype(a.dtype)
        return jnp.where(keep, a, jnp.zeros_like(a))

    from ...decomposition.register import DecompAware
    return apply("dropout", DecompAware(
        "dropout", f, p=p, axis=axis, mode=mode), x, key)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ax = (0, 1) if data_format == "NCHW" else (0, 3)
    return dropout(x, p, axis=list(ax), training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ax = (0, 1) if data_format == "NCDHW" else (0, 4)
    return dropout(x, p, axis=list(ax), training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    # key as positional arg, not closure cell — a captured per-call
    # key defeats the partial-capture segment cache (FC203)
    key = default_generator.next_key()

    def f(a, k):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(k, 1.0 - p, a.shape)
        q = 1.0 - p
        coef_a = (q + alpha_p ** 2 * q * p) ** -0.5
        coef_b = -coef_a * alpha_p * p
        return coef_a * jnp.where(keep, a, jnp.full_like(a, alpha_p)) + coef_b

    return apply("alpha_dropout", f, x, key)


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    """Row gather on the MXU-friendly layout [vocab, dim]; padding_idx rows
    receive zero gradient (via stop_gradient on that row)."""
    def f(idx, w):
        if padding_idx is not None:
            pi = padding_idx if padding_idx >= 0 else w.shape[0] + padding_idx
            frozen_row = jax.lax.stop_gradient(w[pi])
            w = w.at[pi].set(frozen_row)
        return jnp.take(w, idx, axis=0)
    return apply("embedding", f, x, weight)


def one_hot(x, num_classes, name=None):
    return apply_nodiff("one_hot",
                        lambda i: jax.nn.one_hot(i, num_classes, dtype=jnp.float32), x)


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW",
                name=None):
    def f(a):
        channel_last = data_format in ("NHWC", "NDHWC", "NLC")
        nd = a.ndim - 2
        if channel_last:
            spatial = a.shape[1:-1]
        else:
            spatial = a.shape[2:]
        if size is not None:
            tgt = tuple(int(s) for s in (size if isinstance(size, (list, tuple)) else [size]))
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) else [scale_factor] * nd
            tgt = tuple(int(round(s * f_)) for s, f_ in zip(spatial, sf))
        if channel_last:
            out_shape = (a.shape[0],) + tgt + (a.shape[-1],)
        else:
            out_shape = a.shape[:2] + tgt
        method = {"nearest": "nearest", "bilinear": "linear",
                  "trilinear": "linear", "linear": "linear",
                  "bicubic": "cubic", "area": "linear"}[mode]
        return jax.image.resize(a, out_shape, method=method).astype(a.dtype)
    return apply("interpolate", f, x)


def upsample(x, size=None, scale_factor=None, mode="nearest",
             align_corners=False, align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode,
                       data_format)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col: [N,C,H,W] -> [N, C*kh*kw, L]."""
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    ph, pw = (paddings, paddings) if isinstance(paddings, int) else paddings[:2]
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations

    def f(a):
        n, c, h, w = a.shape
        a = jnp.pad(a, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
        oh = (h + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        ow = (w + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        patches = []
        for i in range(kh):
            for j in range(kw):
                sl = a[:, :, i * dh:i * dh + oh * sh:sh, j * dw:j * dw + ow * sw:sw]
                patches.append(sl)
        out = jnp.stack(patches, axis=2)  # [N, C, kh*kw, oh, ow]
        return out.reshape(n, c * kh * kw, oh * ow)
    return apply("unfold", f, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    oh, ow = output_sizes if isinstance(output_sizes, (list, tuple)) else (output_sizes,) * 2
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    ph, pw = (paddings, paddings) if isinstance(paddings, int) else paddings[:2]
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations

    def f(a):
        n, ckk, l = a.shape
        c = ckk // (kh * kw)
        nh = (oh + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        nw = (ow + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        a = a.reshape(n, c, kh, kw, nh, nw)
        out = jnp.zeros((n, c, oh + 2 * ph, ow + 2 * pw), a.dtype)
        for i in range(kh):
            for j in range(kw):
                out = out.at[:, :, i * dh:i * dh + nh * sh:sh,
                             j * dw:j * dw + nw * sw:sw].add(a[:, :, i, j])
        return out[:, :, ph:ph + oh, pw:pw + ow]
    return apply("fold", f, x)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c // (r * r), r, r, h, w)
        a = jnp.transpose(a, (0, 1, 4, 2, 5, 3))
        return a.reshape(n, c // (r * r), h * r, w * r)
    return apply("pixel_shuffle", f, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, c, h // r, r, w // r, r)
        a = jnp.transpose(a, (0, 1, 3, 5, 2, 4))
        return a.reshape(n, c * r * r, h // r, w // r)
    return apply("pixel_unshuffle", f, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def f(a):
        n, c, h, w = a.shape
        a = a.reshape(n, groups, c // groups, h, w)
        a = jnp.swapaxes(a, 1, 2)
        return a.reshape(n, c, h, w)
    return apply("channel_shuffle", f, x)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sample `num_samples` class centers: every positive class (present
    in `label`) is kept, negatives fill the rest uniformly at random.
    Returns (remapped_label, sampled_class_center) — remapped_label maps
    each label to its index within the sorted sampled set; labels whose
    class was not sampled map to -1 (only possible when the number of
    unique positives exceeds num_samples).

    Parity: /root/reference/python/paddle/nn/functional/common.py
    class_center_sample (PLSC margin-softmax sampling; the CUDA kernel
    paddle/phi/kernels/gpu/class_center_sample_kernel.cu). TPU-native:
    fixed-shape top-k over a present-mask + random score — one compiled
    program, no host sync."""
    import jax
    from ...framework.core import Tensor, apply, default_generator

    # key as positional arg, not closure cell — a captured per-call key
    # defeats the partial-capture segment cache (FC203)
    key = default_generator.next_key()

    def f(lab, k):
        lab_i = lab.astype(jnp.int32)
        present = jnp.zeros((num_classes,), jnp.float32).at[lab_i].set(1.0)
        noise = jax.random.uniform(k, (num_classes,))
        # positives (>=2) always outrank negatives (<1)
        score = present * 2.0 + noise
        _, picked = jax.lax.top_k(score, num_samples)
        sampled = jnp.sort(picked).astype(lab_i.dtype)
        remap = jnp.full((num_classes,), -1, jnp.int32).at[sampled].set(
            jnp.arange(num_samples, dtype=jnp.int32))
        return remap[lab_i].astype(lab.dtype), sampled.astype(lab.dtype)

    return apply("class_center_sample", f, label, key)
