"""Loss functionals (parity:
/root/reference/python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor, apply

__all__ = [
    "cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
    "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
    "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_similarity",
    "cosine_embedding_loss", "label_smooth", "square_error_cost",
    "log_loss", "hinge_embedding_loss", "triplet_margin_loss",
    "sigmoid_focal_loss", "ctc_loss", "poisson_nll_loss",
    "chunked_softmax_cross_entropy", "chunked_causal_lm_loss",
]


def _reduce(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100,
                  reduction="mean", soft_label=False, axis=-1,
                  use_softmax=True, label_smoothing=0.0, name=None):
    def f(logits, lbl, *w):
        n_classes = logits.shape[axis]
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=axis)
        else:
            logp = jnp.log(jnp.maximum(logits.astype(jnp.float32), 1e-30))
        if soft_label or (lbl.ndim == logits.ndim and lbl.shape == logits.shape):
            soft = lbl.astype(logp.dtype)
            if label_smoothing > 0:
                soft = soft * (1 - label_smoothing) + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=axis)
            valid = jnp.ones_like(loss, dtype=jnp.bool_)
        else:
            idx = lbl
            if idx.ndim == logp.ndim:  # trailing 1 dim
                idx = jnp.squeeze(idx, axis=axis)
            valid = idx != ignore_index
            safe_idx = jnp.where(valid, idx, 0)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe_idx, axis), axis=axis)
            picked = jnp.squeeze(picked, axis=axis)
            if label_smoothing > 0:
                smooth_loss = -jnp.mean(logp, axis=axis)
                loss = -(1 - label_smoothing) * picked + label_smoothing * smooth_loss
            else:
                loss = -picked
            if w:
                cw = jnp.take(w[0].astype(logp.dtype), safe_idx)
                loss = loss * cw
            loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(logp.dtype)), 1.0)
            if w and not soft_label:
                cw = jnp.take(w[0].astype(logp.dtype), jnp.where(valid, lbl if lbl.ndim == loss.ndim else jnp.squeeze(lbl, axis=axis), 0))
                denom = jnp.maximum(jnp.sum(jnp.where(valid, cw, 0.0)), 1e-12)
            return jnp.sum(loss) / denom
        return _reduce(loss, reduction)

    args = [input, label] + ([weight] if weight is not None else [])
    return apply("cross_entropy", f, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100, return_softmax=False,
                               numeric_stable_mode=True):
    loss = cross_entropy(logits, label, soft_label=soft_label, axis=axis,
                         ignore_index=ignore_index, reduction="none")
    from .activation import softmax as _softmax
    if return_softmax:
        return loss, _softmax(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return apply("mse_loss",
                 lambda a, b: _reduce(jnp.square(a - b), reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return apply("l1_loss",
                 lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean",
             name=None):
    def f(logp, lbl, *w):
        valid = lbl != ignore_index
        safe = jnp.where(valid, lbl, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        loss = -jnp.squeeze(picked, 1)
        if w:
            cw = jnp.take(w[0], safe)
            loss = loss * cw
        loss = jnp.where(valid, loss, 0.0)
        if reduction == "mean":
            denom = jnp.sum(jnp.take(w[0], safe) * valid) if w else jnp.sum(valid)
            return jnp.sum(loss) / jnp.maximum(denom, 1e-12)
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply("nll_loss", f, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def f(p, y, *w):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if w:
            loss = loss * w[0]
        return _reduce(loss, reduction)
    args = [input, label] + ([weight] if weight is not None else [])
    return apply("bce", f, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None,
                                     reduction="mean", pos_weight=None,
                                     name=None):
    def f(z, y, *rest):
        logp = jax.nn.log_sigmoid(z)
        lognotp = jax.nn.log_sigmoid(-z)
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        pos_term = y * logp * (pw if pw is not None else 1.0)
        loss = -(pos_term + (1 - y) * lognotp)
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return apply("bce_logits", f, *args)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def f(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return apply("smooth_l1", f, input, label)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def f(logq, p):
        if log_target:
            loss = jnp.exp(p) * (p - logq)
        else:
            loss = p * (jnp.log(jnp.maximum(p, 1e-30)) - logq)
        if reduction == "batchmean":
            return jnp.sum(loss) / logq.shape[0]
        return _reduce(loss, reduction)
    return apply("kl_div", f, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean",
                        name=None):
    def f(a, b, y):
        loss = jnp.maximum(0.0, -y * (a - b) + margin)
        return _reduce(loss, reduction)
    return apply("margin_ranking", f, input, other, label)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def f(a, b):
        dot = jnp.sum(a * b, axis=axis)
        na = jnp.sqrt(jnp.sum(a * a, axis=axis))
        nb = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return dot / jnp.maximum(na * nb, eps)
    return apply("cosine_similarity", f, x1, x2)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean",
                          name=None):
    def f(a, b, y):
        cos = jnp.sum(a * b, axis=-1) / jnp.maximum(
            jnp.linalg.norm(a, axis=-1) * jnp.linalg.norm(b, axis=-1), 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
        return _reduce(loss, reduction)
    return apply("cosine_embedding", f, input1, input2, label)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def f(y, *pd):
        k = y.shape[-1]
        if pd:
            return (1 - epsilon) * y + epsilon * pd[0]
        return (1 - epsilon) * y + epsilon / k
    args = [label] + ([prior_dist] if prior_dist is not None else [])
    return apply("label_smooth", f, *args)


def square_error_cost(input, label):
    return apply("square_error_cost", lambda a, b: jnp.square(a - b), input, label)


def log_loss(input, label, epsilon=1e-4, name=None):
    def f(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return apply("log_loss", f, input, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def f(a, y):
        loss = jnp.where(y == 1, a, jnp.maximum(0.0, margin - a))
        return _reduce(loss, reduction)
    return apply("hinge_embedding", f, input, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0,
                        epsilon=1e-6, swap=False, reduction="mean", name=None):
    def f(a, pos, neg):
        def dist(u, v):
            return jnp.power(jnp.sum(jnp.power(jnp.abs(u - v) + epsilon, p),
                                     axis=-1), 1.0 / p)
        d_pos = dist(a, pos)
        d_neg = dist(a, neg)
        if swap:
            d_neg = jnp.minimum(d_neg, dist(pos, neg))
        loss = jnp.maximum(0.0, d_pos - d_neg + margin)
        return _reduce(loss, reduction)
    return apply("triplet_margin", f, input, positive, negative)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def f(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = -(y * jax.nn.log_sigmoid(z) + (1 - y) * jax.nn.log_sigmoid(-z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * jnp.power(1 - p_t, gamma) * ce
        if n:
            loss = loss / n[0]
        return _reduce(loss, reduction)
    args = [logit, label] + ([normalizer] if normalizer is not None else [])
    return apply("sigmoid_focal", f, *args)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Connectionist temporal classification loss (parity:
    /root/reference/python/paddle/nn/functional/loss.py:1820, warpctc
    kernel). TPU-native: the CTC forward algorithm's alpha recursion over
    the blank-interleaved extended label sequence, as one lax.scan over
    time in log space — fully differentiable, so the gradient is the
    exact autodiff of the forward algorithm (warpctc computes the same
    thing by hand with a beta sweep).

    log_probs: [T, B, C] raw logits (softmax is applied internally, like
    warpctc); labels: [B, L] int; lengths: [B]. norm_by_times scales the
    GRADIENT by 1/T (the loss value is unchanged — warpctc semantics).
    reduction='mean' divides per-sample loss by label length then means.
    """
    def f(logits, lab, t_len, u_len):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        t_max, b, _ = lp.shape
        l_max = lab.shape[1]
        s_max = 2 * l_max + 1
        lab = lab.astype(jnp.int32)
        t_len = t_len.astype(jnp.int32)
        u_len = u_len.astype(jnp.int32)
        # extended label sequence: blank a1 blank a2 ... blank
        ext = jnp.full((b, s_max), blank, jnp.int32)
        ext = ext.at[:, 1::2].set(lab)
        neg_inf = -1e30

        def emit(t):
            # [B, S] log prob of emitting ext symbol at time t
            return jnp.take_along_axis(lp[t], ext, axis=1)

        alpha0 = jnp.full((b, s_max), neg_inf)
        alpha0 = alpha0.at[:, 0].set(emit(0)[:, 0])
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(u_len > 0, emit(0)[:, 1], neg_inf))

        # the s-2 skip is legal only when ext[s] is a label differing
        # from ext[s-2] (can't skip the separating blank between equal
        # labels, nor skip into a blank)
        same_as_prev2 = jnp.concatenate(
            [jnp.ones((b, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

        def step(alpha, t):
            a_shift1 = jnp.concatenate(
                [jnp.full((b, 1), neg_inf), alpha[:, :-1]], axis=1)
            a_shift2 = jnp.concatenate(
                [jnp.full((b, 2), neg_inf), alpha[:, :-2]], axis=1)
            a_shift2 = jnp.where(same_as_prev2, neg_inf, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1),
                                   a_shift2)
            new = merged + emit(t)
            # frozen past each sample's input length
            new = jnp.where((t < t_len)[:, None], new, alpha)
            return new, None

        alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, t_max))
        # total prob: final blank (s=2U) or final label (s=2U-1)
        send = 2 * u_len
        last_blank = jnp.take_along_axis(alpha, send[:, None],
                                         axis=1)[:, 0]
        last_lab = jnp.take_along_axis(
            alpha, jnp.maximum(send - 1, 0)[:, None], axis=1)[:, 0]
        last_lab = jnp.where(u_len > 0, last_lab, neg_inf)
        nll = -jnp.logaddexp(last_blank, last_lab)
        if norm_by_times:
            # warpctc scales only the GRADIENT by 1/T; keep the value
            # and route autodiff through the scaled branch
            scaled = nll / jnp.maximum(t_len, 1).astype(nll.dtype)
            nll = scaled + jax.lax.stop_gradient(nll - scaled)
        return nll.astype(logits.dtype)

    loss = apply("ctc_loss", f, log_probs, labels, input_lengths,
                 label_lengths)
    if reduction == "mean":
        # reference (loss.py:1962): mean of per-sample loss normalized
        # by label length
        norm = apply("ctc_norm",
                     lambda l, ll: l / jnp.maximum(ll.astype(l.dtype),
                                                   1.0),
                     loss, label_lengths)
        return norm.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


def chunked_softmax_cross_entropy(hidden, labels, weight,
                                  chunk_tokens: int,
                                  transpose_weight: bool = False,
                                  ignore_index: int = -100):
    """Head-matmul + shifted-CE computed in token chunks under
    jax.checkpoint — the [N, V] logits are never materialized; the
    backward rematerializes one chunk at a time. Serves every CausalLM
    in the zoo (the memory pressure is identical across them).

    hidden [B, S, D]; labels [B, S] (shift applied here, like the dense
    loss paths); weight [D, V] (or [V, D] with transpose_weight=True,
    the tied-embedding layout). ignore_index positions are masked from
    numerator AND denominator — exact parity with
    cross_entropy(ignore_index=...)."""
    def f(h, y, wv):
        b, s, d = h.shape
        hs = h[:, :-1].reshape(b * (s - 1), d)
        ys = y[:, 1:].reshape(-1)
        n = hs.shape[0]
        nc = -(-n // chunk_tokens)
        pad = nc * chunk_tokens - n
        hs = jnp.pad(hs, ((0, pad), (0, 0)))
        ys = jnp.pad(ys, (0, pad), constant_values=ignore_index)
        valid = (ys != ignore_index)
        mask = valid.astype(jnp.float32)
        ys_safe = jnp.where(valid, ys, 0)
        hs = hs.reshape(nc, chunk_tokens, d)
        ys_safe = ys_safe.reshape(nc, chunk_tokens)
        mask = mask.reshape(nc, chunk_tokens)

        @jax.checkpoint
        def body(carry, xs):
            hc, yc, mc = xs
            wm = wv.T if transpose_weight else wv
            logits = (hc @ wm.astype(hc.dtype)).astype(jnp.float32)
            lse = jax.nn.logsumexp(logits, axis=-1)
            tgt = jnp.take_along_axis(
                logits, yc[:, None].astype(jnp.int32), axis=1)[:, 0]
            return carry + jnp.sum((lse - tgt) * mc), None

        total, _ = jax.lax.scan(body, jnp.float32(0),
                                (hs, ys_safe, mask))
        return total / jnp.maximum(mask.sum(), 1.0)

    return apply("chunked_ce", f, hidden, labels, weight)


def chunked_causal_lm_loss(hidden, labels, lm_head_weight,
                           embedding_weight, chunk_tokens: int,
                           ignore_index: int = -100):
    """The CausalLM adoption seam for chunked CE: pass the lm_head
    weight (or None when embeddings are tied) and the embedding weight;
    the tied case transposes. One call site per model — the weight-
    selection logic lives here, not copied into every zoo model."""
    if lm_head_weight is not None:
        return chunked_softmax_cross_entropy(
            hidden, labels, lm_head_weight, chunk_tokens,
            ignore_index=ignore_index)
    return chunked_softmax_cross_entropy(
        hidden, labels, embedding_weight, chunk_tokens,
        transpose_weight=True, ignore_index=ignore_index)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def f(a, y):
        if log_input:
            loss = jnp.exp(a) - y * a
        else:
            loss = a - y * jnp.log(a + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return apply("poisson_nll", f, input, label)
