"""Normalization functionals (parity:
/root/reference/python/paddle/nn/functional/norm.py). rms_norm mirrors the
reference's fused kernel API (incubate fused_rms_norm) — on TPU it lowers to
a Pallas kernel when profitable (see paddle_tpu.ops)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...decomposition.register import DecompAware
from ...framework.core import Tensor, apply

__all__ = ["batch_norm", "layer_norm", "group_norm", "instance_norm",
           "rms_norm", "normalize", "local_response_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5,
               data_format="NCHW", use_global_stats=None, name=None):
    """Mutates running_mean/running_var Tensors when training (paddle
    in-place semantics; under a jit trace the new values are read back by
    functional_call)."""
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        def f_stats(a):
            mean = jnp.mean(a, axis=reduce_axes)
            var = jnp.var(a, axis=reduce_axes)
            return mean, var
        mean_t, var_t = apply("bn_stats", DecompAware(
            "bn_stats", f_stats, axes=reduce_axes), x)
        # update running stats in place (on the raw arrays, no tape)
        m = momentum
        running_mean._replace(
            (m * running_mean._value + (1 - m) * mean_t._value).astype(running_mean._value.dtype))
        running_var._replace(
            (m * running_var._value + (1 - m) * var_t._value).astype(running_var._value.dtype))
        mean_u, var_u = mean_t, var_t
    else:
        mean_u, var_u = running_mean, running_var

    shape = [1] * x.ndim
    shape[ch_axis] = -1
    has_w, has_b = weight is not None, bias is not None

    def f(a, mean, var, *wb):
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + epsilon).astype(a.dtype)
        out = (a - mean.reshape(shape).astype(a.dtype)) * inv.reshape(shape)
        it = iter(wb)
        if has_w:
            out = out * next(it).reshape(shape).astype(a.dtype)
        if has_b:
            out = out + next(it).reshape(shape).astype(a.dtype)
        return out

    args = [x, mean_u, var_u]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply("batch_norm", DecompAware(
        "batch_norm", f, ch_axis=ch_axis, epsilon=epsilon,
        has_w=has_w, has_b=has_b), *args)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5,
               name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n = len(tuple(normalized_shape))
    axes = tuple(range(-n, 0))

    def f(a, *wb):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if len(wb) >= 1:
            out = out * wb[0].astype(a.dtype)
        if len(wb) == 2:
            out = out + wb[1].astype(a.dtype)
        return out

    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    elif bias is not None:
        # bias without weight: add after normalize
        out = layer_norm(x, normalized_shape, None, None, epsilon)
        from ...tensor.math import add
        return add(out, bias)
    return apply("layer_norm", DecompAware(
        "layer_norm", f, axes=axes, epsilon=epsilon), *args)


def rms_norm(x, weight=None, epsilon=1e-6, axis=-1, name=None):
    """RMSNorm (reference: fused_rms_norm,
    /root/reference/python/paddle/incubate/nn/functional/fused_rms_norm.py)."""
    from ...ops.rms_norm import rms_norm as _rms
    args = [x] if weight is None else [x, weight]
    def f(a, *w):
        return _rms(a, w[0] if w else None, epsilon, axis)
    return apply("rms_norm", DecompAware(
        "rms_norm", f, epsilon=epsilon, axis=axis), *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    has_w, has_b = weight is not None, bias is not None

    def f(a, *wb):
        if ch_axis != 1:
            a_ = jnp.moveaxis(a, ch_axis, 1)
        else:
            a_ = a
        n, c = a_.shape[0], a_.shape[1]
        g = num_groups
        grouped = a_.reshape((n, g, c // g) + a_.shape[2:])
        axes = tuple(range(2, grouped.ndim))
        mean = jnp.mean(grouped.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(grouped.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((grouped.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon))
        out = out.reshape(a_.shape).astype(a.dtype)
        shape = [1] * a_.ndim
        shape[1] = -1
        it = iter(wb)
        if has_w:
            out = out * next(it).reshape(shape).astype(a.dtype)
        if has_b:
            out = out + next(it).reshape(shape).astype(a.dtype)
        if ch_axis != 1:
            out = jnp.moveaxis(out, 1, ch_axis)
        return out

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply("group_norm", f, *args)


def instance_norm(x, running_mean=None, running_var=None, weight=None,
                  bias=None, use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else \
        tuple(i for i in range(1, x.ndim - 1))
    has_w, has_b = weight is not None, bias is not None

    def f(a, *wb):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + eps)).astype(a.dtype)
        shape = [1] * a.ndim
        shape[ch_axis] = -1
        it = iter(wb)
        if has_w:
            out = out * next(it).reshape(shape).astype(a.dtype)
        if has_b:
            out = out + next(it).reshape(shape).astype(a.dtype)
        return out

    args = [x]
    if has_w:
        args.append(weight)
    if has_b:
        args.append(bias)
    return apply("instance_norm", DecompAware(
        "instance_norm", f, axes=axes, ch_axis=ch_axis, eps=eps,
        has_w=has_w, has_b=has_b), *args)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def f(a):
        if p == 2:
            nrm = jnp.sqrt(jnp.sum(a * a, axis=axis, keepdims=True))
        else:
            nrm = jnp.power(jnp.sum(jnp.power(jnp.abs(a), p), axis=axis,
                                    keepdims=True), 1.0 / p)
        return a / jnp.maximum(nrm, epsilon)
    return apply("normalize", f, x)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def f(a):
        ch_axis = 1 if data_format.startswith("NC") else a.ndim - 1
        sq = jnp.square(a)
        moved = jnp.moveaxis(sq, ch_axis, -1)
        pad = [(0, 0)] * (moved.ndim - 1) + [(size // 2, (size - 1) // 2)]
        padded = jnp.pad(moved, pad)
        windows = jnp.stack([padded[..., i:i + moved.shape[-1]]
                             for i in range(size)], axis=0)
        s = jnp.sum(windows, axis=0)
        s = jnp.moveaxis(s, -1, ch_axis)
        return a / jnp.power(k + alpha * s, beta)
    return apply("local_response_norm", f, x)
