"""Remaining nn.functional parity (reference
python/paddle/nn/functional/): unpooling, extra losses, grid sampling,
sequence utilities, in-place activation aliases."""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ...framework.core import Tensor, apply, apply_nodiff
from . import activation as A

__all__ = [
    "max_unpool1d", "max_unpool2d", "max_unpool3d",
    "fractional_max_pool2d", "fractional_max_pool3d",
    "gaussian_nll_loss", "soft_margin_loss",
    "multi_label_soft_margin_loss", "multi_margin_loss",
    "triplet_margin_with_distance_loss", "pairwise_distance",
    "hsigmoid_loss", "zeropad2d", "sequence_mask", "dice_loss",
    "npair_loss", "temporal_shift", "bilinear", "affine_grid",
    "grid_sample", "gather_tree", "margin_cross_entropy", "rnnt_loss",
    "sparse_attention",
    "elu_", "hardtanh_", "leaky_relu_", "softmax_", "tanh_",
    "thresholded_relu_",
]


# -- unpooling (layer impls already exist; functional forms) ----------------

def _unpool(nd):
    def fn(x, indices, kernel_size, stride=None, padding=0,
           data_format=None, output_size=None, name=None):
        from ..layer.extras import MaxUnPool1D, MaxUnPool2D, MaxUnPool3D
        cls = {1: MaxUnPool1D, 2: MaxUnPool2D, 3: MaxUnPool3D}[nd]
        return cls(kernel_size, stride, padding,
                   output_size=output_size)(x, indices)
    fn.__name__ = f"max_unpool{nd}d"
    return fn


max_unpool1d = _unpool(1)
max_unpool2d = _unpool(2)
max_unpool3d = _unpool(3)


def _fractional_pool(nd):
    def fn(x, output_size, kernel_size=None, random_u=None,
           return_mask=False, name=None):
        """Fractional max pool (reference fractional_max_pool2d/3d):
        pseudo-random pooling regions hitting an exact output size. The
        deterministic variant uses the u-sequence formula with a fixed
        (or provided) u."""
        out_sz = output_size if isinstance(output_size, (tuple, list)) \
            else (output_size,) * nd
        if random_u is None:
            # the stochastic regions ARE the op's regularization value:
            # draw a fresh u per call like the reference
            from ...framework.core import default_generator
            key = default_generator.next_key()
            u = float(jax.device_get(
                jax.random.uniform(key, (), jnp.float32)))
        else:
            u = float(random_u)

        def f(a):
            spatial = a.shape[-nd:]
            idxs = []
            for i, (n_in, n_out) in enumerate(zip(spatial, out_sz)):
                alpha = n_in / n_out
                ks = [int(math.ceil(alpha * (k + u))) -
                      int(math.ceil(alpha * u)) for k in range(n_out + 1)]
                edges = np.minimum(ks, n_in)
                idxs.append(edges)
            out = a
            # pool each spatial dim by segment max
            for d in range(nd):
                ax = a.ndim - nd + d
                edges = idxs[d]
                segs = []
                for k in range(out_sz[d]):
                    lo, hi = edges[k], max(edges[k + 1], edges[k] + 1)
                    seg = jax.lax.slice_in_dim(out, lo, hi, axis=ax)
                    segs.append(seg.max(axis=ax, keepdims=True))
                out = jnp.concatenate(segs, axis=ax)
            return out

        if return_mask:
            raise NotImplementedError(
                f"fractional_max_pool{nd}d(return_mask=True): indices "
                f"for fractional regions are not implemented; use "
                f"max_pool{nd}d for unpooling workflows")
        return apply(f"fractional_max_pool{nd}d", f, x)
    fn.__name__ = f"fractional_max_pool{nd}d"
    return fn


fractional_max_pool2d = _fractional_pool(2)
fractional_max_pool3d = _fractional_pool(3)


# -- losses (functional forms of the new layers) ----------------------------

def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    from ..layer.extras import GaussianNLLLoss
    return GaussianNLLLoss(full, epsilon, reduction)(input, label,
                                                     variance)


def soft_margin_loss(input, label, reduction="mean", name=None):
    from ..layer.extras import SoftMarginLoss
    return SoftMarginLoss(reduction)(input, label)


def multi_label_soft_margin_loss(input, label, weight=None,
                                 reduction="mean", name=None):
    from ..layer.extras import MultiLabelSoftMarginLoss
    return MultiLabelSoftMarginLoss(weight, reduction)(input, label)


def multi_margin_loss(input, label, p=1, margin=1.0, weight=None,
                      reduction="mean", name=None):
    from ..layer.extras import MultiMarginLoss
    return MultiMarginLoss(p, margin, weight, reduction)(input, label)


def triplet_margin_with_distance_loss(input, positive, negative,
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    from ..layer.extras import TripletMarginWithDistanceLoss
    return TripletMarginWithDistanceLoss(
        distance_function, margin, swap, reduction)(input, positive,
                                                    negative)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    from ..layer.extras import PairwiseDistance
    return PairwiseDistance(p, epsilon, keepdim)(x, y)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Functional hierarchical sigmoid using caller-provided weights
    (reference F.hsigmoid_loss)."""
    from ..layer.extras import HSigmoidLoss, _hsigmoid_tree_tables
    layer = HSigmoidLoss.__new__(HSigmoidLoss)
    from ..layer.layers import Layer
    Layer.__init__(layer)
    layer.num_classes = num_classes
    layer.weight = weight
    layer.bias = bias if bias is not None else \
        Tensor(jnp.zeros((num_classes - 1,), jnp.float32))
    layer._table, layer._code, layer._valid = \
        _hsigmoid_tree_tables(num_classes)
    return layer(input, label)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """Dice loss over softmaxed predictions (reference dice_loss:
    input [N, ..., C] probabilities, label [N, ..., 1] ints)."""
    def f(x, y):
        num_classes = x.shape[-1]
        y1 = jax.nn.one_hot(y[..., 0], num_classes, dtype=x.dtype)
        red = tuple(range(1, x.ndim))
        inter = (x * y1).sum(red)
        union = x.sum(red) + y1.sum(red)
        return (1 - (2 * inter + epsilon) / (union + epsilon)).mean()
    return apply("dice_loss", f, input, label)


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    """N-pair loss (reference npair_loss)."""
    def f(a, p, y):
        logits = a @ p.T
        eq = (y[:, None] == y[None, :]).astype(a.dtype)
        tgt = eq / eq.sum(axis=1, keepdims=True)
        logp = jax.nn.log_softmax(logits, axis=1)
        ce = -(tgt * logp).sum(1).mean()
        reg = l2_reg * ((a * a).sum(1) + (p * p).sum(1)).mean() * 0.25
        return ce + reg
    return apply("npair_loss", f, anchor, positive, labels)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean"):
    """ArcFace-style margin softmax (reference margin_cross_entropy:
    cos(m1*θ + m2) - m3 on the target logit)."""
    def f(lg, y):
        n, c = lg.shape
        yi = y.astype(jnp.int32)
        # arccos only on the GATHERED target logit, clipped strictly
        # inside (-1, 1): arccos' derivative is infinite at ±1, and
        # normalized-embedding logits routinely hit exactly 1.0 — the
        # inf would leak through where() as NaN for the whole row
        eps = 1e-6
        tgt_cos = jnp.take_along_axis(lg, yi[:, None], axis=1)[:, 0]
        theta = jnp.arccos(jnp.clip(tgt_cos, -1.0 + eps, 1.0 - eps))
        tgt = jnp.cos(margin1 * theta + margin2) - margin3
        onehot = jax.nn.one_hot(yi, c, dtype=lg.dtype)
        out = (lg * (1 - onehot) + tgt[:, None] * onehot) * scale
        logp = jax.nn.log_softmax(out, axis=1)
        nll = -jnp.take_along_axis(logp, yi[:, None], axis=1)[:, 0]
        return nll, jnp.exp(logp)
    loss, sm = apply("margin_cross_entropy", f, logits, label)
    if reduction == "mean":
        loss = loss.mean()
    elif reduction == "sum":
        loss = loss.sum()
    if return_softmax:
        return loss, sm
    return loss


def rnnt_loss(input, label, input_lengths, label_lengths, blank=0,
              fastemit_lambda=0.0, reduction="mean", name=None):
    """RNN-T transducer loss (reference rnnt_loss over warprnnt): the
    log-space alpha recursion over (t, u) as a lax.scan over t with a
    cumulative-logsumexp sweep over u inside each step. FastEmit
    regularization is not implemented — nonzero fastemit_lambda raises
    rather than silently computing a different loss."""
    if fastemit_lambda:
        raise NotImplementedError(
            "rnnt_loss fastemit_lambda: FastEmit regularization is not "
            "implemented; pass fastemit_lambda=0")

    def f(logits, lab, t_len, u_len):
        # logits: [B, T, U+1, C]; lab: [B, U]
        b, t_max, u1, c = logits.shape
        lp = jax.nn.log_softmax(logits, axis=-1)
        blank_lp = lp[..., blank]                     # [B, T, U+1]
        lab_lp = jnp.take_along_axis(
            lp[:, :, :-1, :],
            lab[:, None, :, None].astype(jnp.int32), axis=3)[..., 0]
        # pad so emit at u reads lab_lp[:, t, u]     # [B, T, U]

        def step(alpha, t):
            # alpha: [B, U+1] at time t-1 → time t.
            # blank move first: stay[u] = alpha[u] + blank(t-1, u); then
            # the emit recursion along u:
            #   alpha_t[u] = logaddexp(stay[u], alpha_t[u-1] + emit(t, u-1))
            stay = alpha + blank_lp[:, t - 1, :]
            emits = lab_lp[:, t, :]                  # [B, U]

            def u_step(prev, inp):
                stay_u, emit_u = inp
                cur = jnp.logaddexp(stay_u, prev + emit_u)
                return cur, cur

            first = stay[:, 0]
            _, rest = jax.lax.scan(
                u_step, first,
                (stay[:, 1:].T, emits.T))
            new = jnp.concatenate([first[:, None], rest.T], axis=1)
            return jnp.where((t < t_len)[:, None], new, alpha), None

        # t=0 row: alpha[0,0]=0; alpha[0,u] = sum emits
        emits0 = lab_lp[:, 0, :]
        a0 = jnp.concatenate(
            [jnp.zeros((b, 1)), jnp.cumsum(emits0, axis=1)], axis=1)
        alpha, _ = jax.lax.scan(step, a0, jnp.arange(1, t_max))
        # total: alpha[t_len-1, u_len] + blank at (t_len-1, u_len)
        ti = jnp.maximum(t_len - 1, 0)
        final = jnp.take_along_axis(alpha, u_len[:, None], axis=1)[:, 0]
        final_blank = blank_lp[jnp.arange(b), ti, u_len]
        return -(final + final_blank)

    loss = apply("rnnt_loss", f, input, label, input_lengths,
                 label_lengths)
    if reduction == "mean":
        return loss.mean()
    if reduction == "sum":
        return loss.sum()
    return loss


# -- spatial / sequence utilities ------------------------------------------

def zeropad2d(x, padding, data_format="NCHW", name=None):
    from ..layer.extras import ZeroPad2D
    return ZeroPad2D(padding, data_format)(x)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    """[..., maxlen] mask of positions < length (reference
    sequence_mask)."""
    from ...framework import dtype as dtypes
    d = dtypes.convert_dtype(dtype)

    def f(lens):
        m = maxlen or int(jax.device_get(lens).max())
        return (jnp.arange(m)[None, :] <
                lens[..., None]).astype(d)
    return apply_nodiff("sequence_mask", f, x)


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW",
                   name=None):
    """TSM temporal shift (reference temporal_shift): shift a fraction
    of channels one step along time within each segment."""
    def f(a):
        if data_format == "NHWC":
            a = jnp.moveaxis(a, -1, 1)
        nt, c, h, w = a.shape
        n = nt // seg_num
        v = a.reshape(n, seg_num, c, h, w)
        c1 = int(c * shift_ratio)
        c2 = int(c * 2 * shift_ratio)
        fwd = jnp.concatenate(
            [v[:, 1:, :c1], jnp.zeros_like(v[:, :1, :c1])], axis=1)
        bwd = jnp.concatenate(
            [jnp.zeros_like(v[:, :1, c1:c2]), v[:, :-1, c1:c2]], axis=1)
        keep = v[:, :, c2:]
        out = jnp.concatenate([fwd, bwd, keep], axis=2)
        out = out.reshape(nt, c, h, w)
        if data_format == "NHWC":
            out = jnp.moveaxis(out, 1, -1)
        return out
    return apply("temporal_shift", f, x)


def bilinear(x1, x2, weight, bias=None, name=None):
    """Bilinear transform out[n, k] = x1ᵀ W_k x2 (reference bilinear)."""
    def f(a, b, w, *rest):
        out = jnp.einsum("bi,kij,bj->bk", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    args = (x1, x2, weight) + ((bias,) if bias is not None else ())
    return apply("bilinear", f, *args)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2D affine sampling grid (reference affine_grid): theta [N, 2, 3]
    → grid [N, H, W, 2]."""
    n, c, h, w = out_shape

    def f(th):
        if align_corners:
            ys = jnp.linspace(-1, 1, h)
            xs = jnp.linspace(-1, 1, w)
        else:
            ys = (jnp.arange(h) * 2 + 1) / h - 1
            xs = (jnp.arange(w) * 2 + 1) / w - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1)     # [H, W, 3]
        return jnp.einsum("nij,hwj->nhwi", th, base)  # [N, H, W, 2]
    return apply("affine_grid", f, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample x [N,C,H,W] at grid [N,Ho,Wo,2] of xy coords in [-1,1]
    (reference grid_sample). Differentiable bilinear gather."""
    def f(a, g):
        n, c, h, w = a.shape
        gx = g[..., 0]
        gy = g[..., 1]
        if align_corners:
            fx = (gx + 1) * (w - 1) / 2
            fy = (gy + 1) * (h - 1) / 2
        else:
            fx = ((gx + 1) * w - 1) / 2
            fy = ((gy + 1) * h - 1) / 2

        def reflect(i, size):
            # reflect across edges onto [0, size-1] (align_corners form)
            span = max(2 * (size - 1), 1)
            i = jnp.abs(i)
            i = i % span
            return jnp.where(i > size - 1, span - i, i)

        def sample(ix, iy):
            inb = ((ix >= 0) & (ix < w) & (iy >= 0) & (iy < h))
            if padding_mode == "reflection":
                ix = reflect(ix, w)
                iy = reflect(iy, h)
            ixc = jnp.clip(ix, 0, w - 1).astype(jnp.int32)
            iyc = jnp.clip(iy, 0, h - 1).astype(jnp.int32)
            vals = a[jnp.arange(n)[:, None, None], :, iyc, ixc]
            # vals: [N, Ho, Wo, C]
            if padding_mode == "zeros":
                vals = vals * inb[..., None]
            return vals

        if mode == "nearest":
            out = sample(jnp.round(fx), jnp.round(fy))
        else:
            x0 = jnp.floor(fx)
            y0 = jnp.floor(fy)
            wx = fx - x0
            wy = fy - y0
            out = (sample(x0, y0) * ((1 - wx) * (1 - wy))[..., None]
                   + sample(x0 + 1, y0) * (wx * (1 - wy))[..., None]
                   + sample(x0, y0 + 1) * ((1 - wx) * wy)[..., None]
                   + sample(x0 + 1, y0 + 1) * (wx * wy)[..., None])
        return jnp.moveaxis(out, -1, 1)  # [N, C, Ho, Wo]
    return apply("grid_sample", f, x, grid)


def gather_tree(ids, parents):
    """Beam-search backtrace (reference gather_tree): ids/parents
    [T, B, beam] → full sequences."""
    def f(idw, par):
        t_max = idw.shape[0]

        def step(carry, t):
            beams = carry  # [B, beam] current beam index per slot
            tok = jnp.take_along_axis(idw[t], beams, axis=1)
            prev = jnp.take_along_axis(par[t], beams, axis=1)
            return prev, tok

        init = jnp.broadcast_to(jnp.arange(idw.shape[2])[None, :],
                                idw.shape[1:])
        _, toks = jax.lax.scan(step, init, jnp.arange(t_max - 1, -1, -1))
        return toks[::-1]
    return apply_nodiff("gather_tree", f, ids, parents)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention (reference binds a CUDA kernel). On TPU a
    mask-materialized flash path is both simpler and faster for the
    sizes this API targets; the CSR pattern (offsets/columns shaped
    [B, H, ...] like the reference) becomes an additive mask, combined
    with the optional key-padding and attention masks."""
    def f(q, k, v, off, cols, *extra):
        b, h, s, d = q.shape
        # CSR → dense mask; per-(batch, head) patterns, host loop is
        # static per pattern
        offs = np.asarray(jax.device_get(off)).reshape(b * h, s + 1)
        colz = np.asarray(jax.device_get(cols)).reshape(b * h, -1)
        allow = np.zeros((b * h, s, s), bool)
        for bi in range(b * h):
            for r in range(s):
                cs = colz[bi, offs[bi, r]:offs[bi, r + 1]]
                allow[bi, r, cs] = True
        amask = jnp.asarray(allow).reshape(b, h, s, s)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / math.sqrt(d)
        scores = jnp.where(amask, scores, -1e30)
        it = iter(extra)
        if key_padding_mask is not None:
            kpm = next(it)  # [B, S]: 1 = valid key
            scores = jnp.where(
                kpm[:, None, None, :] > 0, scores, -1e30)
        if attn_mask is not None:
            scores = scores + next(it)
        p = jax.nn.softmax(scores, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    extra = tuple(m for m in (key_padding_mask, attn_mask)
                  if m is not None)
    return apply("sparse_attention", f, query, key, value,
                 sparse_csr_offset, sparse_csr_columns, *extra)


# -- in-place activation aliases -------------------------------------------

def _inplace(fn_name):
    base = getattr(A, fn_name)

    def fn(x, *args, **kwargs):
        # record the op against a SNAPSHOT of x, then overwrite x: if the
        # new node's input were x itself, x._node would point at a node
        # listing x as input (a self-cycle) and backward would silently
        # drop all upstream gradients.
        snap = Tensor(x._value, stop_gradient=x.stop_gradient)
        snap._node = x._node
        snap._out_idx = x._out_idx
        out = base(snap, *args, **kwargs)
        x._value = out._value
        x._node = out._node
        x._out_idx = out._out_idx
        x.stop_gradient = out.stop_gradient
        return x
    fn.__name__ = fn_name + "_"
    return fn


elu_ = _inplace("elu")
hardtanh_ = _inplace("hardtanh")
leaky_relu_ = _inplace("leaky_relu")
softmax_ = _inplace("softmax")
tanh_ = _inplace("tanh")
thresholded_relu_ = _inplace("thresholded_relu")
